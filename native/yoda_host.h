/* C API of the native host runtime (libyoda_host.so).
 *
 * The reference's host runtime is a compiled Go binary embedding the
 * upstream kube-scheduler (SURVEY.md L1/L2 + the implicit upstream layer);
 * here the host-side hot paths — the scheduling queue, the scalar
 * fallback scoring cycle, and snapshot aggregation — are native C++,
 * bound into Python with ctypes (kubernetes_scheduler_tpu/native/).
 *
 * All tensor arguments are dense row-major float32/int32 buffers, the
 * same layout the bridge ships to the device.
 */
#ifndef YODA_HOST_H
#define YODA_HOST_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- scheduling queue ------------------------------------------------
 * Priority ordering (higher first, FIFO among equals — the sort.go:8-18
 * comparator) + exponential retry backoff between initial and max
 * seconds (deploy/yoda-scheduler.yaml:19-20). Pods are opaque uint64
 * handles owned by the caller. The caller supplies `now` so tests can
 * drive a fake clock.
 */
typedef struct YodaQueue YodaQueue;

YodaQueue* yoda_queue_new(double initial_backoff, double max_backoff);
void yoda_queue_free(YodaQueue* q);
void yoda_queue_push(YodaQueue* q, uint64_t pod, int32_t priority);
/* Failed cycle: requeue with exponential backoff. */
void yoda_queue_requeue_unschedulable(YodaQueue* q, uint64_t pod,
                                      int32_t priority, double now);
/* Successful bind: clear the retry counter. */
void yoda_queue_mark_scheduled(YodaQueue* q, uint64_t pod);
/* Batch form: one foreign call for a whole cycle's binds. */
void yoda_queue_mark_scheduled_batch(YodaQueue* q, const uint64_t* pods,
                                     int64_t n);
/* Drain due backoff entries, then pop up to max_n pods in priority order.
 * Returns the number written to out. */
int64_t yoda_queue_pop_window(YodaQueue* q, double now, uint64_t* out,
                              int64_t max_n);
int64_t yoda_queue_len(const YodaQueue* q);

/* ---- scalar fallback cycle -------------------------------------------
 * The TPUBatchScore=false path: per pod, sequentially — utilization
 * statistics, BalancedCpuDiskIO score (algorithm.go:99-119, with the
 * uint64 truncation at :113 when truncate != 0), min-max normalization
 * (scheduler.go:158-183), resource-fit filtering against free capacity,
 * deterministic argmax (first max in node order), capacity decrement.
 *
 * pod_req  [P,R]  pod resource requests (priority order = row order)
 * r_io     [P]    diskIO annotation MB/s (0 = absent -> beta = 0)
 * free_cap [N,R]  in: free capacity; out: capacity after bindings
 * disk_io  [N]    node disk-IO MB/s   (advisor series)
 * cpu_pct  [N]    node CPU percent    (advisor series)
 * out_idx  [P]    assigned node index, -1 = unschedulable
 * Returns the number of pods bound.
 */
int64_t yoda_scalar_cycle(int64_t P, int64_t N, int64_t R,
                          const float* pod_req, const float* r_io,
                          float* free_cap, const float* disk_io,
                          const float* cpu_pct, int truncate,
                          int32_t* out_idx);

/* Buffer-reusing variant: free_in is const, post-bind capacities land in
 * free_out (free_out == free_in degenerates to the in-place cycle). Lets
 * a caller with stable buffers prebind every pointer once and pay only
 * the foreign-call cost per cycle. */
int64_t yoda_scalar_cycle_buf(int64_t P, int64_t N, int64_t R,
                              const float* pod_req, const float* r_io,
                              const float* free_in, float* free_out,
                              const float* disk_io, const float* cpu_pct,
                              int truncate, int32_t* out_idx);

/* ---- native tiny-cycle loop ------------------------------------------
 * One foreign call runs up to n_cycles full host cycles: pop a window of
 * pod handles (indices into the [M,R] pod arrays) from q, score it with
 * yoda_scalar_cycle's exact decisions, bind (capacity decrement +
 * mark-scheduled) or requeue unschedulable with backoff. Stops early
 * when the queue drains. The clock starts at `now` and advances
 * dt_per_cycle per cycle (deterministic backoff). out_idx [M] must be
 * caller-initialized (-1); binds of retried pods overwrite their slot.
 * Returns total binds (-1 on a handle out of range); *out_cycles reports
 * cycles actually run.
 */
int64_t yoda_native_loop(YodaQueue* q, int64_t n_cycles, int64_t window,
                         int64_t M, int64_t N, int64_t R,
                         const float* pod_req, const float* r_io,
                         const int32_t* prio, float* free_cap,
                         const float* disk_io, const float* cpu_pct,
                         int truncate, int reset_free, double now,
                         double dt_per_cycle, int32_t* out_idx,
                         int64_t* out_cycles);

/* ---- snapshot aggregation --------------------------------------------
 * Sum running-pod requests into the per-node requested matrix
 * (the host-side analog of CalculateResourceAllocatableRequest's
 * nonZeroRequested accumulation, algorithm.go:209-233).
 * pod_node [M] node index per running pod (entries outside [0,N) skipped)
 * pod_req  [M,R]; requested [N,R] accumulated in place.
 */
void yoda_aggregate_requested(int64_t M, int64_t N, int64_t R,
                              const int32_t* pod_node, const float* pod_req,
                              float* requested);

/* Library ABI version; bump on any signature change. */
int32_t yoda_host_abi_version(void);

#ifdef __cplusplus
}
#endif

#endif /* YODA_HOST_H */
