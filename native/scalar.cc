// Scalar fallback scoring cycle.
//
// Native implementation of the TPUBatchScore=false path: the same
// decisions as host/plugins.py's ScalarYodaPlugin + scalar_schedule_one
// (which reproduce the reference's per-pod hook sequence, SURVEY.md
// §3.2), computed in double precision like the Go float64 original:
//   u_i = diskIO_i / 50,  v_i = cpu_i / 100        (algorithm.go:70-75)
//   beta = 1/(1 + Rcpu/Rio), alpha = 1 - beta       (algorithm.go:105-106)
//   S_i = 10 - 10*|alpha*v_i - beta*u_i|            (algorithm.go:110-111)
//   optional uint64 truncation                      (algorithm.go:113)
//   min-max normalize to [0,100], guard hi==lo      (scheduler.go:161-180)
// plus the resource-fit filter and capacity decrement upstream provides
// around the plugin. Statistics (u_avg, variance) are intentionally not
// computed: the reference stores them in Redis but the live formula never
// reads them (SURVEY.md §2 "score (live path)").

#include "yoda_host.h"

#include <cmath>
#include <cstdint>
#include <vector>

extern "C" int64_t yoda_scalar_cycle(int64_t P, int64_t N, int64_t R,
                                     const float* pod_req, const float* r_io,
                                     float* free_cap, const float* disk_io,
                                     const float* cpu_pct, int truncate,
                                     int32_t* out_idx) {
  std::vector<double> u(N), v(N);
  for (int64_t j = 0; j < N; ++j) {
    u[j] = disk_io[j] / 50.0;
    v[j] = cpu_pct[j] / 100.0;
  }
  std::vector<double> score(N);
  std::vector<char> feasible(N);

  int64_t bound = 0;
  for (int64_t i = 0; i < P; ++i) {
    const float* req = pod_req + i * R;

    // filter: resource fit against current free capacity
    bool any = false;
    for (int64_t j = 0; j < N; ++j) {
      bool ok = true;
      const float* freej = free_cap + j * R;
      for (int64_t r = 0; r < R; ++r) {
        if (req[r] > 0.0f && req[r] > freej[r]) {
          ok = false;
          break;
        }
      }
      feasible[j] = ok;
      any |= ok;
    }
    if (!any) {
      out_idx[i] = -1;
      continue;
    }

    // score
    const double rio = static_cast<double>(r_io[i]);
    const double rcpu = static_cast<double>(req[0]);
    const double beta = rio > 0.0 ? 1.0 / (1.0 + rcpu / rio) : 0.0;
    const double alpha = 1.0 - beta;
    double hi = 0.0;  // reference clamps highest at >= 0 (scheduler.go:165)
    double lo = std::numeric_limits<double>::infinity();
    for (int64_t j = 0; j < N; ++j) {
      if (!feasible[j]) continue;
      double s = 10.0 - 10.0 * std::fabs(alpha * v[j] - beta * u[j]);
      if (truncate) s = s >= 0.0 ? std::trunc(s) : 0.0;
      score[j] = s;
      if (s > hi) hi = s;
      if (s < lo) lo = s;
    }
    if (hi == lo) lo -= 1.0;

    // normalize + deterministic argmax (first max in node order)
    int64_t best = -1;
    double best_s = -std::numeric_limits<double>::infinity();
    for (int64_t j = 0; j < N; ++j) {
      if (!feasible[j]) continue;
      const double s = (score[j] - lo) * 100.0 / (hi - lo);
      if (s > best_s) {
        best_s = s;
        best = j;
      }
    }

    out_idx[i] = static_cast<int32_t>(best);
    float* freeb = free_cap + best * R;
    for (int64_t r = 0; r < R; ++r) freeb[r] -= req[r];
    ++bound;
  }
  return bound;
}

// Buffer-reusing variant: leaves free_in untouched and writes the
// post-bind capacities to free_out (free_out == free_in is allowed and
// degenerates to the in-place cycle above). With stable input/output
// buffers a caller can prebind every pointer once and pay only the
// foreign-call cost per cycle — the per-cycle floor for tiny clusters
// (see native.ScalarCycler), where ctypes marshaling would otherwise
// dominate the whole cycle.
extern "C" int64_t yoda_scalar_cycle_buf(int64_t P, int64_t N, int64_t R,
                                         const float* pod_req,
                                         const float* r_io,
                                         const float* free_in, float* free_out,
                                         const float* disk_io,
                                         const float* cpu_pct, int truncate,
                                         int32_t* out_idx) {
  if (free_out != free_in) {
    for (int64_t k = 0; k < N * R; ++k) free_out[k] = free_in[k];
  }
  return yoda_scalar_cycle(P, N, R, pod_req, r_io, free_out, disk_io, cpu_pct,
                           truncate, out_idx);
}

extern "C" void yoda_aggregate_requested(int64_t M, int64_t N, int64_t R,
                                         const int32_t* pod_node,
                                         const float* pod_req,
                                         float* requested) {
  for (int64_t i = 0; i < M; ++i) {
    const int32_t j = pod_node[i];
    if (j < 0 || j >= N) continue;
    const float* req = pod_req + i * R;
    float* row = requested + static_cast<int64_t>(j) * R;
    for (int64_t r = 0; r < R; ++r) row[r] += req[r];
  }
}
