// Scheduling queue: priority heap + backoff heap.
//
// Semantics mirror kubernetes_scheduler_tpu/host/queue.py (itself modeled
// on the reference's sort.go:8-18 comparator and the upstream queue's
// podInitialBackoffSeconds/podMaxBackoffSeconds behavior,
// deploy/yoda-scheduler.yaml:19-20): higher priority first, FIFO among
// equals via a monotone sequence number; unschedulable pods re-enter the
// active queue only after an exponentially growing delay.

#include "yoda_host.h"

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <queue>
#include <unordered_map>
#include <vector>

namespace {

struct ActiveEntry {
  int32_t priority;
  uint64_t seq;
  uint64_t pod;
  // min-heap on (-priority, seq): invert for std::priority_queue's max-heap
  bool operator<(const ActiveEntry& o) const {
    if (priority != o.priority) return priority < o.priority;
    return seq > o.seq;
  }
};

struct BackoffEntry {
  double ready_at;
  uint64_t seq;
  uint64_t pod;
  int32_t priority;
  bool operator<(const BackoffEntry& o) const {
    if (ready_at != o.ready_at) return ready_at > o.ready_at;  // min-heap
    return seq > o.seq;
  }
};

}  // namespace

struct YodaQueue {
  std::priority_queue<ActiveEntry> active;
  std::priority_queue<BackoffEntry> backoff;
  std::unordered_map<uint64_t, int32_t> attempts;
  uint64_t seq = 0;
  double initial_backoff;
  double max_backoff;
};

extern "C" {

YodaQueue* yoda_queue_new(double initial_backoff, double max_backoff) {
  auto* q = new YodaQueue();
  q->initial_backoff = initial_backoff;
  q->max_backoff = max_backoff;
  return q;
}

void yoda_queue_free(YodaQueue* q) { delete q; }

void yoda_queue_push(YodaQueue* q, uint64_t pod, int32_t priority) {
  q->active.push(ActiveEntry{priority, q->seq++, pod});
}

void yoda_queue_requeue_unschedulable(YodaQueue* q, uint64_t pod,
                                      int32_t priority, double now) {
  int32_t attempt = ++q->attempts[pod];
  double delay = q->initial_backoff * std::ldexp(1.0, attempt - 1);
  delay = std::min(delay, q->max_backoff);
  q->backoff.push(BackoffEntry{now + delay, q->seq++, pod, priority});
}

void yoda_queue_mark_scheduled(YodaQueue* q, uint64_t pod) {
  q->attempts.erase(pod);
}

void yoda_queue_mark_scheduled_batch(YodaQueue* q, const uint64_t* pods,
                                     int64_t n) {
  for (int64_t i = 0; i < n; ++i) q->attempts.erase(pods[i]);
}

int64_t yoda_queue_pop_window(YodaQueue* q, double now, uint64_t* out,
                              int64_t max_n) {
  while (!q->backoff.empty() && q->backoff.top().ready_at <= now) {
    const BackoffEntry e = q->backoff.top();
    q->backoff.pop();
    q->active.push(ActiveEntry{e.priority, q->seq++, e.pod});
  }
  int64_t n = 0;
  while (!q->active.empty() && n < max_n) {
    out[n++] = q->active.top().pod;
    q->active.pop();
  }
  return n;
}

int64_t yoda_queue_len(const YodaQueue* q) {
  return static_cast<int64_t>(q->active.size() + q->backoff.size());
}

int32_t yoda_host_abi_version(void) { return 4; }

}  // extern "C"
