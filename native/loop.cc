// Fully-native tiny-cycle host loop: queue pop -> scalar cycle -> bind.
//
// The per-cycle floor of the Python host on tiny constraint-free cycles
// is the ctypes foreign-call dispatch (~2us), ~20x the C++ scheduling
// work itself (PARITY.md "single-pod floor analysis"). This loop moves
// the whole cycle sequence native: ONE foreign call runs up to n_cycles
// full cycles — each popping a window from the native queue (queue.cc),
// scoring it with the scalar cycle's exact decisions (scalar.cc), then
// binding (capacity decrement + mark-scheduled) or requeueing
// unschedulable pods with backoff. Decisions are identical to driving
// yoda_scalar_cycle one window at a time from Python; only the dispatch
// overhead changes.
//
// The clock is injected and advances dt_per_cycle per cycle so backoff
// behaves deterministically in benchmarks and tests.

#include "yoda_host.h"

#include <cstdint>
#include <vector>

// Runs up to n_cycles cycles (stopping early once the queue is fully
// drained, backoff entries included). Pod handles pushed to the queue
// must be indices into the [M, R] pod arrays. out_idx[M] must arrive
// initialized (typically -1); each bind overwrites the pod's slot, so a
// later bind of a retried pod wins. Returns the total number of binds;
// *out_cycles reports how many cycles actually ran.
extern "C" int64_t yoda_native_loop(YodaQueue* q, int64_t n_cycles,
                                    int64_t window, int64_t M, int64_t N,
                                    int64_t R, const float* pod_req,
                                    const float* r_io, const int32_t* prio,
                                    float* free_cap, const float* disk_io,
                                    const float* cpu_pct, int truncate,
                                    int reset_free, double now,
                                    double dt_per_cycle, int32_t* out_idx,
                                    int64_t* out_cycles) {
  std::vector<uint64_t> handles(window);
  std::vector<float> w_req(window * R);
  std::vector<float> w_rio(window);
  std::vector<int32_t> w_idx(window);
  // reset_free: each cycle schedules against the ORIGINAL capacity — the
  // steady-state regime where the snapshot is rebuilt from cluster state
  // between cycles and earlier test pods have moved on (what the
  // ScalarCycler benchmark's rebound free buffer models)
  std::vector<float> free0;
  if (reset_free) free0.assign(free_cap, free_cap + N * R);
  int64_t bound_total = 0;
  int64_t cycles = 0;
  for (; cycles < n_cycles; ++cycles) {
    if (yoda_queue_len(q) == 0) break;
    const int64_t p =
        yoda_queue_pop_window(q, now, handles.data(), window);
    if (p == 0) {
      // everything queued is in backoff: idle-tick the clock forward
      now += dt_per_cycle;
      continue;
    }
    if (reset_free) {
      for (int64_t k = 0; k < N * R; ++k) free_cap[k] = free0[k];
    }
    for (int64_t i = 0; i < p; ++i) {
      const uint64_t h = handles[i];
      if (h >= static_cast<uint64_t>(M)) return -1;  // caller bug
      const float* src = pod_req + h * R;
      float* dst = w_req.data() + i * R;
      for (int64_t r = 0; r < R; ++r) dst[r] = src[r];
      w_rio[i] = r_io[h];
    }
    bound_total += yoda_scalar_cycle(p, N, R, w_req.data(), w_rio.data(),
                                     free_cap, disk_io, cpu_pct, truncate,
                                     w_idx.data());
    for (int64_t i = 0; i < p; ++i) {
      const uint64_t h = handles[i];
      out_idx[h] = w_idx[i];
      if (w_idx[i] >= 0) {
        yoda_queue_mark_scheduled(q, h);
      } else {
        yoda_queue_requeue_unschedulable(q, h, prio[h], now);
      }
    }
    now += dt_per_cycle;
  }
  *out_cycles = cycles;
  return bound_total;
}
