"""Node-axis-sharded scheduling engine: shard_map over a device mesh.

The single-device pipeline (engine.schedule_batch) with the node axis split
across chips:

- utilization mean/variance become `psum`s (the analog of the reference's
  Redis-shared statistics, algorithm.go:67-89 — now an ICI collective);
- score-normalization bounds and card-metric maxima become `pmax`/`pmin`;
- greedy assignment keeps exact sequential-greedy semantics: each step does
  a local masked argmax per shard, then an `all_gather` of (best score,
  global index) candidates — one small collective per pod — and only the
  owning shard decrements its capacity slice.

Pods stay replicated (they are small: [p, r] vectors), nodes are sharded:
the same layout choice as sequence parallelism with a sharded sequence
axis. All collectives ride ICI inside a slice; nothing here needs DCN
unless the mesh itself spans hosts.
"""

from __future__ import annotations

import functools
import inspect

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level with check_vma
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

from kubernetes_scheduler_tpu.engine import (
    FusedLayout,
    PodBatch,
    ResidentState,
    ScheduleResult,
    SnapshotArrays,
    SnapshotDelta,
    compute_feasibility,
    compute_free_capacity,
)
from kubernetes_scheduler_tpu.ops import card_fit, card_score, free_capacity
from kubernetes_scheduler_tpu.ops.assign import (
    NEG,
    AffinityState,
    _affinity_round_mask,
    _evict_conflicts_core,
    _priority_order,
    _segmented_admission,
    affinity_ok_from_counts,
    anti_reverse_ok,
    pod_has_anti_onehot,
    tie_jitter,
)
from kubernetes_scheduler_tpu.ops.collect import local_max_card_values
from kubernetes_scheduler_tpu.ops.normalize import min_max_normalize, score_bounds, softmax_normalize
from kubernetes_scheduler_tpu.ops.score import (
    balanced_cpu_diskio,
    balanced_diskio_from_m,
    balanced_diskio_local_bounds,
    balanced_diskio_m,
)
from kubernetes_scheduler_tpu.ops.stats import CPU_DIVISOR, DISK_IO_DIVISOR, UtilizationStats
from kubernetes_scheduler_tpu.parallel.mesh import NODE_AXIS, make_mesh

_VMA_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions (check_vma was called check_rep
    before the experimental module graduated). The pre-graduation
    check_rep verifier has no replication rule for while_loop (the
    auction assigner's round loop), so on old jax the checker is off
    entirely — it is a trace-time development aid; decisions are
    identical either way."""
    if _VMA_KW == "check_rep":
        check_vma = False
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_VMA_KW: check_vma},
    )


if hasattr(jax.lax, "pcast"):
    def _pcast_varying(x, axes):
        return jax.lax.pcast(x, axes, to="varying")
else:
    def _pcast_varying(x, axes):
        # pre-pcast jax has no varying-manual-axes annotations; the
        # check_rep checker infers replication on its own
        return x


def _sharded_stats(snapshot: SnapshotArrays, axes) -> UtilizationStats:
    """utilization_stats with psum reductions over the node shards."""
    mask = snapshot.node_mask.astype(jnp.float32)
    n_valid = jnp.maximum(jax.lax.psum(mask.sum(), axes), 1.0)
    u = snapshot.disk_io / DISK_IO_DIVISOR
    v = snapshot.cpu_pct / CPU_DIVISOR
    u_avg = jax.lax.psum((u * mask).sum(), axes) / n_valid
    m_var = jax.lax.psum((((u - u_avg) ** 2) * mask).sum(), axes) / n_valid
    return UtilizationStats(u=u, v=v, u_avg=u_avg, m_var=m_var, n_valid=n_valid)


def _sharded_scores(
    snapshot: SnapshotArrays, pods: PodBatch, policy: str, axes
) -> jnp.ndarray:
    stats = _sharded_stats(snapshot, axes)
    if policy == "balanced_cpu_diskio":
        return balanced_cpu_diskio(stats, pods.request[:, 0], pods.r_io)
    if policy == "balanced_diskio":
        m = balanced_diskio_m(stats, snapshot.disk_io, pods.r_io)
        m_hi, m_lo = balanced_diskio_local_bounds(m, snapshot.node_mask)
        m_hi = jax.lax.pmax(m_hi, axes)
        m_lo = jax.lax.pmin(m_lo, axes)
        return balanced_diskio_from_m(m, m_hi, m_lo)
    if policy == "free_capacity":
        s = free_capacity(snapshot.cpu_pct, snapshot.mem_pct, snapshot.disk_io)
        return jnp.broadcast_to(s[None, :], (pods.request.shape[0], s.shape[0]))
    if policy == "card":
        node_fits, per_card = card_fit(
            snapshot.cards, snapshot.card_mask, snapshot.card_healthy,
            pods.want_number, pods.want_memory, pods.want_clock,
        )
        local_max = local_max_card_values(
            snapshot.cards, per_card & node_fits[:, :, None]
        )
        maxima = jnp.maximum(jax.lax.pmax(local_max, axes), 1.0)
        return card_score(snapshot.cards, snapshot.card_mask, per_card, maxima)
    if policy in ("least_allocated", "balanced_allocation", "image_locality"):
        # purely node-local (A/Q matrices / the host-prescaled image
        # signal): the dense kernels shard along the node axis with no
        # collective — reuse them so the paths cannot diverge
        from kubernetes_scheduler_tpu.engine import compute_scores

        return compute_scores(snapshot, pods, policy)
    raise ValueError(f"unknown policy {policy!r}")


def _sharded_combined_scores(
    snapshot: SnapshotArrays, pods: PodBatch, score_plugins: tuple, axes
) -> jnp.ndarray:
    """engine.combine_scores on the mesh: per-plugin matrices from
    _sharded_scores (each already globally exact), min-max rescaled with
    GLOBAL pmax/pmin bounds for plugins the framework normalizes, then
    the weighted sum — term order and f32 arithmetic match the dense
    combination, so decisions stay bit-identical."""
    from kubernetes_scheduler_tpu.engine import PRESCALED_PLUGINS

    total = None
    for name, weight in score_plugins:
        raw = _sharded_scores(snapshot, pods, name, axes)
        if name not in PRESCALED_PLUGINS:
            hi, lo = score_bounds(raw, snapshot.node_mask)
            hi = jax.lax.pmax(hi, axes)
            lo = jax.lax.pmin(lo, axes)
            raw = min_max_normalize(raw, snapshot.node_mask, bounds=(hi, lo))
        term = raw * float(weight)
        total = term if total is None else total + term
    return total


def _sharded_greedy(
    norm: jnp.ndarray,
    feasible: jnp.ndarray,
    pods: PodBatch,
    free0: jnp.ndarray,
    snapshot: SnapshotArrays,
    axes,
    added2_0: jnp.ndarray | None = None,
):
    """Exact greedy over the sharded node axis.

    Each scan step: local masked argmax -> all_gather of (score, global idx)
    candidates -> identical global choice on every shard (first-max
    tie-break matches the single-device argmax) -> owning shard decrements
    its capacity slice, and the chosen node's topology-domain ids are
    psum-broadcast so every shard updates the (replicated) in-window
    inter-pod-affinity counts identically.

    added2_0: optional [2, n_global, S] in-window domain-count carry
    (matches + avoiders) from PREVIOUS windows of the same backlog, so a
    multi-window caller (make_sharded_windows_fn) keeps exact cross-window
    (anti)affinity; it is threaded through and returned for the next
    window.
    """
    n_local = norm.shape[1]
    n_devices = jax.lax.psum(1, axes)
    n_global = n_local * n_devices
    offset = jax.lax.axis_index(axes).astype(jnp.int32) * n_local
    order = _priority_order(pods.priority, pods.pod_mask)
    p = norm.shape[0]
    s = snapshot.domain_counts.shape[1]
    cols = jnp.arange(s)
    from kubernetes_scheduler_tpu.engine import match_matrix

    matches = match_matrix(pods, s)
    has_anti = pod_has_anti_onehot(pods.anti_affinity_sel, s)
    # the scan body mixes per-shard (varying) values into the update chain,
    # so the carry must start out marked varying for the vma checker
    added0 = (
        added2_0
        if added2_0 is not None
        else _pcast_varying(jnp.zeros((2, n_global, s), jnp.float32), axes)
    )

    def step(carry, i):
        free, added2 = carry
        added, added_avoid = added2[0], added2[1]
        req = pods.request[i]
        cap_ok = ((req[None, :] <= free) | (req[None, :] == 0)).all(-1)
        # live inter-pod affinity counts: base (local) + in-window
        # placements (replicated, indexed by global domain id)
        cnt = snapshot.domain_counts + added[snapshot.domain_id, cols[None, :]]
        aff_ok = affinity_ok_from_counts(
            cnt, pods.affinity_sel[i], pods.anti_affinity_sel[i]
        )
        avoid_cnt = (
            snapshot.avoid_counts + added_avoid[snapshot.domain_id, cols[None, :]]
        )
        aff_ok = aff_ok & anti_reverse_ok(avoid_cnt, matches[i])
        # hard topology spread with a GLOBAL min over the sharded node axis
        big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
        dmin = jax.lax.pmin(
            jnp.where(snapshot.node_mask[:, None], cnt, big).min(0), axes
        )                                                          # [S]
        spc = jnp.clip(pods.spread_sel[i], 0, max(s - 1, 0))
        skew = cnt[:, spc] + 1.0 - dmin[spc][None, :]
        sp_ok = (
            (skew <= pods.spread_max[i][None, :].astype(jnp.float32))
            | (pods.spread_sel[i] < 0)[None, :]
        ).all(-1) & ~(pods.spread_sel[i] >= s).any()
        aff_ok = aff_ok & sp_ok
        mask = feasible[i] & cap_ok & aff_ok & pods.pod_mask[i]
        row = jnp.where(mask, norm[i], NEG)
        local_best = row.max()
        local_arg = jnp.argmax(row).astype(jnp.int32) + offset
        cand_s = jax.lax.all_gather(local_best, axes)  # [D]
        cand_i = jax.lax.all_gather(local_arg, axes)   # [D]
        # Every shard with no feasible node contributes exactly NEG, so
        # "any feasible anywhere" falls out of the gathered maxima — no
        # extra psum collective needed in this latency-bound scan body.
        found = cand_s.max() > NEG * 0.5
        shard = jnp.argmax(cand_s)
        chosen = cand_i[shard]
        local_idx = chosen - offset
        mine = found & (local_idx >= 0) & (local_idx < n_local)
        delta = jnp.zeros_like(free).at[jnp.clip(local_idx, 0, n_local - 1)].set(req)
        free = jnp.where(mine, free - delta, free)
        # broadcast the chosen node's domain ids (owning shard contributes
        # id+1, others 0; -1 after psum means "not found")
        local_dom = snapshot.domain_id[jnp.clip(local_idx, 0, n_local - 1)]  # [S]
        dom = jax.lax.psum(jnp.where(mine, local_dom + 1, 0), axes) - 1
        dom_c = jnp.clip(dom, 0, n_global - 1)
        ok = found & (dom >= 0)
        inc = jnp.where(ok, matches[i].astype(jnp.float32), 0.0)
        inc_a = jnp.where(ok, has_anti[i].astype(jnp.float32), 0.0)
        added2 = jnp.stack(
            [
                added.at[dom_c, cols].add(inc),
                added_avoid.at[dom_c, cols].add(inc_a),
            ]
        )
        return (free, added2), jnp.where(found, chosen, jnp.int32(-1))

    (free_after, added2_f), picks = jax.lax.scan(step, (free0, added0), order)
    node_idx = jnp.full((p,), -1, jnp.int32).at[order].set(picks)
    # picks are computed identically on every shard, but the replication
    # checker cannot see that through all_gather/argmax; a pmax over equal
    # values is the identity and makes replication provable.
    node_idx = jax.lax.pmax(node_idx, axes)
    return node_idx, free_after, added2_f


def _sharded_auction(
    norm: jnp.ndarray,
    feasible: jnp.ndarray,
    pods: PodBatch,
    free0: jnp.ndarray,
    snapshot: SnapshotArrays,
    axes,
    rounds: int,
    price_frac: float,
    added2_0: jnp.ndarray | None = None,
):
    """Distributed price-guided auction over the sharded node axis.

    The dense auction_assign round structure — bid → admit → evict →
    reprice — with per-ROUND collectives instead of greedy's per-POD
    candidate election (rounds are few; this is the regime where the
    auction's parallel rounds beat greedy's O(P) latency-bound collective
    chain on a mesh). Per round:

      1. local bid: each shard computes every pod's best (score + jitter −
         price) over ITS node columns — the [p, n_local] mask includes
         dynamic (anti)affinity/spread against live counts, as in the
         dense affinity-aware auction;
      2. election: ONE stacked all_gather of the per-shard (best value,
         global index) pairs; every shard then picks the identical global
         argmax per pod (first-max tie-break matches the dense argmax);
      3. admission: each shard runs the segmented prefix-sum admission for
         bids that landed on ITS nodes (a node's bidder group never spans
         shards), then one psum ORs the per-shard verdicts;
      4. eviction: same-round conflict resolution runs REPLICATED on every
         shard via _evict_conflicts_core — the only node-side lookups it
         needs (bid node's domain ids and base counts) are psum-broadcast
         from the owning shard, and the spread dmin is a pmin;
      5. fold + reprice: domain-count carries live in the REPRESENTATIVE-
         ROW layout ([n_global, S], indexed by global domain rep id — the
         same table _sharded_greedy threads), so the fold is a replicated
         O(p·S) scatter; free capacity and prices update shard-locally.

    Collectives per round: one all_gather ([2, p] candidate pairs) + three
    psums ([p] admission, [p, S] domain ids, [p, S] base counts) + one
    pmin ([S] spread dmin) — all O(p·S), none O(n).

    Decision parity with the dense auction is exact (bit-identical
    node_idx): the tie-break jitter is a counter-based hash of (row,
    GLOBAL column) (ops/assign.tie_jitter) so shards materialize the same
    values the dense path sees, and the row normalization bounds are
    pmax/pmin'd to global.

    added2_0: optional [2, n_global, S] in-window carry from previous
    windows (representative-row layout); threaded through and returned,
    so make_sharded_windows_fn mixes windows across assigners with exact
    cross-window (anti)affinity.
    """
    from kubernetes_scheduler_tpu.engine import match_matrix

    p, n_local = norm.shape
    n_devices = jax.lax.psum(1, axes)
    n_global = n_local * n_devices
    offset = jax.lax.axis_index(axes).astype(jnp.int32) * n_local
    s = snapshot.domain_counts.shape[1]
    cols = jnp.arange(s)[None, :]
    matches = match_matrix(pods, s)
    has_anti = pod_has_anti_onehot(pods.anti_affinity_sel, s)

    # global per-row min-max to [0, 1] over feasible entries (the dense
    # auction's pricing-scale normalization, with global bounds)
    row_hi = jax.lax.pmax(
        jnp.where(feasible, norm, -jnp.inf).max(axis=1), axes
    )                                                              # [p]
    row_lo = jax.lax.pmin(
        jnp.where(feasible, norm, jnp.inf).min(axis=1), axes
    )
    row_ok = jnp.isfinite(row_hi) & jnp.isfinite(row_lo)
    denom = jnp.where(row_ok, jnp.maximum(row_hi - row_lo, 1e-6), 1.0)
    scores = jnp.where(
        row_ok[:, None],
        (norm - jnp.where(row_ok, row_lo, 0.0)[:, None]) / denom[:, None],
        0.0,
    )
    step = jnp.asarray(price_frac, scores.dtype)
    jitter = tie_jitter(
        p, n_local, 0.01 * price_frac, col_offset=offset, dtype=scores.dtype
    )

    by_prio = _priority_order(pods.priority, pods.pod_mask)
    rank = jnp.zeros((p,), jnp.int32).at[by_prio].set(
        jnp.arange(p, dtype=jnp.int32)
    )
    prio_key = p - rank

    aff_local = AffinityState(
        domain_counts=snapshot.domain_counts,
        domain_id=snapshot.domain_id,   # global representative ids
        pod_matches=matches,
        affinity_sel=pods.affinity_sel,
        anti_affinity_sel=pods.anti_affinity_sel,
        avoid_counts=snapshot.avoid_counts,
        pod_has_anti=has_anti,
        spread_sel=pods.spread_sel,
        spread_max=pods.spread_max,
        node_mask=snapshot.node_mask,
    )
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)

    def varying(x):
        return _pcast_varying(x, axes)

    added2_init = (
        added2_0
        if added2_0 is not None
        else varying(jnp.zeros((2, n_global, s), jnp.float32))
    )

    def round_body(state):
        assigned, free, price, added2, _, r = state
        added, added_avoid = added2[0], added2[1]
        active = pods.pod_mask & (assigned < 0)
        cap_ok = (
            (pods.request[:, None, :] <= free[None, :, :])
            | (pods.request[:, None, :] == 0)
        ).all(-1)                                                  # [p, n_local]
        # live counts: rep-layout carry gathered to this shard's nodes
        added_exp = added[snapshot.domain_id, cols]                # [n_local, S]
        avoid_exp = added_avoid[snapshot.domain_id, cols]
        live_local = snapshot.domain_counts + added_exp
        dmin = jax.lax.pmin(
            jnp.where(snapshot.node_mask[:, None], live_local, big).min(0),
            axes,
        )                                                          # [S]
        aff_ok = _affinity_round_mask(aff_local, added_exp, avoid_exp, dmin=dmin)
        mask = feasible & cap_ok & active[:, None] & aff_ok
        row = jnp.where(mask, scores + jitter - price[None, :], NEG)
        local_best = row.max(axis=1)                               # [p]
        local_arg = jnp.argmax(row, axis=1).astype(jnp.int32) + offset
        # ONE stacked gather per election: the index rides as bitcast f32
        # payload (never arithmetically touched), halving the per-round
        # collective launches on the latency-bound election
        cand = jax.lax.all_gather(
            jnp.stack(
                [local_best, jax.lax.bitcast_convert_type(local_arg, jnp.float32)]
            ),
            axes,
        )                                                          # [D, 2, p]
        cand_s = cand[:, 0, :]                                     # [D, p]
        cand_i = jax.lax.bitcast_convert_type(cand[:, 1, :], jnp.int32)
        gbest = cand_s.max(axis=0)
        shard = jnp.argmax(cand_s, axis=0)                         # first max
        bid = jnp.take_along_axis(cand_i, shard[None, :], axis=0)[0]  # [p]
        has_bid = gbest > NEG * 0.5
        blocal = bid - offset
        mine = has_bid & (blocal >= 0) & (blocal < n_local)
        adm_local = _segmented_admission(
            blocal, mine, pods.request, free, by_prio
        )
        admitted = jax.lax.psum(adm_local.astype(jnp.int32), axes) > 0  # [p]
        # same-round conflict eviction, replicated: broadcast the owning
        # shard's bid-node lookups, then every shard runs the identical
        # per-pod resolution
        bl_c = jnp.clip(blocal, 0, n_local - 1)
        dom_local = snapshot.domain_id[bl_c]                       # [p, S]
        dom_p = (
            jax.lax.psum(jnp.where(mine[:, None], dom_local + 1, 0), axes) - 1
        )
        dom_c = jnp.clip(dom_p, 0, n_global - 1)
        base_at_bid = jax.lax.psum(
            jnp.where(mine[:, None], snapshot.domain_counts[bl_c], 0.0), axes
        )
        added_at_bid = added[dom_c, cols]
        evict = _evict_conflicts_core(
            matches, pods.anti_affinity_sel, has_anti,
            pods.spread_sel, pods.spread_max, admitted, dom_c, prio_key,
            base_at_bid, added_at_bid, dmin, n_global,
        )
        admitted = admitted & ~evict
        # fold permanent placements into the rep-layout carries (replicated)
        inc_m = jnp.where(admitted[:, None], matches.astype(jnp.float32), 0.0)
        inc_a = jnp.where(admitted[:, None], has_anti.astype(jnp.float32), 0.0)
        added2 = jnp.stack(
            [
                added.at[dom_c, cols].add(inc_m),
                added_avoid.at[dom_c, cols].add(inc_a),
            ]
        )
        new_assigned = jnp.where(admitted, bid, assigned)
        used = jnp.zeros_like(free).at[bl_c].add(
            jnp.where((admitted & mine)[:, None], pods.request, 0.0)
        )
        rejected = (
            jnp.zeros((n_local,), bool).at[bl_c].max(mine & ~admitted)
        )
        return (
            new_assigned,
            free - used,
            price + jnp.where(rejected, step, 0.0),
            added2,
            has_bid.any(),
            r + 1,
        )

    def cond(state):
        can_bid, r = state[-2], state[-1]
        return (r < rounds) & can_bid

    assigned, free_after, _, added2_f, _, _ = jax.lax.while_loop(
        cond,
        round_body,
        (
            varying(jnp.full((p,), -1, jnp.int32)),
            free0,
            varying(jnp.zeros((n_local,), jnp.float32)),
            added2_init,
            varying(jnp.asarray(True)),
            jnp.int32(0),
        ),
    )
    # identical on every shard; pmax makes replication provable (see
    # _sharded_greedy)
    assigned = jax.lax.pmax(assigned, axes)
    return assigned, free_after, added2_f


def _check_fused(fused, policy, normalizer, score_fn) -> None:
    """The fused kernel's contract (engine.check_fused_contract — ONE
    definition for both surfaces) plus the sharded-only score_fn clash."""
    if not fused:
        return
    if score_fn is not None:
        raise ValueError("fused=True cannot combine with a custom score_fn")
    from kubernetes_scheduler_tpu.engine import check_fused_contract

    check_fused_contract(policy, normalizer)


def _mesh_specs(mesh: Mesh, node_axes):
    """Validated mesh axes + the standard sharding specs: per-node arrays
    shard on their leading node axis, per-pod arrays replicate. Shared by
    both sharded factories so the layouts cannot drift."""
    axes = node_axes if isinstance(node_axes, tuple) else (node_axes,)
    missing = [a for a in axes if a not in mesh.axis_names]
    if missing:
        raise ValueError(f"mesh {mesh.axis_names} lacks axes {missing}")
    node = P(axes)
    rep = P()
    snap_specs = SnapshotArrays(**{f: node for f in SnapshotArrays._fields})
    pod_specs = PodBatch(**{f: rep for f in PodBatch._fields})
    return axes, node, rep, snap_specs, pod_specs


def _delta_specs(axes) -> SnapshotDelta:
    """Partition specs of a stacked per-shard SnapshotDelta (see
    stack_shard_deltas): every leaf carries a leading shard axis, so
    each shard's block is its own row delta in shard-local coordinates."""
    return SnapshotDelta(**{f: P(axes) for f in SnapshotDelta._fields})


def _layout_specs(axes) -> FusedLayout:
    """Partition specs of a mesh-sharded engine.FusedLayout: the
    kernel-layout buffers shard on their node/column axis (axis 1)."""
    col = P(None, axes)
    return FusedLayout(node_ft=col, alloc_t=col, reqd_t=col)


def _local_delta(delta: SnapshotDelta) -> SnapshotDelta:
    """Strip the leading shard axis off a stacked delta inside a
    shard_map body (each shard sees its own [1, ...] block)."""
    return SnapshotDelta(*[leaf[0] for leaf in delta])


def _window_pipeline(snapshot, pods, policy, normalizer, soft, axes,
                     score_fn=None, fused=False, score_plugins=None,
                     layout=None):
    """Scores + static feasibility + normalization for one window on one
    shard — the shared front half of the sharded single-window and
    multi-window programs (they must not diverge).

    score_fn: optional custom scorer called with the SHARD-LOCAL
    (snapshot, pods), returning a [p, n_local] raw score matrix — the
    hook that puts e.g. the learned two-tower policy on the mesh (its
    node tower is node-local, so the scorer shards for free); the
    global normalization (pmax/pmin/psum bounds) still applies on top.
    When given, `policy` is ignored.

    fused=True routes score + resource fit through the Pallas kernel on
    this shard's node columns — the balanced_cpu_diskio formula is
    purely node-local (u, v per node; no cross-node statistic), so the
    kernel shards with zero extra collectives. Requires
    normalizer="none" (STRICTER than the dense path, which also admits
    min_max via the kernel epilogue: the sharded min-max bounds are
    pmax/pmin-reduced GLOBAL values a shard-local epilogue cannot see —
    engine.check_fused_contract's min_max_ok stays False here);
    `scores`/`feasible` carry the NEG-masked contract of
    engine._fused_masked_scores."""
    # spec.nodeName pinning is GLOBAL (target_node indexes the full
    # node axis) but feasibility columns are shard-LOCAL: translate by
    # this shard's offset, mapping out-of-shard targets to the
    # matches-nothing encoding (n_local) — NOT to a negative value,
    # which node_name_fit reads as "unpinned".
    n_local = snapshot.allocatable.shape[0]
    offset = jax.lax.axis_index(axes).astype(jnp.int32) * n_local
    local = pods.target_node - offset
    local = jnp.where((local < 0) | (local >= n_local), n_local, local)
    pods_local = pods._replace(
        target_node=jnp.where(pods.target_node < 0, pods.target_node, local)
    )

    if fused:
        from kubernetes_scheduler_tpu.engine import _fused_masked_scores

        # layout: this shard's retained kernel-layout buffers
        # (ShardedEngine resident cycles) — the per-shard twin of the
        # dense resident layout pass; None re-preps per call
        raw = _fused_masked_scores(
            snapshot, pods_local, include_pod_affinity=False, layout=layout
        )
        feasible = raw > NEG * 0.5
        norm = raw
        if soft:
            norm = norm + _sharded_soft_scores(snapshot, pods, axes)
        return raw, norm, feasible

    if score_plugins:
        # weighted multi-plugin combination: per-plugin normalization
        # happens inside (with global bounds) and the weighted sum is
        # final — `normalizer` is ignored like the dense path
        raw = _sharded_combined_scores(snapshot, pods, score_plugins, axes)
        feasible = compute_feasibility(
            snapshot, pods_local, include_pod_affinity=False
        )
        norm = raw
        if soft:
            norm = norm + _sharded_soft_scores(snapshot, pods, axes)
        return raw, norm, feasible

    raw = (
        score_fn(snapshot, pods)
        if score_fn is not None
        else _sharded_scores(snapshot, pods, policy, axes)
    )
    # purely local/elementwise on the node axis — reuse the
    # single-device implementation so the two paths cannot diverge.
    # Inter-pod affinity is excluded from the static mask: the greedy
    # scan evaluates it dynamically (base + in-window counts).
    feasible = compute_feasibility(
        snapshot, pods_local, include_pod_affinity=False
    )

    if normalizer == "min_max":
        hi, lo = score_bounds(raw, snapshot.node_mask)
        hi = jax.lax.pmax(hi, axes)
        lo = jax.lax.pmin(lo, axes)
        norm = min_max_normalize(raw, snapshot.node_mask, bounds=(hi, lo))
    elif normalizer == "softmax":
        # masked softmax with a global denominator
        neg = jnp.asarray(-1e30, raw.dtype)
        logits = jnp.where(snapshot.node_mask[None, :], raw, neg)
        z = jax.lax.pmax(logits.max(axis=1, keepdims=True), axes)
        e = jnp.exp(logits - z)
        denom = jax.lax.psum(e.sum(axis=1, keepdims=True), axes)
        norm = e / denom
    elif normalizer == "none":
        norm = raw
    else:
        raise ValueError(f"unknown normalizer {normalizer!r}")

    if soft:
        norm = norm + _sharded_soft_scores(snapshot, pods, axes)
    return raw, norm, feasible


def _sharded_soft_scores(snapshot, pods, axes) -> jnp.ndarray:
    """compute_soft_scores on this shard's node columns. Every soft
    family reads node-LOCAL state except the ScheduleAnyway spread term's
    min-over-domains, which must be the GLOBAL minimum (domains span
    shards) — the dense definition's local value, pmin'd."""
    from kubernetes_scheduler_tpu.engine import (
        compute_soft_scores,
        local_spread_dmin,
    )

    dmin = jax.lax.pmin(local_spread_dmin(snapshot), axes)
    return compute_soft_scores(snapshot, pods, spread_dmin=dmin)


def _with_auction_knobs(jfn, rounds0: int, price_frac0: float):
    """Wrap a jitted sharded program taking (snapshot, pods, rounds,
    price_frac) into the engine call surface with optional per-call
    auction knobs. The knobs are TRACED operands (the round loop's bound
    and the price step), so per-call overrides recompile nothing — the
    sidecar honors request-carried knobs instead of aborting (round-4
    verdict "what's weak" #5); the build-time values are the defaults.
    Rounds are clamped into int32 range: a wire int64 beyond it means
    "run to convergence", which the bid-exhaustion condition already
    bounds — an OverflowError here would surface as a gRPC INTERNAL."""
    int32_max = jnp.iinfo(jnp.int32).max

    def call(
        snapshot, pods, *extra,
        auction_rounds=None, auction_price_frac=None,
    ):
        r = auction_rounds if auction_rounds is not None else rounds0
        f = (
            auction_price_frac
            if auction_price_frac is not None
            else price_frac0
        )
        return jfn(
            snapshot, pods,
            jnp.asarray(min(int(r), int32_max), jnp.int32),
            jnp.asarray(f, jnp.float32),
            *extra,
        )

    return call


def make_sharded_schedule_fn(
    mesh: Mesh,
    *,
    policy: str = "balanced_cpu_diskio",
    normalizer: str = "min_max",
    node_axes: str | tuple[str, ...] = NODE_AXIS,
    soft: bool = False,
    score_fn=None,
    assigner: str = "greedy",
    auction_rounds: int = 1024,
    auction_price_frac: float = 1.0,
    fused: bool = False,
    score_plugins: tuple | None = None,
    resident_layout: bool = False,
):
    """Build a jitted shard_map'd schedule function for `mesh`.

    Expects every per-node array sharded on its node axis (axis 0 for
    [n, ...] arrays, axis 1 for the returned [p, n] score matrices) and all
    per-pod arrays replicated. The returned function has the same signature
    and result type as engine.schedule_batch.

    node_axes: mesh axis (or axis tuple) the cluster-node dimension shards
    over. For a multi-host slice pass a mesh from make_mesh_multihost and
    node_axes=(DCN_AXIS, NODE_AXIS): every collective then runs over the
    combined axis and XLA lowers it hierarchically — the big per-shard
    reductions ride ICI, only the tiny cross-host residual (scalar stats,
    one (score, index) candidate pair per host group) crosses DCN.

    soft=True layers the preferred-constraint score terms
    (engine.compute_soft_scores) onto the normalized score, exactly like
    schedule_batch(soft=True): every soft family reads node-LOCAL state
    (labels, taints, per-node-replicated domain counts, preferred-term
    matrices), so the term shards along the node axis with no extra
    collective; normalization bounds are already global (pmax/pmin), so
    weight-vs-range semantics match the dense path bit-for-bit.

    assigner selects between the exact sequential greedy (_sharded_greedy:
    one candidate-election collective per POD — the right trade at small
    windows) and the distributed price-guided auction (_sharded_auction:
    a handful of O(p·S) collectives per ROUND, bit-identical decisions to
    the dense auction_assign — the performance assigner for large
    windows, now first-class on the mesh). Both paths evaluate inter-pod
    (anti)affinity and spread dynamically against live counts.

    For a whole backlog in one dispatch use make_sharded_windows_fn,
    which threads the capacity AND (anti)affinity carries across
    windows exactly like engine.schedule_windows does on one device.

    resident_layout=True (fused only) makes the returned function take a
    third operand: a mesh-sharded engine.FusedLayout (leaves sharded on
    their node/column axis — build with make_sharded_build_layout_fn,
    fold deltas with make_sharded_apply_layout_fn) so resident cycles
    feed each shard's retained kernel-layout buffers straight into the
    megakernel instead of re-prepping per call — the ShardedEngine
    production path.
    """
    if resident_layout and not fused:
        raise ValueError("resident_layout=True requires fused=True")
    if assigner not in ("greedy", "auction"):
        raise ValueError(f"unknown assigner {assigner!r}")
    if score_plugins and (fused or score_fn is not None):
        # the fused kernel hardwires the single yoda formula and a
        # custom score_fn replaces the policy outright — silently
        # preferring either over the weighted combination would serve
        # different placements than the options advertise
        raise ValueError(
            "score_plugins cannot combine with fused=True or score_fn"
        )
    _check_fused(fused, policy, normalizer, score_fn)
    axes, node, rep, snap_specs, pod_specs = _mesh_specs(mesh, node_axes)
    out_specs = ScheduleResult(
        node_idx=rep,
        scores=P(None, axes),
        raw_scores=P(None, axes),
        feasible=P(None, axes),
        free_after=node,
        n_assigned=rep,
    )

    def body(
        snapshot: SnapshotArrays, pods: PodBatch, rounds, price_frac,
        *extra,
    ) -> ScheduleResult:
        raw, norm, feasible = _window_pipeline(
            snapshot, pods, policy, normalizer, soft, axes, score_fn,
            fused, score_plugins,
            layout=extra[0] if resident_layout else None,
        )
        free0 = compute_free_capacity(snapshot)
        if assigner == "greedy":
            node_idx, free_after, _ = _sharded_greedy(
                norm, feasible, pods, free0, snapshot, axes
            )
        else:
            node_idx, free_after, _ = _sharded_auction(
                norm, feasible, pods, free0, snapshot, axes,
                rounds, price_frac,
            )
        return ScheduleResult(
            node_idx=node_idx,
            scores=norm,
            raw_scores=raw,
            feasible=feasible,
            free_after=free_after,
            n_assigned=(node_idx >= 0).sum().astype(jnp.int32),
        )

    in_specs: tuple = (snap_specs, pod_specs, P(), P())
    if resident_layout:
        in_specs = in_specs + (_layout_specs(axes),)
    # the Pallas kernel's out_shape carries no vma annotation, so the
    # fused variant runs with the varying-manual-axes checker off (the
    # non-fused paths keep it: pcast/pmax provability is its value)
    fn = shard_map(
        body, mesh=mesh, in_specs=in_specs,
        out_specs=out_specs, check_vma=not fused,
    )
    return _with_auction_knobs(
        jax.jit(fn), auction_rounds, auction_price_frac
    )


def make_sharded_windows_fn(
    mesh: Mesh,
    *,
    policy: str = "balanced_cpu_diskio",
    normalizer: str = "min_max",
    node_axes: str | tuple[str, ...] = NODE_AXIS,
    soft: bool = False,
    score_fn=None,
    assigner: str = "greedy",
    auction_rounds: int = 1024,
    auction_price_frac: float = 1.0,
    fused: bool = False,
    score_plugins: tuple | None = None,
):
    """Multi-window sharded scheduling: engine.schedule_windows with the
    node axis sharded over `mesh`.

    Takes (snapshot, pods_windows) where pods_windows carries a leading
    [w, p, ...] window axis (engine.stack_windows) and returns
    engine.WindowsResult. One device dispatch schedules the whole
    backlog: a lax.scan over windows threads free capacity AND the
    in-window (anti)affinity domain-count carry (the [2, n_global, S]
    representative-row table both sharded assigners maintain) between
    windows, so window k+1 sees window k's placements exactly as the
    dense schedule_windows scan does. assigner selects greedy or the
    distributed auction per window (see make_sharded_schedule_fn).
    """
    from kubernetes_scheduler_tpu.engine import WindowsResult

    if assigner not in ("greedy", "auction"):
        raise ValueError(f"unknown assigner {assigner!r}")
    if score_plugins and (fused or score_fn is not None):
        # the fused kernel hardwires the single yoda formula and a
        # custom score_fn replaces the policy outright — silently
        # preferring either over the weighted combination would serve
        # different placements than the options advertise
        raise ValueError(
            "score_plugins cannot combine with fused=True or score_fn"
        )
    _check_fused(fused, policy, normalizer, score_fn)
    axes, node, rep, snap_specs, pod_specs = _mesh_specs(mesh, node_axes)
    out_specs = WindowsResult(node_idx=rep, free_after=node, n_assigned=rep)

    def body(
        snapshot: SnapshotArrays, pods_w: PodBatch, rounds, price_frac
    ) -> WindowsResult:
        s = snapshot.domain_counts.shape[1]
        n_local = snapshot.allocatable.shape[0]
        n_global = n_local * jax.lax.psum(1, axes)
        free0 = compute_free_capacity(snapshot)
        added0 = _pcast_varying(
            jnp.zeros((2, n_global, s), jnp.float32), axes
        )

        cols = jnp.arange(s)[None, :]

        def wstep(carry, w):
            free, added2 = carry
            # feasibility must see the capacity consumed by previous
            # windows, and the SOFT terms (preferred inter-pod affinity,
            # the one domain_counts reader in the pipeline) must see
            # their placements' domain counts, like the dense scan's
            # fold. avoid_counts is NOT folded here: its only reader
            # (the reverse anti-affinity check) runs inside
            # _sharded_greedy from the added2 carry directly. Scores
            # read utilization series, static across the backlog.
            snap_pipe = snapshot._replace(
                requested=snapshot.allocatable - free,
                domain_counts=snapshot.domain_counts
                + added2[0][snapshot.domain_id, cols],
            )
            _, norm, feasible = _window_pipeline(
                snap_pipe, w, policy, normalizer, soft, axes, score_fn,
                fused, score_plugins,
            )
            # the assigner takes the ORIGINAL counts plus the added2 carry
            # (it layers the carry itself — snap_pipe's folded counts
            # would double-count)
            if assigner == "greedy":
                node_idx, free_after, added2 = _sharded_greedy(
                    norm, feasible, w, free, snapshot, axes, added2
                )
            else:
                node_idx, free_after, added2 = _sharded_auction(
                    norm, feasible, w, free, snapshot, axes,
                    rounds, price_frac, added2,
                )
            return (free_after, added2), (
                node_idx, (node_idx >= 0).sum().astype(jnp.int32)
            )

        (free_f, _), (idx, counts) = jax.lax.scan(
            wstep, (free0, added0), pods_w
        )
        return WindowsResult(
            node_idx=idx,
            free_after=free_f,
            n_assigned=counts.sum().astype(jnp.int32),
        )

    fn = shard_map(
        body, mesh=mesh, in_specs=(snap_specs, pod_specs, P(), P()),
        out_specs=out_specs, check_vma=not fused,
    )
    return _with_auction_knobs(
        jax.jit(fn), auction_rounds, auction_price_frac
    )


# ---- sharded resident state: per-shard retained buffers + delta folds -----


def stack_shard_deltas(
    delta: SnapshotDelta, routed: dict, n_shards: int
) -> SnapshotDelta:
    """Stack per-shard routed deltas (host.snapshot.shard_snapshot_delta)
    into ONE SnapshotDelta whose every leaf carries a leading [D] shard
    axis — the operand layout the shard_map'd appliers consume (each
    shard receives exactly its block, so per-device host->device bytes
    scale with that shard's change, not the cluster).

    Shards that shipped nothing contribute all-sentinel row blocks (the
    row bucket is the max emitted shard's, keeping the stack
    rectangular and the jitted appliers' shapes stable); the node-mask
    plane is ALWAYS the full current mask reshaped [D, n_local] — it is
    cheap, and every shard's slice must be current after the fold,
    exactly like the dense applier's whole-mask refresh."""
    import numpy as np

    mask = np.asarray(delta.node_mask, bool)
    n = mask.shape[0]
    if n_shards <= 0 or n % n_shards:
        raise ValueError(f"node axis {n} does not divide {n_shards} shards")
    n_local = n // n_shards
    r = int(np.asarray(delta.req_vals).shape[1])
    s = int(np.asarray(delta.dom_vals).shape[1])

    def stack(rows_attr: str, vals_attr: str, val_shape: tuple):
        k = max(
            (np.asarray(getattr(d, rows_attr)).shape[0] for d in routed.values()),
            default=8,
        )
        rows = np.full((n_shards, k), n_local, np.int32)
        vals = np.zeros((n_shards, k) + val_shape, np.float32)
        for i, d in routed.items():
            rr = np.asarray(getattr(d, rows_attr))
            rows[i, : rr.shape[0]] = rr
            vals[i, : rr.shape[0]] = np.asarray(getattr(d, vals_attr))
        return rows, vals

    req_rows, req_vals = stack("req_rows", "req_vals", (r,))
    util_rows, util_vals = stack("util_rows", "util_vals", (5,))
    dom_rows, dom_vals = stack("dom_rows", "dom_vals", (s, 4))
    return SnapshotDelta(
        req_rows=req_rows,
        req_vals=req_vals,
        util_rows=util_rows,
        util_vals=util_vals,
        dom_rows=dom_rows,
        dom_vals=dom_vals,
        node_mask=mask.reshape(n_shards, n_local),
    )


def make_sharded_apply_delta_fn(mesh: Mesh, node_axes=NODE_AXIS):
    """shard_map'd donated-buffer SnapshotDelta fold: each shard
    scatters ITS routed row block (stack_shard_deltas layout) into its
    retained snapshot slice via engine._apply_delta_rows — the ONE
    definition the dense apply_snapshot_delta jits, so a shard's fold
    is bitwise the dense fold restricted to its rows. Zero collectives
    (the budget pins it); the snapshot tree is DONATED like the dense
    applier's, so no [n_local, r] matrix crosses host<->device."""
    from kubernetes_scheduler_tpu.engine import _apply_delta_rows

    axes, _, _, snap_specs, _ = _mesh_specs(mesh, node_axes)

    def body(snapshot: SnapshotArrays, delta: SnapshotDelta) -> SnapshotArrays:
        return _apply_delta_rows(snapshot, _local_delta(delta))

    fn = shard_map(
        body, mesh=mesh, in_specs=(snap_specs, _delta_specs(axes)),
        out_specs=snap_specs,
    )
    return jax.jit(fn, donate_argnums=(0,))


def make_sharded_build_layout_fn(mesh: Mesh, node_axes=NODE_AXIS):
    """Per-shard engine.build_fused_layout: each shard preps ITS node
    columns into kernel-layout buffers (FusedLayout leaves sharded on
    their column axis, per-shard TILE padding). u/v are per-node divisor
    expressions (ops/stats.py) — the global mean/variance never enter
    the prep — so the shard-local prep is bitwise the dense prep
    restricted to the shard's columns. Zero collectives; ONE prep per
    full resident upload, after which deltas land straight in layout."""
    from kubernetes_scheduler_tpu.ops.pallas_fused import prep_node_operands
    from kubernetes_scheduler_tpu.ops.stats import (
        CPU_DIVISOR,
        DISK_IO_DIVISOR,
    )

    axes, _, _, snap_specs, _ = _mesh_specs(mesh, node_axes)

    def body(snapshot: SnapshotArrays) -> FusedLayout:
        u = snapshot.disk_io / DISK_IO_DIVISOR
        v = snapshot.cpu_pct / CPU_DIVISOR
        node_ft, alloc_t, reqd_t = prep_node_operands(
            u, v, snapshot.node_mask,
            snapshot.allocatable, snapshot.requested,
        )
        return FusedLayout(node_ft=node_ft, alloc_t=alloc_t, reqd_t=reqd_t)

    fn = shard_map(
        body, mesh=mesh, in_specs=(snap_specs,),
        out_specs=_layout_specs(axes),
    )
    return jax.jit(fn)


def make_sharded_apply_layout_fn(mesh: Mesh, node_axes=NODE_AXIS):
    """shard_map'd donated-buffer kernel-layout fold: the per-shard twin
    of engine.apply_layout_delta, sharing its body
    (engine._apply_layout_rows) so a shard's fold writes the exact
    float32 values the dense fold writes to its columns. Zero
    collectives; the layout tree is DONATED."""
    from kubernetes_scheduler_tpu.engine import _apply_layout_rows

    axes, *_ = _mesh_specs(mesh, node_axes)
    lay = _layout_specs(axes)

    def body(layout: FusedLayout, delta: SnapshotDelta) -> FusedLayout:
        return _apply_layout_rows(layout, _local_delta(delta))

    fn = shard_map(
        body, mesh=mesh, in_specs=(lay, _delta_specs(axes)), out_specs=lay,
    )
    return jax.jit(fn, donate_argnums=(0,))


def sharded_device_count(n_devices: int | None = None) -> int:
    """The automatic ShardedEngine mesh size: the largest divisor of 8
    that the visible device count covers. The host pads node buckets to
    multiples of 8 (utils/padding.bucket_size), so any mesh size in
    {8, 4, 2, 1} divides every snapshot's node axis — a 6-device host
    runs a 4-shard mesh rather than failing the divisibility check
    every cycle."""
    have = len(jax.devices()) if n_devices is None else n_devices
    for d in (8, 4, 2):
        if d <= have:
            return d
    return 1


class _ShardedResident(ResidentState):
    """ResidentState whose snapshot/layout leaves are mesh-sharded jax
    arrays, plus: the host-side node-mask copy the delta router needs
    (a shard whose mask slice changed must receive a delta even when
    none of its rows moved), and the DEVICE-resident [D, n_local] mask
    plane the stacked deltas reuse — the mask is invariant across delta
    cycles (any real mask change is static churn and flushes to full),
    so re-shipping n bytes of it every delta would make per-cycle
    host->device bytes grow with the cluster; the retained plane costs
    zero transfer and is rebuilt on the rare belt-and-braces mask edit."""

    __slots__ = ("node_mask_host", "mask_plane")

    def __init__(self, snapshot, epoch: int, node_mask_host, mask_plane):
        super().__init__(snapshot, epoch)
        self.node_mask_host = node_mask_host
        self.mask_plane = mask_plane


class ShardedEngine:
    """In-process mesh-sharded engine with LocalEngine's call surface.

    The production form of the sharded factories above: the host
    scheduler swaps it in behind config.sharded_engine and every
    dispatch runs the scheduling cycle shard-local with the budgeted
    collectives — the snapshot's node axis sharded over the mesh, pods
    replicated. Resident state (config.resident_state) is PER-SHARD:
    one full upload builds each shard's retained snapshot slice (and,
    on fused paths, its kernel-layout FusedLayout slice); later cycles
    route each SnapshotDelta to the shards that own its rows
    (host.snapshot.shard_snapshot_delta), so per-cycle host->device
    bytes scale with the change — flat as the cluster grows — and the
    donated shard_map'd appliers fold them in place.

    Not served here: gang masking (the host's all-or-nothing backstop
    — ops.gang.mask_partial_gangs_np, test-pinned bitwise-equal to the
    device op — re-masks every reply, so supports_gangs() is False and
    decisions still match the dense engine), device preemption (the
    host falls back to in-host evaluation), and the fused min-max
    epilogue (the sharded min-max bounds are global pmax/pmin values a
    shard-local epilogue cannot see — supports_fused_min_max() is
    False, so min_max configurations ride the unfused sharded path
    with globally-reduced bounds, bitwise the dense normalize)."""

    def __init__(self, mesh: Mesh | None = None, *, node_axes=NODE_AXIS):
        from jax.sharding import NamedSharding

        self.mesh = mesh if mesh is not None else make_mesh(
            sharded_device_count()
        )
        self.node_axes = node_axes
        axes = node_axes if isinstance(node_axes, tuple) else (node_axes,)
        self._node_sharding = NamedSharding(self.mesh, P(axes))
        node = self._node_sharding
        self._snap_shardings = SnapshotArrays(
            **{f: node for f in SnapshotArrays._fields}
        )
        # built-on-demand programs keyed by their static knobs, and the
        # lazily-built apply/build companions (one per engine, like the
        # jit caches they wrap)
        self._fns: dict = {}
        self._apply_fn = None
        self._build_layout_fn = None
        self._apply_layout_fn = None
        self._resident: _ShardedResident | None = None
        # mirrors LocalEngine.resident_used_delta: which path served the
        # LAST resident call; the host reads it after forcing the result
        self.resident_used_delta = False
        # per-shard routed SnapshotDelta payload bytes of the last delta
        # cycle (empty tuple on full uploads) — the host folds it into
        # CycleMetrics.shard_delta_bytes for the {shard}-labeled counter
        self.shard_delta_bytes: tuple = ()

    # ---- capability surface -------------------------------------------

    @property
    def n_shards(self) -> int:
        return int(self.mesh.size)

    def supports_resident(self) -> bool:
        return True

    def supports_windows_resident(self) -> bool:
        return True

    def supports_gangs(self) -> bool:
        # raw placements come back; the host backstop re-masks (bitwise-
        # equal to the device op) and the recorder journals the masked
        # vector (Scheduler._trace_node_idx)
        return False

    def supports_fused_min_max(self) -> bool:
        return False

    def healthy(self) -> bool:
        return True

    def close(self) -> None:
        pass

    # ---- program cache ------------------------------------------------

    def _check_divisible(self, snapshot) -> None:
        n = int(snapshot.node_mask.shape[0])
        if n % self.n_shards:
            raise ValueError(
                f"node axis {n} is not divisible by the {self.n_shards}-"
                "shard mesh (host node buckets are multiples of 8, so "
                "this means a hand-built snapshot bypassed the builder)"
            )

    @staticmethod
    def _knobs(kw: dict) -> dict:
        return {
            k: kw[k]
            for k in ("auction_rounds", "auction_price_frac")
            if k in kw
        }

    def _program(self, kind: str, kw: dict, *, resident_layout=False):
        """The jitted sharded program for this call's static options.
        `affinity_aware` is absorbed: the sharded assigners ALWAYS
        evaluate (anti)affinity dynamically against live counts, which
        is exact in both of the dense path's modes (the host only
        passes False when static counts are provably equivalent)."""
        key = (
            kind,
            kw.get("policy", "balanced_cpu_diskio"),
            kw.get("assigner", "greedy" if kind == "schedule" else "auction"),
            kw.get("normalizer", "min_max" if kind == "schedule" else "none"),
            bool(kw.get("soft", False)),
            bool(kw.get("fused", False)),
            kw.get("score_plugins") or None,
            resident_layout,
        )
        fn = self._fns.get(key)
        if fn is None:
            build = dict(
                policy=key[1], assigner=key[2], normalizer=key[3],
                soft=key[4], fused=key[5], node_axes=self.node_axes,
            )
            if key[6]:
                build["score_plugins"] = key[6]
            if kind == "schedule":
                if resident_layout:
                    build["resident_layout"] = True
                fn = make_sharded_schedule_fn(self.mesh, **build)
            else:
                fn = make_sharded_windows_fn(self.mesh, **build)
            self._fns[key] = fn
        return fn

    def _apply(self):
        if self._apply_fn is None:
            self._apply_fn = make_sharded_apply_delta_fn(
                self.mesh, self.node_axes
            )
        return self._apply_fn

    def _build_layout(self):
        if self._build_layout_fn is None:
            self._build_layout_fn = make_sharded_build_layout_fn(
                self.mesh, self.node_axes
            )
        return self._build_layout_fn

    def _apply_layout(self):
        if self._apply_layout_fn is None:
            self._apply_layout_fn = make_sharded_apply_layout_fn(
                self.mesh, self.node_axes
            )
        return self._apply_layout_fn

    # ---- plain (non-resident) dispatch --------------------------------

    def schedule_batch(self, snapshot, pods, **kw) -> ScheduleResult:
        self._check_divisible(snapshot)
        return self._program("schedule", kw)(
            snapshot, pods, **self._knobs(kw)
        )

    def schedule_batch_async(self, snapshot, pods, **kw):
        from kubernetes_scheduler_tpu.engine import PendingSchedule

        return PendingSchedule(self.schedule_batch(snapshot, pods, **kw))

    def schedule_windows(self, snapshot, pods_windows, **kw):
        self._check_divisible(snapshot)
        return self._program("windows", kw)(
            snapshot, pods_windows, **self._knobs(kw)
        )

    # ---- resident cluster state (per-shard delta uploads) -------------

    def invalidate_resident(self) -> None:
        self._resident = None

    def _fold_delta(self, st: _ShardedResident, delta, epoch: int) -> None:
        """Route + apply one accepted delta: per-shard row deltas on the
        host, donated shard_map folds on device, the layout twin kept in
        lockstep when built. The donated trees are dead after each call
        — rebind before anything can read them (LocalEngine's rule)."""
        import numpy as np

        from kubernetes_scheduler_tpu.engine import snapshot_nbytes
        from kubernetes_scheduler_tpu.host.snapshot import (
            shard_snapshot_delta,
        )

        routed = shard_snapshot_delta(
            delta, self.n_shards, prev_node_mask=st.node_mask_host
        )
        new_mask = np.array(np.asarray(delta.node_mask), bool)
        mask_changed = not np.array_equal(st.node_mask_host, new_mask)
        if mask_changed:
            # belt-and-braces: a mask edit that somehow escaped the
            # static-churn flush rebuilds the device plane (n bytes,
            # rare); steady-state delta cycles reuse the retained plane
            # and ship ZERO mask bytes
            st.mask_plane = jax.device_put(
                new_mask.reshape(self.n_shards, -1), self._node_sharding
            )
        stacked = stack_shard_deltas(
            delta, routed, self.n_shards
        )._replace(node_mask=st.mask_plane)
        st.snapshot = self._apply()(st.snapshot, stacked)
        if st.layout is not None:
            st.layout = self._apply_layout()(st.layout, stacked)
        st.epoch = epoch
        st.node_mask_host = new_mask
        # per-shard transfer accounting: row planes always ship; the
        # mask slice only on the rare rebuild
        self.shard_delta_bytes = tuple(
            (
                snapshot_nbytes(routed[i])
                - (0 if mask_changed else routed[i].node_mask.nbytes)
            )
            if i in routed
            else 0
            for i in range(self.n_shards)
        )
        self.resident_used_delta = True

    def _upload_full(self, snapshot, epoch: int) -> _ShardedResident:
        import numpy as np

        self._check_divisible(snapshot)
        # full upload into PRIVATE per-shard buffers: leaves are forced
        # through host numpy first — jax.device_put of an already-
        # device-backed array with a matching sharding is an identity
        # (no copy), and the donated appliers would then delete the
        # CALLER's buffers on the next delta fold. The host builder
        # hands numpy anyway, so the force is free on the real path.
        # graftlint: disable=host-sync -- deliberate one-time materialization; full uploads ship the whole snapshot by definition
        snapshot = type(snapshot)(*[np.asarray(a) for a in snapshot])
        mask = np.array(snapshot.node_mask, bool)
        st = _ShardedResident(
            jax.device_put(snapshot, self._snap_shardings),
            epoch,
            mask,
            jax.device_put(
                mask.reshape(self.n_shards, -1), self._node_sharding
            ),
        )
        self._resident = st
        self.resident_used_delta = False
        return st

    def _resident_dispatch(self, snapshot, delta, epoch):
        """Shared single-window/backlog resident front half: fold the
        delta into the retained per-shard state or flush to a full
        upload, mirroring LocalEngine.schedule_resident's degrade
        semantics (any mismatch costs a full upload, never the cycle)."""
        st = self._resident
        self.shard_delta_bytes = ()
        if delta is not None and st is not None and st.accepts(delta, epoch):
            self._fold_delta(st, delta, epoch)
            return st
        return self._upload_full(snapshot, epoch)

    def schedule_resident(
        self, snapshot, pods, *, delta=None, epoch=0, **kw
    ) -> ScheduleResult:
        st = self._resident_dispatch(snapshot, delta, epoch)
        if kw.get("fused"):
            if st.layout is None:
                st.layout = self._build_layout()(st.snapshot)
            return self._program("schedule", kw, resident_layout=True)(
                st.snapshot, pods, st.layout, **self._knobs(kw)
            )
        return self._program("schedule", kw)(
            st.snapshot, pods, **self._knobs(kw)
        )

    def schedule_resident_async(
        self, snapshot, pods, *, delta=None, epoch=0, **kw
    ):
        from kubernetes_scheduler_tpu.engine import PendingSchedule

        return PendingSchedule(
            self.schedule_resident(
                snapshot, pods, delta=delta, epoch=epoch, **kw
            )
        )

    def schedule_windows_resident(
        self, snapshot, pods_windows, *, delta=None, epoch=0, **kw
    ):
        """Multi-window twin on the same per-shard epoch sequence. The
        sharded windows scan re-preps its kernel operands per window
        (its capacity carry is per-shard and cheap at n_local columns);
        the retained layout is still delta-folded so interleaved
        single-window fused cycles stay current."""
        st = self._resident_dispatch(snapshot, delta, epoch)
        return self._program("windows", kw)(
            st.snapshot, pods_windows, **self._knobs(kw)
        )
