"""Device-mesh construction.

One logical axis for the scheduler: `node` — the cluster-node dimension is
sharded across chips (ICI within a slice; DCN only if a snapshot ever spans
hosts). Built here so every component agrees on axis names.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

NODE_AXIS = "node"


def make_mesh(n_devices: int | None = None, *, axis: str = NODE_AXIS) -> Mesh:
    """1-D mesh over the first `n_devices` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))
