"""Device-mesh construction.

One logical axis for the scheduler: `node` — the cluster-node dimension is
sharded across chips (ICI within a slice; DCN only if a snapshot ever spans
hosts). Built here so every component agrees on axis names.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

NODE_AXIS = "node"
# Outer (cross-host) mesh axis for slices spanning hosts: collectives over
# (DCN_AXIS, NODE_AXIS) are lowered hierarchically by XLA — reductions ride
# ICI within a host first, then the small cross-host residual rides DCN.
DCN_AXIS = "dcn"


def make_mesh(n_devices: int | None = None, *, axis: str = NODE_AXIS) -> Mesh:
    """1-D mesh over the first `n_devices` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_mesh_multihost(
    n_hosts: int,
    devices_per_host: int | None = None,
    *,
    outer_axis: str = DCN_AXIS,
    axis: str = NODE_AXIS,
) -> Mesh:
    """2-D (hosts, devices-per-host) mesh for slices spanning hosts.

    The cluster-node dimension shards over the PRODUCT of both axes
    (PartitionSpec((outer_axis, axis))) — the sharded engine takes
    node_axes=(outer_axis, axis) and every psum/pmax/all_gather runs over
    the combined axis, hierarchically (ICI inner, DCN outer). Device order
    follows jax.devices(), which groups by host, so the inner axis is
    intra-host ICI as long as devices_per_host divides the per-host device
    count."""
    devs = jax.devices()
    if devices_per_host is None:
        devices_per_host = len(devs) // n_hosts
    need = n_hosts * devices_per_host
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(
        np.asarray(devs[:need]).reshape(n_hosts, devices_per_host),
        (outer_axis, axis),
    )
