"""Mesh construction and the node-axis-sharded scheduling engine.

The "parallelism" of a batch scheduler is the node axis (10k+) and the
pending-pod axis (5k+): nodes shard across TPU chips over ICI, pods stay
replicated, and the per-cycle reductions (utilization mean/variance, score
bounds, global argmax during assignment) become XLA collectives. This is
the structural cousin of sequence parallelism in an ML framework — a long
sharded axis with cheap elementwise math and a few collective reductions —
without any O(N^2) attention term (SURVEY.md §2, §5).
"""

from kubernetes_scheduler_tpu.parallel.mesh import NODE_AXIS, make_mesh
from kubernetes_scheduler_tpu.parallel.engine import (
    ShardedEngine,
    make_sharded_apply_delta_fn,
    make_sharded_apply_layout_fn,
    make_sharded_build_layout_fn,
    make_sharded_schedule_fn,
    make_sharded_windows_fn,
    sharded_device_count,
    stack_shard_deltas,
)
