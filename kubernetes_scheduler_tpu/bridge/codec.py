"""Tensor (de)serialization for the host <-> sidecar bridge.

Packs the engine's NamedTuples (SnapshotArrays / PodBatch /
ScheduleResult) into `NamedTensors` protobuf maps of raw C-order bytes —
the TPU-era analog of the reference shipping per-node scalars through
Redis keys (pkg/yoda/score/algorithm.go:74-88): one dense transfer per
cycle instead of O(N) round-trips.
"""

from __future__ import annotations

import numpy as np

from kubernetes_scheduler_tpu.bridge import schedule_pb2 as pb

_ALLOWED_DTYPES = {"float32", "float64", "int32", "int64", "bool", "uint8"}


def pack_array(a) -> pb.Tensor:
    arr = np.asarray(a)
    shape = arr.shape  # before ascontiguousarray, which promotes 0-d to 1-d
    arr = np.ascontiguousarray(arr)
    name = "bool" if arr.dtype == np.bool_ else arr.dtype.name
    if name not in _ALLOWED_DTYPES:
        raise TypeError(f"unsupported dtype {arr.dtype} for bridge tensor")
    return pb.Tensor(dtype=name, shape=list(shape), data=arr.tobytes())


def unpack_array(t: pb.Tensor) -> np.ndarray:
    if t.dtype not in _ALLOWED_DTYPES:
        raise TypeError(f"unsupported dtype {t.dtype!r} on the wire")
    dtype = np.bool_ if t.dtype == "bool" else np.dtype(t.dtype)
    arr = np.frombuffer(t.data, dtype=dtype)
    shape = tuple(t.shape)
    expect = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if arr.size != expect:
        raise ValueError(
            f"tensor payload has {arr.size} elements, shape {shape} needs {expect}"
        )
    return arr.reshape(shape)


def pack_fields(nt, out: pb.NamedTensors, *, only=None) -> pb.NamedTensors:
    """Pack a NamedTuple of arrays field-by-field into a NamedTensors map."""
    for name, value in zip(type(nt)._fields, nt):
        if only is not None and name not in only:
            continue
        out.tensors[name].CopyFrom(pack_array(value))
    return out


def unpack_fields(cls, named: pb.NamedTensors, *, defaults: dict | None = None):
    """Rebuild NamedTuple `cls` from a NamedTensors map.

    Missing fields fall back to `defaults` (used for decisions_only
    replies); unknown wire fields are rejected so schema drift fails loud.
    """
    fields = cls._fields
    unknown = set(named.tensors) - set(fields)
    if unknown:
        raise ValueError(f"unknown {cls.__name__} fields on the wire: {sorted(unknown)}")
    kwargs = {}
    for name in fields:
        if name in named.tensors:
            kwargs[name] = unpack_array(named.tensors[name])
        elif defaults is not None and name in defaults:
            kwargs[name] = defaults[name]
        else:
            raise ValueError(f"missing {cls.__name__} field {name!r} on the wire")
    return cls(**kwargs)
