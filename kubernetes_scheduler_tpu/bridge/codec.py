"""Tensor (de)serialization for the host <-> sidecar bridge.

Packs the engine's NamedTuples (SnapshotArrays / PodBatch /
ScheduleResult) into `NamedTensors` protobuf maps of raw C-order bytes —
the TPU-era analog of the reference shipping per-node scalars through
Redis keys (pkg/yoda/score/algorithm.go:74-88): one dense transfer per
cycle instead of O(N) round-trips.
"""

from __future__ import annotations

import numpy as np

from kubernetes_scheduler_tpu.bridge import schedule_pb2 as pb

_ALLOWED_DTYPES = {"float32", "float64", "int32", "int64", "bool", "uint8"}


def pack_array(a) -> pb.Tensor:
    arr = np.asarray(a)
    shape = arr.shape  # before ascontiguousarray, which promotes 0-d to 1-d
    arr = np.ascontiguousarray(arr)
    name = "bool" if arr.dtype == np.bool_ else arr.dtype.name
    if name not in _ALLOWED_DTYPES:
        raise TypeError(f"unsupported dtype {arr.dtype} for bridge tensor")
    return pb.Tensor(dtype=name, shape=list(shape), data=arr.tobytes())


def unpack_array(t: pb.Tensor) -> np.ndarray:
    if t.dtype not in _ALLOWED_DTYPES:
        raise TypeError(f"unsupported dtype {t.dtype!r} on the wire")
    dtype = np.bool_ if t.dtype == "bool" else np.dtype(t.dtype)
    arr = np.frombuffer(t.data, dtype=dtype)
    shape = tuple(t.shape)
    expect = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if arr.size != expect:
        raise ValueError(
            f"tensor payload has {arr.size} elements, shape {shape} needs {expect}"
        )
    return arr.reshape(shape)


def pack_fields(
    nt, out: pb.NamedTensors, *, only=None, cache: dict | None = None
) -> pb.NamedTensors:
    """Pack a NamedTuple of arrays field-by-field into a NamedTensors map.

    With `cache` (the client side of the wire field cache — a plain
    {field: ndarray} of this session's previously sent values), a leaf
    bytewise-identical to its predecessor is replaced by a
    `same_as_last` marker instead of its payload — most snapshot leaves
    (allocatable, labels, taints, masks, utilization series) are
    identical cycle after cycle. The caller owns the protocol
    preconditions: the sidecar advertised HealthReply.field_cache and
    the request carries the session_id the cache is scoped to."""
    for name, value in zip(type(nt)._fields, nt):
        if only is not None and name not in only:
            continue
        if cache is not None:
            arr = np.ascontiguousarray(np.asarray(value))
            prev = cache.get(name)
            if (
                prev is not None
                and prev.dtype == arr.dtype
                and prev.shape == arr.shape
                and np.array_equal(prev, arr)
            ):
                out.tensors[name].same_as_last = True
                continue
            # own copy: the comparison must never read a buffer the
            # caller mutates after the send
            cache[name] = arr.copy()
        out.tensors[name].CopyFrom(pack_array(value))
    return out


class FieldCacheMiss(KeyError):
    """A same_as_last tensor referenced a field this server has no
    cached value for (sidecar restart, evicted session, skewed client)."""


def unpack_fields(
    cls,
    named: pb.NamedTensors,
    *,
    defaults: dict | None = None,
    cache: dict | None = None,
):
    """Rebuild NamedTuple `cls` from a NamedTensors map.

    Missing fields fall back to `defaults` (used for decisions_only
    replies, and for struct leaves newer than the sending client — e.g.
    the gang tensors an old host never ships); a CALLABLE default is
    invoked with the kwargs decoded so far, so it can shape itself from
    earlier fields. Unknown wire fields are rejected so schema drift
    fails loud.

    With `cache` (the server side of the wire field cache), a
    `same_as_last` tensor resolves to the session's previously received
    value — raising FieldCacheMiss when there is none (the handler
    aborts FAILED_PRECONDITION "field-cache-miss" and the client resends
    in full) — and every full tensor refreshes its cache slot.
    """
    fields = cls._fields
    unknown = set(named.tensors) - set(fields)
    if unknown:
        raise ValueError(f"unknown {cls.__name__} fields on the wire: {sorted(unknown)}")
    kwargs = {}
    for name in fields:
        if name in named.tensors:
            t = named.tensors[name]
            if t.same_as_last:
                if cache is None or name not in cache:
                    raise FieldCacheMiss(
                        f"field-cache-miss: {cls.__name__}.{name}"
                    )
                kwargs[name] = cache[name]
            else:
                arr = unpack_array(t)
                if cache is not None:
                    cache[name] = arr
                kwargs[name] = arr
        elif defaults is not None and name in defaults:
            d = defaults[name]
            kwargs[name] = d(kwargs) if callable(d) else d
        else:
            raise ValueError(f"missing {cls.__name__} field {name!r} on the wire")
    return cls(**kwargs)
