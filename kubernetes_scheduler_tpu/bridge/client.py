"""Host-side client for the engine sidecar.

`RemoteEngine` exposes the same call surface as the in-process engine
(`schedule_batch(snapshot, pods, policy=..., ...) -> ScheduleResult`), so
host/scheduler.py can swap between LocalEngine and RemoteEngine behind
the TPUBatchScore feature gate. Deadline + bounded retry + health check
implement the failure-detection contract of SURVEY.md §5: an unreachable
sidecar raises EngineUnavailable and the scheduler's cycle falls back to
the scalar path instead of stalling.
"""

from __future__ import annotations

import logging
import time
import uuid

import grpc
import numpy as np

from kubernetes_scheduler_tpu import engine
from kubernetes_scheduler_tpu.bridge import codec
from kubernetes_scheduler_tpu.bridge import schedule_pb2 as pb
from kubernetes_scheduler_tpu.bridge.server import MAX_MESSAGE_BYTES, SERVICE

log = logging.getLogger("yoda_tpu.bridge.client")

_RETRYABLE = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)


class EngineUnavailable(RuntimeError):
    """The sidecar could not serve the cycle (after retries)."""


class _FutureSchedule:
    """RemoteEngine's in-flight ScheduleBatch handle: the whole RPC
    (pack, send, server compute, unpack) runs on the client's dedicated
    worker thread so the pipelined host overlaps it with next-cycle
    host work. Same one-method surface as engine.PendingSchedule."""

    __slots__ = ("_future",)

    def __init__(self, future):
        self._future = future

    def result(self):
        return self._future.result()


LocalEngine = engine.LocalEngine  # re-export; defined grpc-free in engine.py


class RemoteEngine:
    def __init__(
        self,
        target: str,
        *,
        deadline_seconds: float = 30.0,
        retries: int = 1,
        decisions_only: bool = False,
    ):
        self.target = target
        self.deadline_seconds = deadline_seconds
        self.retries = retries
        self.decisions_only = decisions_only
        self._channel = grpc.insecure_channel(
            target,
            options=[
                ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
                ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
            ],
        )
        self._schedule = self._channel.unary_unary(
            f"/{SERVICE}/ScheduleBatch",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleReply.FromString,
        )
        self._schedule_windows = self._channel.unary_unary(
            f"/{SERVICE}/ScheduleWindows",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleReply.FromString,
        )
        self._preempt = self._channel.unary_unary(
            f"/{SERVICE}/Preempt",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleReply.FromString,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthReply.FromString,
        )
        self.last_engine_seconds = 0.0
        # wire field cache (Tensor.same_as_last): most snapshot leaves
        # are bytewise identical cycle after cycle — after the sidecar
        # advertises HealthReply.field_cache, unchanged leaves ride the
        # wire as one-bit markers. Keyed per (rpc, map) so batch and
        # windows shapes never flap each other's slots.
        self._session_id = uuid.uuid4().hex
        self._wire_cache: dict[str, dict] = {}
        self._field_cache_ok: bool | None = None
        # lazy single-worker pool for schedule_batch_async: ONE worker
        # because the wire field cache and capability latch are mutated
        # per call, and the pipelined host forces result() before the
        # next dispatch — at most one RPC is ever in flight per client
        self._async_pool = None

    def _field_cache_enabled(self) -> bool:
        """Resolve the sidecar's field-cache capability ONCE per client
        (older sidecars would read a marker as a malformed empty
        tensor). Called once per schedule call, never inside the
        per-map packing — a down sidecar must not add health-probe
        latency twice per cycle on the outage path."""
        if self._field_cache_ok is None:
            info = self.health_info()
            # only a positive health reply resolves it; an unreachable
            # sidecar stays unknown and is probed again next call
            if info is not None:
                self._field_cache_ok = bool(info.field_cache)
        return bool(self._field_cache_ok)

    def _cache_for(self, key: str, enabled: bool):
        if not enabled:
            return None
        return self._wire_cache.setdefault(key, {})

    def _call_cached(self, method, build_request):
        """Send with field-cache recovery: on FAILED_PRECONDITION
        "field-cache-miss" (sidecar restart / session eviction), clear
        the local cache and resend ONE full request. Any OTHER failure
        also clears the cache — packing commits values the server may
        never have processed, and a desynced cache would silently
        resolve later markers to stale server-side tensors — AND drops
        the latched capability back to unknown: the sidecar behind this
        target may have been replaced by an older build without
        field_cache support (its INVALID_ARGUMENT on a marker-bearing
        send would otherwise repeat forever), so the next call re-probes
        health instead of trusting a dead sidecar's advertisement. A
        resend that itself fails gets the same treatment — its
        build_request() just repopulated the cache with values the
        server never stored."""
        try:
            return self._call_with_retry(method, build_request())
        except EngineUnavailable as e:
            cause = e.__cause__
            if (
                isinstance(cause, grpc.RpcError)
                and cause.code() == grpc.StatusCode.FAILED_PRECONDITION
                and "field-cache-miss" in (cause.details() or "")
            ):
                log.warning(
                    "sidecar %s lost the wire field cache (restart?); "
                    "resending in full", self.target,
                )
                self._wire_cache.clear()
                try:
                    return self._call_with_retry(method, build_request())
                except Exception:
                    self._wire_cache.clear()
                    self._field_cache_ok = None
                    raise
            self._wire_cache.clear()
            self._field_cache_ok = None
            raise
        except Exception:
            self._wire_cache.clear()
            self._field_cache_ok = None
            raise

    def schedule_batch(
        self,
        snapshot,
        pods,
        *,
        policy: str = "balanced_cpu_diskio",
        assigner: str = "greedy",
        normalizer: str = "min_max",
        fused: bool = False,
        affinity_aware: bool = True,
        soft: bool = False,
        auction_price_frac: float = 0.0,
        auction_rounds: int = 0,
        score_plugins: tuple | None = None,
    ) -> engine.ScheduleResult:
        request = pb.ScheduleRequest(
            policy=policy,
            assigner=assigner,
            normalizer=normalizer,
            decisions_only=self.decisions_only,
            fused=fused,
            affinity_aware=affinity_aware,
            soft=soft,
            # 0 = sidecar default; nonzero rides the wire so remote
            # engines honor the host's auction config instead of
            # silently degrading to defaults
            auction_price_frac=auction_price_frac,
            auction_rounds=auction_rounds,
        )
        def build_request():
            req = pb.ScheduleRequest()
            req.CopyFrom(request)
            enabled = self._field_cache_enabled()
            snap_cache = self._cache_for("batch:snapshot", enabled)
            pods_cache = self._cache_for("batch:pods", enabled)
            if enabled:
                req.session_id = self._session_id
            codec.pack_fields(snapshot, req.snapshot, cache=snap_cache)
            codec.pack_fields(pods, req.pods, cache=pods_cache)
            return req

        for name, weight in score_plugins or ():
            request.score_plugins.add(name=name, weight=float(weight))
        reply = self._call_cached(self._schedule, build_request)
        return self._unpack_result(reply, snapshot, pods)

    def schedule_batch_async(self, snapshot, pods, **kw) -> _FutureSchedule:
        """Concurrent in-flight ScheduleBatch (the pipelined host loop's
        async surface): submits the full synchronous call — retries,
        field-cache recovery and all — to the dedicated worker thread
        and returns immediately. Errors (EngineUnavailable included)
        surface from `handle.result()`, where the scheduler's existing
        fallback handling catches them."""
        if self._async_pool is None:
            import concurrent.futures

            self._async_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="yoda-bridge-async"
            )
        return _FutureSchedule(
            self._async_pool.submit(self.schedule_batch, snapshot, pods, **kw)
        )

    def schedule_windows(
        self,
        snapshot,
        pods_windows,
        *,
        policy: str = "balanced_cpu_diskio",
        assigner: str = "auction",
        normalizer: str = "none",
        fused: bool = False,
        affinity_aware: bool = True,
        soft: bool = False,
        auction_price_frac: float = 0.0,
        auction_rounds: int = 0,
        score_plugins: tuple | None = None,
    ) -> "engine.WindowsResult":
        """Whole-backlog RPC: pods_windows carries a leading [w, p, ...]
        window axis (engine.stack_windows); one sidecar dispatch
        schedules every window with capacity and (anti)affinity carries
        threaded between them, and the reply is engine.WindowsResult."""
        request = pb.ScheduleRequest(
            policy=policy,
            assigner=assigner,
            normalizer=normalizer,
            fused=fused,
            affinity_aware=affinity_aware,
            soft=soft,
            auction_price_frac=auction_price_frac,
            auction_rounds=auction_rounds,
        )
        def build_request():
            req = pb.ScheduleRequest()
            req.CopyFrom(request)
            enabled = self._field_cache_enabled()
            snap_cache = self._cache_for("windows:snapshot", enabled)
            pods_cache = self._cache_for("windows:pods", enabled)
            if enabled:
                req.session_id = self._session_id
            codec.pack_fields(snapshot, req.snapshot, cache=snap_cache)
            codec.pack_fields(pods_windows, req.pods, cache=pods_cache)
            return req

        for name, weight in score_plugins or ():
            request.score_plugins.add(name=name, weight=float(weight))
        reply = self._call_cached(self._schedule_windows, build_request)
        return codec.unpack_fields(engine.WindowsResult, reply.result)

    def preempt(self, snapshot, pods, victims, *, k_cap: int):
        """Preemption pass on the sidecar (engine.preempt_batch): `pods`
        = this cycle's unschedulable preemptors, `victims` an
        ops.preempt.VictimArrays. Raises NotImplementedError against a
        version-skewed sidecar without the RPC — the host then runs the
        pass in-process (host/scheduler._run_preemption)."""
        from kubernetes_scheduler_tpu.ops.preempt import PreemptResult

        request = pb.ScheduleRequest(preempt_k_cap=k_cap)
        codec.pack_fields(snapshot, request.snapshot)
        codec.pack_fields(pods, request.pods)
        codec.pack_fields(victims, request.victims)
        reply = self._call_with_retry(self._preempt, request)
        return codec.unpack_fields(PreemptResult, reply.result)

    def _call_with_retry(self, method, request):
        last_err = None
        for attempt in range(self.retries + 1):
            try:
                reply = method(request, timeout=self.deadline_seconds)
                self.last_engine_seconds = reply.engine_seconds
                return reply
            except grpc.RpcError as e:
                last_err = e
                if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                    # version-skewed sidecar without this RPC: callers
                    # (host backlog mode) degrade to the per-window
                    # surface rather than treating it as an outage
                    raise NotImplementedError(
                        f"sidecar {self.target} does not serve this RPC"
                    ) from e
                if e.code() not in _RETRYABLE:
                    raise EngineUnavailable(
                        f"sidecar rejected cycle: {e.code().name}: {e.details()}"
                    ) from e
                log.warning(
                    "sidecar %s unavailable (attempt %d/%d): %s",
                    self.target, attempt + 1, self.retries + 1, e.code().name,
                )
                if attempt < self.retries:
                    time.sleep(min(0.1 * 2**attempt, 1.0))
        raise EngineUnavailable(
            f"sidecar {self.target} unreachable after {self.retries + 1} attempts"
        ) from last_err

    def _unpack_result(self, reply, snapshot, pods) -> engine.ScheduleResult:
        p = np.asarray(pods.request).shape[0]
        n = np.asarray(snapshot.allocatable).shape[0]
        # decisions_only replies omit the [p, n] matrices; fill with empties
        defaults = {
            "scores": np.zeros((p, n), np.float32),
            "raw_scores": np.zeros((p, n), np.float32),
            "feasible": np.zeros((p, n), bool),
        }
        return codec.unpack_fields(
            engine.ScheduleResult, reply.result, defaults=defaults
        )

    def healthy(self, *, timeout: float = 2.0) -> bool:
        try:
            reply = self._health(pb.HealthRequest(), timeout=timeout)
            return reply.status == "SERVING"
        except grpc.RpcError:
            return False

    def health_info(self, *, timeout: float = 2.0) -> pb.HealthReply | None:
        try:
            return self._health(pb.HealthRequest(), timeout=timeout)
        except grpc.RpcError:
            return None

    def close(self) -> None:
        if self._async_pool is not None:
            # wait=True: an in-flight RPC owns the channel — closing it
            # under the worker would surface a spurious cycle failure
            self._async_pool.shutdown(wait=True)
            self._async_pool = None
        self._channel.close()
