"""Host-side client for the engine sidecar.

`RemoteEngine` exposes the same call surface as the in-process engine
(`schedule_batch(snapshot, pods, policy=..., ...) -> ScheduleResult`), so
host/scheduler.py can swap between LocalEngine and RemoteEngine behind
the TPUBatchScore feature gate. Deadline + bounded retry + health check
implement the failure-detection contract of SURVEY.md §5: an unreachable
sidecar raises EngineUnavailable and the scheduler's cycle falls back to
the scalar path instead of stalling.
"""

from __future__ import annotations

import logging
import time
import uuid

import grpc
import numpy as np

from kubernetes_scheduler_tpu import engine
from kubernetes_scheduler_tpu.bridge import codec
from kubernetes_scheduler_tpu.bridge import schedule_pb2 as pb
from kubernetes_scheduler_tpu.bridge.server import MAX_MESSAGE_BYTES, SERVICE
from kubernetes_scheduler_tpu.host.observe import Counter
from kubernetes_scheduler_tpu.host.resilience import (
    BackoffPolicy,
    CircuitBreaker,
)

log = logging.getLogger("yoda_tpu.bridge.client")

_RETRYABLE = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)


class EngineUnavailable(RuntimeError):
    """The sidecar could not serve the cycle (after retries)."""


def _noop_state(state) -> None:
    """Connectivity-subscription callback for _kick_reconnect (module
    level so subscribe/unsubscribe always see the same object)."""


# gang co-scheduling tensors (ops/gang.py), stripped off the wire when
# the sidecar does not advertise HealthReply.gang_scheduling: an old
# build's strict unpack rejects unknown PodBatch fields, so sending them
# would error every cycle into the scalar fallback. The host's
# _resolve_gangs backstop then enforces all-or-nothing host-side.
_GANG_FIELDS = ("gang_id", "gang_size")
_PODS_SANS_GANGS = frozenset(engine.PodBatch._fields) - set(_GANG_FIELDS)

# HealthReply capability bit -> the RemoteEngine latch attribute holding
# it. THE canonical table: _probe_capabilities resolves every unresolved
# latch from one Health reply through it, _invalidate_session drops the
# whole set back to unknown through it, and the capability-completeness
# lint family checks it against the .proto both ways — a new HealthReply
# bool that is not wired in here fails lint, and the parametrized
# mid-stream-downgrade regression tests (tests/test_resident.py) pick a
# new entry up for free. The protocol itself (probe fills ALL unresolved
# latches together; any failure invalidates ALL of them together with
# the wire field cache) is model-checked in analysis/model/protocols.py.
CAPABILITY_LATCHES = {
    "field_cache": "_field_cache_ok",
    "resident_state": "_resident_cap",
    "windows_resident": "_windows_resident_cap",
    "gang_scheduling": "_gang_cap",
    "fused_min_max": "_fused_min_max_cap",
}


class _FutureSchedule:
    """RemoteEngine's in-flight ScheduleBatch handle: the whole RPC
    (pack, send, server compute, unpack) runs on the client's dedicated
    worker thread so the pipelined host overlaps it with next-cycle
    host work. Same one-method surface as engine.PendingSchedule."""

    __slots__ = ("_future",)

    def __init__(self, future):
        self._future = future

    def result(self):
        return self._future.result()


LocalEngine = engine.LocalEngine  # re-export; defined grpc-free in engine.py


class RemoteEngine:
    def __init__(
        self,
        target: str,
        *,
        deadline_seconds: float = 30.0,
        retries: int = 1,
        decisions_only: bool = False,
        breaker: CircuitBreaker | None = None,
    ):
        self.target = target
        self.deadline_seconds = deadline_seconds
        self.retries = retries
        self.decisions_only = decisions_only
        # unified resilience (host/resilience.py): the circuit breaker
        # gating EVERY RPC on this client (schedule, preempt, health) —
        # a down sidecar costs one half-open probe per recovery window
        # instead of a deadline timeout per call — and the deterministic-
        # jitter backoff between in-call retries (replacing the old bare
        # min(0.1 * 2**attempt, 1.0) sleep). The breaker is injectable
        # so the host can share one instance across clients of the same
        # sidecar.
        self.breaker = breaker or CircuitBreaker(f"bridge:{target}")
        self._backoff = BackoffPolicy(
            initial=0.1, max_delay=1.0, multiplier=2.0
        )
        # transport-down vs deadline-exceeded health failures, counted
        # SEPARATELY (a saturated-but-alive sidecar and a dead one need
        # different operator responses); "breaker-open" counts probes
        # the breaker answered without touching the wire. Exported via
        # the host exporter (Scheduler folds engine `collectors` into
        # prom_collectors).
        self.ctr_health_failures = Counter(
            "engine_health_failures_total",
            "Sidecar health-probe failures by kind (transport-down vs "
            "deadline-exceeded vs answered-by-open-breaker)",
            labels=("kind",),
        )
        self.collectors = (self.ctr_health_failures,)
        self._channel = grpc.insecure_channel(
            target,
            options=[
                ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
                ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
                # cap the channel's reconnect backoff: grpc's default
                # grows to ~2 minutes, so a client that rode out a
                # sidecar outage could keep failing long AFTER the
                # sidecar recovered — the circuit breaker's half-open
                # probe cadence (seconds) is the recovery clock here,
                # and the transport must not out-wait it
                ("grpc.max_reconnect_backoff_ms", 5000),
            ],
        )
        self._schedule = self._channel.unary_unary(
            f"/{SERVICE}/ScheduleBatch",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleReply.FromString,
        )
        self._schedule_windows = self._channel.unary_unary(
            f"/{SERVICE}/ScheduleWindows",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleReply.FromString,
        )
        self._preempt = self._channel.unary_unary(
            f"/{SERVICE}/Preempt",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleReply.FromString,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthReply.FromString,
        )
        self.last_engine_seconds = 0.0
        # wire field cache (Tensor.same_as_last): most snapshot leaves
        # are bytewise identical cycle after cycle — after the sidecar
        # advertises HealthReply.field_cache, unchanged leaves ride the
        # wire as one-bit markers. Keyed per (rpc, map) so batch and
        # windows shapes never flap each other's slots.
        self._session_id = uuid.uuid4().hex
        self._wire_cache: dict[str, dict] = {}
        self._field_cache_ok: bool | None = None
        # resident-cluster-state capability (HealthReply.resident_state),
        # latched like the field cache and INVALIDATED TOGETHER with it:
        # a mid-stream downgrade (sidecar replaced by an older build)
        # otherwise leaves one latch trusting a dead sidecar's
        # advertisement while the other re-probes
        self._resident_cap: bool | None = None
        # windows-resident capability (HealthReply.windows_resident):
        # resident deltas on the ScheduleWindows RPC — probed, latched,
        # and invalidated together with the other two
        self._windows_resident_cap: bool | None = None
        # gang-scheduling capability (HealthReply.gang_scheduling):
        # whether the sidecar's PodBatch knows the gang tensors — same
        # latch/invalidate discipline as the other capability bits
        self._gang_cap: bool | None = None
        # fused min-max capability (HealthReply.fused_min_max): the
        # sidecar serves the fused megakernel's min-max epilogue AND
        # sits on a backend that profits from it (TPU) — the host's
        # min_max->fused widening keys off this latch; same discipline
        self._fused_min_max_cap: bool | None = None
        # did the LAST schedule_resident call apply a delta server-side?
        # (mirrors LocalEngine.resident_used_delta for the host's
        # delta/full upload metrics)
        self.resident_used_delta = False
        # lazy single-worker pool for schedule_batch_async: ONE worker
        # because the wire field cache and capability latch are mutated
        # per call, and the pipelined host forces result() before the
        # next dispatch — at most one RPC is ever in flight per client
        self._async_pool = None
        # span/profile context, shipped as gRPC METADATA (no message
        # changes): the host cycle's trace id + flight-recorder seq ride
        # every schedule call so the sidecar's spans join the host
        # timeline; a /debug/profile arm forwards on the next call (the
        # sidecar owns the device, so the dump lands on its side)
        self._trace_md: list | None = None
        self._profile_ask = 0

    def set_trace_id(self, trace_id: int, seq: int = -1) -> None:
        """Span context for subsequent calls (mirrors
        LocalEngine.set_trace_id): attached to the wire as metadata keys
        `yoda-trace-id` / `yoda-trace-seq` (bridge/schedule.proto)."""
        self._trace_md = [
            ("yoda-trace-id", str(int(trace_id))),
            ("yoda-trace-seq", str(int(seq))),
        ]

    def arm_profile(self, cycles: int, out_dir: str | None = None) -> dict:
        """Forward a /debug/profile arm to the sidecar over metadata on
        the next schedule call (best effort: a call that never reaches
        the server drops the ask). The dump lands under the sidecar's
        --profile-path — the device lives there."""
        self._profile_ask = int(cycles)
        return {
            "armed": self._profile_ask,
            "forwarded_to": self.target,
            "note": "dump lands under the sidecar's --profile-path",
        }

    def _call_metadata(self, *, profile_ok: bool = True) -> list | None:
        md = list(self._trace_md or ())
        # the ask rides only schedule calls: the Preempt handler never
        # reads the key, and consuming the arm there would lose it
        # silently after /debug/profile already reported it armed
        if profile_ok and self._profile_ask > 0:
            md.append(("yoda-profile-cycles", str(self._profile_ask)))
            self._profile_ask = 0
        return md or None

    def _probe_capabilities(self) -> None:
        """ONE Health RPC resolves BOTH capability latches (field cache
        and resident state): they ride the same reply, and a down
        sidecar must not pay the probe timeout once per latch per cycle
        on the outage path. Only a positive reply resolves them; an
        unreachable sidecar leaves both unknown to be probed again next
        call."""
        info = self.health_info()
        if info is not None:
            # fill only UNRESOLVED latches, and fill every unresolved
            # one from this ONE reply: a latch someone already resolved
            # (or pinned) stays put until _invalidate_session drops the
            # whole set back to unknown together. Table-driven so a new
            # HealthReply bit cannot be probed without also being
            # invalidated (capability-completeness lint + the
            # analysis/model/ protocol model both check this shape).
            # getattr default False: a reply from a build older than
            # the field reads as "capability absent".
            for fieldname, attr in CAPABILITY_LATCHES.items():
                if getattr(self, attr) is None:
                    setattr(
                        self, attr, bool(getattr(info, fieldname, False))
                    )

    def _field_cache_enabled(self) -> bool:
        """Resolve the sidecar's field-cache capability ONCE per client
        (older sidecars would read a marker as a malformed empty
        tensor). Called once per schedule call, never inside the
        per-map packing."""
        if self._field_cache_ok is None:
            self._probe_capabilities()
        return bool(self._field_cache_ok)

    def supports_resident(self) -> bool:
        """Resolve the sidecar's resident-cluster-state capability, once
        per client (re-probed after any failure — see
        _invalidate_session). Clients must never send delta uploads to a
        sidecar that has not advertised HealthReply.resident_state."""
        if self._resident_cap is None:
            self._probe_capabilities()
        return bool(self._resident_cap)

    def supports_windows_resident(self) -> bool:
        """Resolve the sidecar's windows-resident capability (resident
        deltas on the ScheduleWindows backlog RPC) — same latch
        discipline as supports_resident."""
        if self._windows_resident_cap is None:
            self._probe_capabilities()
        return bool(self._windows_resident_cap)

    def supports_gangs(self) -> bool:
        """Resolve the sidecar's gang-scheduling capability
        (HealthReply.gang_scheduling) — same latch discipline. False
        flips every schedule call into degraded mode: the gang tensors
        are stripped off the wire (_PODS_SANS_GANGS) and the host's
        _resolve_gangs backstop enforces all-or-nothing instead of the
        device op, with identical bindings."""
        if self._gang_cap is None:
            self._probe_capabilities()
        return bool(self._gang_cap)

    def supports_fused_min_max(self) -> bool:
        """Resolve the sidecar's fused min-max epilogue capability
        (HealthReply.fused_min_max) — same latch discipline. False
        keeps the host's normalizer="min_max" cycles on the unfused
        path (exactly the pre-widening behavior), so a version-skewed
        or CPU-backed sidecar is never asked for a fused contract it
        would reject or serve slowly."""
        if self._fused_min_max_cap is None:
            self._probe_capabilities()
        return bool(self._fused_min_max_cap)

    def _pods_wire_fields(self) -> frozenset | None:
        """The PodBatch fields to put on the wire: everything, or
        everything minus the gang tensors against a gang-blind sidecar."""
        return None if self.supports_gangs() else _PODS_SANS_GANGS

    def _invalidate_session(self) -> None:
        """Reset everything scoped to the sidecar behind this target:
        the wire field cache AND every capability latch — always
        together, through the one canonical latch table. A failed
        send means the sidecar may have been replaced (restart,
        rollback to an older build): clearing only the field cache
        would leave the other latches trusting the dead sidecar's
        advertisement, so the client would keep shipping deltas/gang
        tensors/fused contracts an older build cannot serve. The next
        call re-probes Health and re-learns the whole set. This
        invalidate-together contract is a checked invariant of the
        analysis/model/ client-session protocol model (and the PR-3
        regression class its mutation harness re-introduces)."""
        self._wire_cache.clear()
        for attr in CAPABILITY_LATCHES.values():
            setattr(self, attr, None)

    def _cache_for(self, key: str, enabled: bool):
        if not enabled:
            return None
        return self._wire_cache.setdefault(key, {})

    def _call_cached(self, method, build_request):
        """Send with field-cache recovery: on FAILED_PRECONDITION
        "field-cache-miss" (sidecar restart / session eviction), clear
        the local cache and resend ONE full request. Any OTHER failure
        also clears the cache — packing commits values the server may
        never have processed, and a desynced cache would silently
        resolve later markers to stale server-side tensors — AND drops
        the latched capability back to unknown: the sidecar behind this
        target may have been replaced by an older build without
        field_cache support (its INVALID_ARGUMENT on a marker-bearing
        send would otherwise repeat forever), so the next call re-probes
        health instead of trusting a dead sidecar's advertisement. A
        resend that itself fails gets the same treatment — its
        build_request() just repopulated the cache with values the
        server never stored."""
        try:
            return self._call_with_retry(method, build_request())
        except EngineUnavailable as e:
            cause = e.__cause__
            if (
                isinstance(cause, grpc.RpcError)
                and cause.code() == grpc.StatusCode.FAILED_PRECONDITION
                and "field-cache-miss" in (cause.details() or "")
            ):
                log.warning(
                    "sidecar %s lost the wire field cache (restart?); "
                    "resending in full", self.target,
                )
                self._wire_cache.clear()
                try:
                    return self._call_with_retry(method, build_request())
                except Exception:
                    self._invalidate_session()
                    raise
            self._invalidate_session()
            raise
        except Exception:
            self._invalidate_session()
            raise

    def _base_request(
        self,
        *,
        policy: str = "balanced_cpu_diskio",
        assigner: str = "greedy",
        normalizer: str = "min_max",
        fused: bool = False,
        affinity_aware: bool = True,
        soft: bool = False,
        auction_price_frac: float = 0.0,
        auction_rounds: int = 0,
        score_plugins: tuple | None = None,
    ) -> pb.ScheduleRequest:
        """The option skeleton shared by ScheduleBatch-shaped calls
        (plain and resident), so the two cannot drift on how cycle
        options ride the wire."""
        request = pb.ScheduleRequest(
            policy=policy,
            assigner=assigner,
            normalizer=normalizer,
            decisions_only=self.decisions_only,
            fused=fused,
            affinity_aware=affinity_aware,
            soft=soft,
            # 0 = sidecar default; nonzero rides the wire so remote
            # engines honor the host's auction config instead of
            # silently degrading to defaults
            auction_price_frac=auction_price_frac,
            auction_rounds=auction_rounds,
        )
        for name, weight in score_plugins or ():
            request.score_plugins.add(name=name, weight=float(weight))
        return request

    def schedule_batch(self, snapshot, pods, **kw) -> engine.ScheduleResult:
        request = self._base_request(**kw)

        def build_request():
            req = pb.ScheduleRequest()
            req.CopyFrom(request)
            enabled = self._field_cache_enabled()
            snap_cache = self._cache_for("batch:snapshot", enabled)
            pods_cache = self._cache_for("batch:pods", enabled)
            if enabled:
                req.session_id = self._session_id
            codec.pack_fields(snapshot, req.snapshot, cache=snap_cache)
            codec.pack_fields(
                pods, req.pods, cache=pods_cache,
                only=self._pods_wire_fields(),
            )
            return req

        reply = self._call_cached(self._schedule, build_request)
        return self._unpack_result(reply, snapshot, pods)

    def schedule_resident(
        self, snapshot, pods, *, delta=None, epoch: int = 0, **kw
    ) -> engine.ScheduleResult:
        """ScheduleBatch against sidecar-resident cluster state:
        `snapshot` is always the full host build (the fallback payload);
        when `delta` is given it ships INSTEAD of the snapshot map and
        the sidecar applies it to the state retained under this client's
        session. An inapplicable delta (sidecar restart, session
        eviction, epoch desync, layout churn) aborts INVALID_ARGUMENT
        "resident-epoch-mismatch" and this method transparently resends
        the full snapshot — the cycle never pays a fallback for it. A
        sidecar that does not advertise the capability is served a plain
        ScheduleBatch."""
        if not self.supports_resident():
            self.resident_used_delta = False
            return self.schedule_batch(snapshot, pods, **kw)
        request = self._base_request(**kw)

        def build_request(with_delta: bool):
            req = pb.ScheduleRequest()
            req.CopyFrom(request)
            enabled = self._field_cache_enabled()
            pods_cache = self._cache_for("batch:pods", enabled)
            # resident state is session-keyed regardless of the field
            # cache: the id always rides resident requests
            req.session_id = self._session_id
            req.resident_epoch = epoch
            if with_delta:
                # the snapshot map stays EMPTY — the sidecar resolves it
                # from its retained state; only the delta crosses the wire
                codec.pack_fields(delta, req.snapshot_delta)
            else:
                req.resident_full = True
                snap_cache = self._cache_for("batch:snapshot", enabled)
                codec.pack_fields(snapshot, req.snapshot, cache=snap_cache)
            codec.pack_fields(
                pods, req.pods, cache=pods_cache,
                only=self._pods_wire_fields(),
            )
            return req

        reply = self._resident_call(
            self._schedule, build_request, delta, "resident"
        )
        return self._unpack_result(reply, snapshot, pods)

    def _resident_call(self, method, build_request, delta, what: str):
        """Delta-first resident send with the transparent full resend on
        INVALID_ARGUMENT "resident-epoch-mismatch" (sidecar restart,
        session eviction, epoch desync, layout churn) — ONE
        implementation of the recovery protocol for both resident
        surfaces (ScheduleBatch and ScheduleWindows), so the
        string-matched detail contract cannot drift between them.
        Leaves `resident_used_delta` reporting which path served the
        call."""
        if delta is not None:
            try:
                reply = self._call_cached(
                    method, lambda: build_request(True)
                )
                self.resident_used_delta = True
                return reply
            except EngineUnavailable as e:
                cause = e.__cause__
                if not (
                    isinstance(cause, grpc.RpcError)
                    and cause.code() == grpc.StatusCode.INVALID_ARGUMENT
                    and "resident-epoch-mismatch" in (cause.details() or "")
                ):
                    raise
                log.warning(
                    "sidecar %s cannot apply the %s delta "
                    "(restart/eviction/churn); resending in full",
                    self.target, what,
                )
        self.resident_used_delta = False
        return self._call_cached(method, lambda: build_request(False))

    def schedule_resident_async(
        self, snapshot, pods, *, delta=None, epoch: int = 0, **kw
    ) -> _FutureSchedule:
        """In-flight twin of schedule_resident on the dedicated worker
        thread (see schedule_batch_async); errors surface from
        handle.result()."""
        if self._async_pool is None:
            import concurrent.futures

            self._async_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="yoda-bridge-async"
            )
        return _FutureSchedule(
            self._async_pool.submit(
                self.schedule_resident, snapshot, pods,
                delta=delta, epoch=epoch, **kw,
            )
        )

    def schedule_batch_async(self, snapshot, pods, **kw) -> _FutureSchedule:
        """Concurrent in-flight ScheduleBatch (the pipelined host loop's
        async surface): submits the full synchronous call — retries,
        field-cache recovery and all — to the dedicated worker thread
        and returns immediately. Errors (EngineUnavailable included)
        surface from `handle.result()`, where the scheduler's existing
        fallback handling catches them."""
        if self._async_pool is None:
            import concurrent.futures

            self._async_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="yoda-bridge-async"
            )
        return _FutureSchedule(
            self._async_pool.submit(self.schedule_batch, snapshot, pods, **kw)
        )

    def schedule_windows(
        self,
        snapshot,
        pods_windows,
        *,
        policy: str = "balanced_cpu_diskio",
        assigner: str = "auction",
        normalizer: str = "none",
        fused: bool = False,
        affinity_aware: bool = True,
        soft: bool = False,
        auction_price_frac: float = 0.0,
        auction_rounds: int = 0,
        score_plugins: tuple | None = None,
    ) -> "engine.WindowsResult":
        """Whole-backlog RPC: pods_windows carries a leading [w, p, ...]
        window axis (engine.stack_windows); one sidecar dispatch
        schedules every window with capacity and (anti)affinity carries
        threaded between them, and the reply is engine.WindowsResult."""
        request = pb.ScheduleRequest(
            policy=policy,
            assigner=assigner,
            normalizer=normalizer,
            fused=fused,
            affinity_aware=affinity_aware,
            soft=soft,
            auction_price_frac=auction_price_frac,
            auction_rounds=auction_rounds,
        )
        def build_request():
            req = pb.ScheduleRequest()
            req.CopyFrom(request)
            enabled = self._field_cache_enabled()
            snap_cache = self._cache_for("windows:snapshot", enabled)
            pods_cache = self._cache_for("windows:pods", enabled)
            if enabled:
                req.session_id = self._session_id
            codec.pack_fields(snapshot, req.snapshot, cache=snap_cache)
            codec.pack_fields(
                pods_windows, req.pods, cache=pods_cache,
                only=self._pods_wire_fields(),
            )
            return req

        for name, weight in score_plugins or ():
            request.score_plugins.add(name=name, weight=float(weight))
        reply = self._call_cached(self._schedule_windows, build_request)
        return codec.unpack_fields(engine.WindowsResult, reply.result)

    def schedule_windows_resident(
        self, snapshot, pods_windows, *, delta=None, epoch: int = 0, **kw
    ) -> "engine.WindowsResult":
        """ScheduleWindows against sidecar-resident cluster state (the
        backlog twin of schedule_resident, same session-retained
        snapshot and epoch sequence). `snapshot` is always the full host
        build; a given `delta` ships instead of the snapshot map, and an
        inapplicable delta (restart, eviction, epoch desync, churn)
        aborts INVALID_ARGUMENT "resident-epoch-mismatch" — this method
        transparently resends the full snapshot. A sidecar without the
        windows_resident capability is served a plain ScheduleWindows."""
        if not self.supports_windows_resident():
            self.resident_used_delta = False
            return self.schedule_windows(snapshot, pods_windows, **kw)
        request = self._base_request(**kw)

        def build_request(with_delta: bool):
            req = pb.ScheduleRequest()
            req.CopyFrom(request)
            enabled = self._field_cache_enabled()
            pods_cache = self._cache_for("windows:pods", enabled)
            req.session_id = self._session_id
            req.resident_epoch = epoch
            if with_delta:
                codec.pack_fields(delta, req.snapshot_delta)
            else:
                req.resident_full = True
                snap_cache = self._cache_for("windows:snapshot", enabled)
                codec.pack_fields(snapshot, req.snapshot, cache=snap_cache)
            codec.pack_fields(
                pods_windows, req.pods, cache=pods_cache,
                only=self._pods_wire_fields(),
            )
            return req

        reply = self._resident_call(
            self._schedule_windows, build_request, delta, "windows-resident"
        )
        return codec.unpack_fields(engine.WindowsResult, reply.result)

    def preempt(self, snapshot, pods, victims, *, k_cap: int):
        """Preemption pass on the sidecar (engine.preempt_batch): `pods`
        = this cycle's unschedulable preemptors, `victims` an
        ops.preempt.VictimArrays. Raises NotImplementedError against a
        version-skewed sidecar without the RPC — the host then runs the
        pass in-process (host/scheduler._run_preemption)."""
        from kubernetes_scheduler_tpu.ops.preempt import PreemptResult

        request = pb.ScheduleRequest(preempt_k_cap=k_cap)
        codec.pack_fields(snapshot, request.snapshot)
        codec.pack_fields(pods, request.pods)
        codec.pack_fields(victims, request.victims)
        try:
            reply = self._call_with_retry(
                self._preempt, request, profile_ok=False
            )
        except EngineUnavailable:
            # same session hygiene as every schedule path: a failed
            # Preempt means the sidecar behind this target may have
            # been replaced, so the latched capabilities and the wire
            # field cache must not outlive it. (Previously the ONE RPC
            # surface that skipped the session invalidation — found by
            # the capability-completeness lint family; a clean
            # UNIMPLEMENTED degrade keeps the session, the sidecar
            # answered.)
            self._invalidate_session()
            raise
        return codec.unpack_fields(PreemptResult, reply.result)

    def _call_with_retry(self, method, request, *, profile_ok: bool = True):
        if not self.breaker.allow():
            # open breaker: fail the cycle in microseconds instead of a
            # deadline timeout — the scheduler's scalar fallback serves
            # it, and ONE half-open probe per recovery window retests
            # the sidecar
            raise EngineUnavailable(
                f"sidecar {self.target} circuit open (one probe per "
                f"{self.breaker.recovery_window_s:g}s window)"
            )
        last_err = None
        metadata = self._call_metadata(profile_ok=profile_ok)
        # the kwarg is attached only when telemetry context exists:
        # metadata-free calls keep the bare (request, timeout) surface
        # (injectable test doubles and old stubs depend on it)
        kw = {"metadata": metadata} if metadata else {}
        for attempt in range(self.retries + 1):
            try:
                reply = method(request, timeout=self.deadline_seconds, **kw)
                self.last_engine_seconds = reply.engine_seconds
                self.breaker.record_success()
                return reply
            except grpc.RpcError as e:
                last_err = e
                if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                    # version-skewed sidecar without this RPC: callers
                    # (host backlog mode) degrade to the per-window
                    # surface rather than treating it as an outage —
                    # the sidecar ANSWERED, so the breaker reads it as
                    # alive
                    self.breaker.record_success()
                    raise NotImplementedError(
                        f"sidecar {self.target} does not serve this RPC"
                    ) from e
                if e.code() not in _RETRYABLE:
                    # an explicit rejection (INVALID_ARGUMENT epoch
                    # mismatch, FAILED_PRECONDITION cache miss) is a
                    # live sidecar speaking — not an outage
                    self.breaker.record_success()
                    raise EngineUnavailable(
                        f"sidecar rejected cycle: {e.code().name}: {e.details()}"
                    ) from e
                log.warning(
                    "sidecar %s unavailable (attempt %d/%d): %s",
                    self.target, attempt + 1, self.retries + 1, e.code().name,
                )
                if e.code() == grpc.StatusCode.UNAVAILABLE:
                    self._kick_reconnect()
                if attempt < self.retries:
                    # deterministic-jitter exponential backoff
                    # (host/resilience.BackoffPolicy): same growth as
                    # the old bare sleep, de-phased across targets
                    time.sleep(
                        self._backoff.delay(attempt, key=self.target)
                    )
        self.breaker.record_failure()
        raise EngineUnavailable(
            f"sidecar {self.target} unreachable after {self.retries + 1} attempts"
        ) from last_err

    def _unpack_result(self, reply, snapshot, pods) -> engine.ScheduleResult:
        p = np.asarray(pods.request).shape[0]
        n = np.asarray(snapshot.allocatable).shape[0]
        # decisions_only replies omit the [p, n] matrices; fill with empties
        defaults = {
            "scores": np.zeros((p, n), np.float32),
            "raw_scores": np.zeros((p, n), np.float32),
            "feasible": np.zeros((p, n), bool),
        }
        return codec.unpack_fields(
            engine.ScheduleResult, reply.result, defaults=defaults
        )

    def _kick_reconnect(self) -> None:
        """Nudge the channel to actually re-dial after a transport
        failure. grpc-python's fail-fast RPCs on a TRANSIENT_FAILURE
        channel return immediately WITHOUT requesting a new connection
        — observed on this grpc build: a client created while the
        sidecar was down keeps failing for minutes after the sidecar
        recovers, while a fresh client connects instantly. A
        try_to_connect subscription (immediately unsubscribed) forces
        the re-dial, so the breaker's half-open probe cadence — not the
        transport's stuck state — is the recovery clock."""
        cb = _noop_state
        try:
            self._channel.subscribe(cb, try_to_connect=True)
            self._channel.unsubscribe(cb)
        except Exception:
            log.debug("reconnect kick failed", exc_info=True)

    def _health_failed(self, e: grpc.RpcError) -> None:
        """Classify one health-probe failure — deadline-exceeded (the
        sidecar exists but could not answer in time: saturation, GC,
        device wedge) vs transport-down (connection refused/reset: the
        process or network is gone) — count them SEPARATELY and feed
        the breaker. Previously both were swallowed identically, so
        dashboards could not tell a saturated sidecar from a dead one
        and the outage never tripped the breaker."""
        kind = (
            "deadline"
            if e.code() == grpc.StatusCode.DEADLINE_EXCEEDED
            else "transport"
        )
        self.ctr_health_failures.inc(kind=kind)
        self.breaker.record_failure()
        if kind == "transport":
            self._kick_reconnect()
        log.debug(
            "sidecar %s health probe failed (%s): %s",
            self.target, kind, e.code().name,
        )

    def healthy(self, *, timeout: float = 2.0) -> bool:
        if not self.breaker.allow():
            self.ctr_health_failures.inc(kind="breaker-open")
            return False
        try:
            reply = self._health(pb.HealthRequest(), timeout=timeout)
        except grpc.RpcError as e:
            self._health_failed(e)
            return False
        self.breaker.record_success()
        return reply.status == "SERVING"

    def health_info(self, *, timeout: float = 2.0) -> pb.HealthReply | None:
        if not self.breaker.allow():
            self.ctr_health_failures.inc(kind="breaker-open")
            return None
        try:
            reply = self._health(pb.HealthRequest(), timeout=timeout)
        except grpc.RpcError as e:
            self._health_failed(e)
            return None
        self.breaker.record_success()
        return reply

    def close(self) -> None:
        if self._async_pool is not None:
            # wait=True: an in-flight RPC owns the channel — closing it
            # under the worker would surface a spurious cycle failure
            self._async_pool.shutdown(wait=True)
            self._async_pool = None
        self._channel.close()
