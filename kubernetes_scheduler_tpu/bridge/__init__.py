"""Host <-> TPU-sidecar gRPC bridge (SURVEY.md §7 step 3).

One RPC per scheduling cycle: ScheduleBatch(matrices) -> bindings.
Replaces the reference's per-score network chatter (5·(N+1) Prometheus
HTTP calls + O(N) Redis round-trips per pod, SURVEY.md §3.2) with a
single dense transfer.
"""

from kubernetes_scheduler_tpu.bridge.client import (
    EngineUnavailable,
    LocalEngine,
    RemoteEngine,
)
from kubernetes_scheduler_tpu.bridge.server import make_server
