"""The TPU engine sidecar: a gRPC server wrapping the batched engine.

This process owns the device. The host scheduler (Python or native) sends
one ScheduleBatch RPC per cycle; the sidecar runs the jitted program and
returns bindings — the stateless, restartable device worker of SURVEY.md
§5 ("sidecar restart = stateless recovery"). The gRPC stubs are
hand-written against the method paths in schedule.proto because this
image ships protoc without grpc_python_plugin.

Run:  python -m kubernetes_scheduler_tpu.bridge.server --port 50051
"""

from __future__ import annotations

import argparse
import logging
import threading
import time
from collections import OrderedDict
from concurrent import futures

import grpc
import jax
import numpy as np

from kubernetes_scheduler_tpu import engine
from kubernetes_scheduler_tpu.bridge import codec
from kubernetes_scheduler_tpu.bridge import schedule_pb2 as pb
from kubernetes_scheduler_tpu.ops.gang import GANG_MASKED_BASE

log = logging.getLogger("yoda_tpu.bridge.server")

SERVICE = "yodatpu.Engine"
_DECISION_FIELDS = ("node_idx", "free_after", "n_assigned")
# wire field cache: per-session last-value tensors (Tensor.same_as_last).
# One deep-backlog session is ~a few MB; 8 sessions bound the sidecar's
# exposure to clients that churn session ids.
_MAX_CACHE_SESSIONS = 8


def _auction_kw(request: pb.ScheduleRequest) -> dict:
    """Auction knobs from the wire; proto3 zero means "engine default"."""
    kw = {}
    if request.auction_price_frac > 0:
        kw["auction_price_frac"] = request.auction_price_frac
    if request.auction_rounds > 0:
        kw["auction_rounds"] = int(request.auction_rounds)
    return kw


def _score_plugins(request: pb.ScheduleRequest) -> tuple | None:
    """Weighted multi-plugin config from the wire (None = single-policy);
    proto3 zero weight means 1."""
    if not request.score_plugins:
        return None
    return tuple(
        (e.name, e.weight if e.weight else 1.0) for e in request.score_plugins
    )


# PodBatch leaves newer than an old client's wire schema, backfilled
# with their neutral defaults (codec.unpack_fields callable defaults):
# gang_id=-1 / gang_size=0 is exactly "no gangs", and the gang mask is
# bitwise the identity then. Shapes derive from the request tensor so
# one table serves both [p, r] batch and [w, p, r] windows requests.
_POD_WIRE_DEFAULTS = {
    "gang_id": lambda kw: np.full(kw["request"].shape[:-1], -1, np.int32),
    "gang_size": lambda kw: np.zeros(kw["request"].shape[:-1], np.int32),
}

# Matrices are ~P*N*4 bytes; 10k nodes x 4k pods of f32 scores is ~160 MB.
MAX_MESSAGE_BYTES = 512 * 1024 * 1024

# HealthReply capability bit -> the EngineService switch attribute that
# answers it. THE canonical server-side table (the twin of
# bridge/client.CAPABILITY_LATCHES): health() renders every advertised
# capability through it, tests/canaries flip individual switches to
# impersonate older builds, and the capability-completeness lint family
# checks it against the .proto both ways — a new HealthReply bool that
# is not wired in here fails lint.
CAPABILITY_SWITCHES = {
    "field_cache": "field_cache_enabled",
    "resident_state": "resident_enabled",
    "windows_resident": "windows_resident_enabled",
    "gang_scheduling": "gang_enabled",
    "fused_min_max": "fused_min_max_enabled",
}


class EngineService:
    """Unary handlers for the two RPCs. A single worker thread serializes
    device access (the batched design needs no cross-request locking —
    contrast the reference's RWMutex around Score, scheduler.go:147-149)."""

    def __init__(
        self,
        *,
        engine_override=None,
        sharded_fn=None,
        sharded_opts: dict | None = None,
        sharded_fn_soft=None,
        sharded_windows_fn=None,
        sharded_windows_fn_soft=None,
        field_cache: bool = True,
        resident_state: bool = True,
        span_path: str | None = None,
        profile_path: str | None = None,
        step_slo_ms: float = 0.0,
        mesh_devices: int = 0,
    ):
        # serve a custom engine (e.g. models.learned.LearnedEngine) on
        # the dense branch instead of the module-level heuristic engine;
        # the sharded branches take precedence when configured. Resolved
        # once: the choice is fixed for the server's lifetime.
        self._engine = engine_override or engine
        self._sharded_fn = sharded_fn
        self._sharded_windows_fn = sharded_windows_fn
        self._sharded_windows_fn_soft = sharded_windows_fn_soft
        # soft (preferred-constraint) variant: request.soft selects it, so
        # a host that detects preferred terms is served them rather than
        # getting silently-unscored placements
        self._sharded_fn_soft = sharded_fn_soft
        # options baked into sharded_fn at startup; requests asking for
        # anything else must fail loud, not be silently overridden
        self._sharded_opts = sharded_opts or {}
        self.cycles_served = 0
        # capability switches, read dynamically by the handlers (so a
        # test — or a canary rollout — can downgrade a live server and
        # exercise the client's invalidate-together recovery)
        self.field_cache_enabled = field_cache
        self.resident_enabled = resident_state
        # resident deltas on the ScheduleWindows RPC (multi-window
        # backlog path; HealthReply.windows_resident) — its own switch
        # so a canary can downgrade it independently of batch-resident
        self.windows_resident_enabled = resident_state
        # gang co-scheduling (HealthReply.gang_scheduling): this build's
        # PodBatch knows the gang tensors and finish_cycle rescinds
        # partial gangs on device. The switch exists so a test/canary
        # can impersonate an OLD sidecar and exercise the client's
        # strip-and-degrade path (host-side all-or-nothing backstop).
        self.gang_enabled = True
        # fused min-max epilogue (HealthReply.fused_min_max): this
        # build's engine serves fused=True with normalizer="min_max"
        # (PR-8's megakernel epilogue), but the bit is advertised only
        # when the backend PROFITS from it — a CPU sidecar would trade
        # the XLA normalize pass for the interpret-mode Pallas kernel,
        # so it keeps the bit off and hosts stay on unfused min_max.
        # Tests/canaries flip the switch to exercise the latch on CPU.
        self.fused_min_max_enabled = jax.default_backend() == "tpu"
        # resident-state observability (tests + ops): how many cycles
        # were served from an applied delta vs. a full resident upload
        self.resident_deltas_served = 0
        self.resident_fulls_served = 0
        self._lock = threading.Lock()
        # serializes DEVICE access explicitly (schedule/windows/preempt
        # bodies), so the executor may run more than one worker without
        # ever interleaving two device programs: with a pipelined host
        # keeping a ScheduleBatch in flight most of the time, a
        # single-worker executor would queue Health probes (liveness,
        # field-cache capability re-probes) behind the cycle
        self._device_lock = threading.Lock()
        # session id -> {"<rpc>:<map>": {field: ndarray}} (LRU-bounded)
        self._field_cache: "OrderedDict[str, dict]" = OrderedDict()
        # sidecar telemetry (host/observe primitives — the sidecar was
        # Health-only before; SURVEY.md §5's blindness, now on its own
        # /metrics): labeled device-step histogram, per-RPC counters,
        # resident delta-vs-full applies, live resident session count
        from kubernetes_scheduler_tpu.host import observe

        self.metrics_step = observe.Histogram(
            "device_step_duration_seconds",
            "Device (engine) step time by RPC",
            labels=("rpc",),
        )
        self.metrics_rpcs = observe.Counter(
            "rpcs_served_total", "RPCs served by the sidecar", labels=("rpc",)
        )
        self.metrics_resident = observe.Counter(
            "resident_applies_total",
            "Resident-state cluster uploads applied (delta vs full)",
            labels=("upload",),
        )
        # mesh-sharded serving (--mesh-devices > 1): the sidecar twins
        # of the host's sharded counters — RPCs served by the sharded
        # program, and each applied delta's routed per-shard payload
        # split (what each mesh shard's rows cost on the wire)
        self.mesh_devices = int(mesh_devices)
        self.metrics_sharded = observe.Counter(
            "sharded_cycles_total",
            "RPCs served by this sidecar's mesh-sharded engine",
            labels=("rpc",),
        )
        self.metrics_shard_bytes = observe.Counter(
            "shard_delta_bytes_total",
            "Routed SnapshotDelta payload bytes per owning node shard "
            "(mesh-sharded resident sessions)",
            labels=("shard",),
        )
        self.metrics_sessions = observe.Gauge(
            "resident_sessions_count",
            "Sessions currently holding resident device state",
        )
        # the sidecar-side half of the gang counters (the host exports
        # admit/defer totals; the device is where placements are
        # rescinded, so the masked count is surfaced HERE too)
        self.metrics_gang_masked = observe.Counter(
            "gang_pods_masked_total",
            "Tentative placements rescinded on device by the gang "
            "all-or-nothing rule (ops/gang.py)",
        )
        # sidecar-side SLO watchdog (--step-slo-ms): the host's
        # cycle_slo_ms detector cannot tell a slow device step from a
        # slow host stage; this counter is the device half, so
        # slo_breaches_total exists on BOTH exporters and an alert can
        # attribute a breach to the right side of the bridge. 0 = off.
        self.step_slo_ms = float(step_slo_ms)
        self.metrics_slo = observe.Counter(
            "slo_breaches_total",
            "Device steps that blew the configured --step-slo-ms budget",
            labels=("rpc",),
        )
        # server-side spans (trace/spans.py): opened under the trace id
        # the host shipped as gRPC metadata, so `spans merge` joins the
        # two timelines; requests without an id are not spanned (a
        # sidecar-assigned id could collide with a host id and fake a
        # join)
        self.spans = None
        if span_path:
            self.spans = observe.SpanRecorder(span_path, process="sidecar")
        # on-demand jax.profiler capture (/debug/profile, or forwarded
        # from the host over the yoda-profile-cycles metadata key)
        self._profile_left = 0
        self._profile_dir = profile_path

    def _session(self, request) -> dict | None:
        """The per-session state dict (field caches + resident state),
        LRU-bounded; None when the request carries no session id."""
        sid = request.session_id
        if not sid:
            return None
        with self._lock:
            sess = self._field_cache.get(sid)
            if sess is None:
                sess = {}
                self._field_cache[sid] = sess
                while len(self._field_cache) > _MAX_CACHE_SESSIONS:
                    self._field_cache.popitem(last=False)
            else:
                self._field_cache.move_to_end(sid)
        return sess

    def _session_caches(self, request, which: str):
        """(snapshot_cache, pods_cache) for this request's session, or
        (None, None) when the client did not opt into the field cache
        (or this server does not serve it)."""
        sess = self._session(request) if self.field_cache_enabled else None
        if sess is None:
            return None, None
        return (
            sess.setdefault(f"{which}:snapshot", {}),
            sess.setdefault(f"{which}:pods", {}),
        )

    # ---- telemetry ----------------------------------------------------

    def _request_telemetry(self, context):
        """(trace_id, seq, span_set) from the call's gRPC metadata
        (bridge/schedule.proto documents the keys). Also arms the
        profiler when the host forwarded a /debug/profile ask."""
        md = {}
        try:
            md = {k: v for k, v in (context.invocation_metadata() or ())}
        except Exception:
            pass
        try:
            tid = int(md.get("yoda-trace-id", 0))
        except (TypeError, ValueError):
            tid = 0
        try:
            seq = int(md.get("yoda-trace-seq", -1))
        except (TypeError, ValueError):
            seq = -1
        ask = md.get("yoda-profile-cycles")
        if ask:
            try:
                self.arm_profile(int(ask))
            except (TypeError, ValueError):
                pass
        ss = (
            self.spans.begin(tid)
            if self.spans is not None and tid > 0
            else None
        )
        return tid, seq, ss

    def arm_profile(self, cycles: int, out_dir: str | None = None) -> dict:
        """Capture the next `cycles` device steps under jax.profiler;
        each dump is named after the trace id it covers (step-<id>) so
        a profile pairs with its spans and flight-recorder record."""
        with self._lock:
            if out_dir is None:
                out_dir = self._profile_dir
            if out_dir is None:
                import tempfile

                out_dir = tempfile.mkdtemp(prefix="yoda-sidecar-profile-")
            self._profile_dir = out_dir
            self._profile_left = int(cycles)
        return {"armed": int(cycles), "out_dir": out_dir}

    def _maybe_profile(self, call, trace_id: int):
        """One device dispatch, under jax.profiler when an arm is
        outstanding (zero cost otherwise). Runs inside _device_lock —
        the profiler session must never interleave two programs."""
        with self._lock:
            armed = self._profile_left > 0
            if armed:
                self._profile_left -= 1
            out_dir = self._profile_dir
        if not armed:
            return call()
        import os

        from kubernetes_scheduler_tpu.host.observe import profile_device_step

        tag = "step-%08d" % trace_id if trace_id > 0 else "step-unlabeled"
        return profile_device_step(call, os.path.join(out_dir, tag))

    def render_metrics(self) -> str:
        """Prometheus exposition for the sidecar's own /metrics."""
        with self._lock:
            sessions = sum(
                1 for s in self._field_cache.values() if "resident" in s
            )
        self.metrics_sessions.set(sessions)
        collectors = [
            self.metrics_rpcs,
            self.metrics_step,
            self.metrics_resident,
            self.metrics_sessions,
            self.metrics_gang_masked,
            self.metrics_sharded,
            self.metrics_shard_bytes,
            self.metrics_slo,
        ]
        out = []
        for c in collectors:
            out.extend(c.render())
        return "\n".join(out) + "\n"

    def _finish_call(self, rpc: str, dt: float, tid: int, seq: int, ss) -> None:
        """Per-RPC telemetry epilogue, OFF the device section: histogram
        + counter feeds, the step-SLO watchdog, and the span flush (the
        handler added its stage spans — deserialize, device_step,
        serialize, plus delta_apply from _resident_snapshot — before
        calling here; the names are a registry-pinned contract, see
        observe.SHIPPED_SPANS)."""
        self.metrics_step.observe(dt, rpc=rpc)
        self.metrics_rpcs.inc(rpc=rpc)
        if self.step_slo_ms > 0 and dt * 1e3 > self.step_slo_ms:
            self.metrics_slo.inc(rpc=rpc)
            log.warning(
                "SLO breach: %s device step took %.1f ms (budget %.1f "
                "ms) trace_id=%s journal_seq=%s",
                rpc, dt * 1e3, self.step_slo_ms,
                tid if tid > 0 else "-", seq if seq >= 0 else "-",
            )
        if ss is not None:
            self.spans.flush(ss, seq=seq if seq >= 0 else None)

    def _resident_snapshot(self, request, context, snap_cache, ss=None):
        """Resolve the request's cluster state under the resident-state
        protocol: a delta applies to the session's retained snapshot
        (INVALID_ARGUMENT "resident-epoch-mismatch" when inapplicable —
        the client resends in full), a resident_full upload replaces it,
        and either path retags the session to request.resident_epoch.
        Plain requests (no delta, no resident_full) pass through
        untouched."""
        delta_present = bool(request.snapshot_delta.tensors)
        if not (delta_present or request.resident_full):
            return codec.unpack_fields(
                engine.SnapshotArrays, request.snapshot, cache=snap_cache
            )
        if not self.resident_enabled:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "resident-epoch-mismatch: this sidecar does not serve "
                "resident cluster state",
            )
        sess = self._session(request)
        if sess is None:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "resident cluster state requires a session_id",
            )
        if delta_present:
            st = sess.get("resident")
            if st is None or st["epoch"] != request.resident_epoch - 1:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"resident-epoch-mismatch: session holds epoch "
                    f"{None if st is None else st['epoch']}, delta wants "
                    f"{request.resident_epoch - 1}",
                )
            delta = codec.unpack_fields(
                engine.SnapshotDelta, request.snapshot_delta
            )
            if (
                delta.node_mask.shape != st["snapshot"].node_mask.shape
                or delta.req_vals.shape[1:]
                != st["snapshot"].requested.shape[1:]
                or delta.dom_vals.shape[1]
                != st["snapshot"].domain_counts.shape[1]
            ):
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "resident-epoch-mismatch: delta shape does not match "
                    "the retained snapshot (layout churn)",
                )
            # applied in numpy BY VALUE: bitwise the snapshot the client
            # would have shipped in full, so delta cycles cannot diverge
            # from full-upload cycles (PARITY.md)
            t_apply = time.perf_counter()
            snapshot = engine.apply_snapshot_delta_np(st["snapshot"], delta)
            if ss is not None:
                ss.add("delta_apply", t_apply, time.perf_counter())
            with self._lock:
                self.resident_deltas_served += 1
            self.metrics_resident.inc(upload="delta")
            if self.mesh_devices > 1 and (
                delta.node_mask.shape[0] % self.mesh_devices == 0
            ):
                # per-shard routed payload split of the delta just
                # applied — the sidecar twin of the host's
                # shard_delta_bytes{shard} accounting, measured the
                # SAME way (prev-mask probe for mask-only shards;
                # steady-state mask bytes excluded — the retained mask
                # plane ships nothing, ShardedEngine._fold_delta)
                import numpy as _np

                from kubernetes_scheduler_tpu.engine import snapshot_nbytes
                from kubernetes_scheduler_tpu.host.snapshot import (
                    shard_snapshot_delta,
                )

                prev_mask = _np.asarray(st["snapshot"].node_mask, bool)
                mask_changed = not _np.array_equal(
                    prev_mask, _np.asarray(delta.node_mask, bool)
                )
                for shard, routed in shard_snapshot_delta(
                    delta, self.mesh_devices, prev_node_mask=prev_mask
                ).items():
                    self.metrics_shard_bytes.inc(
                        snapshot_nbytes(routed)
                        - (0 if mask_changed else routed.node_mask.nbytes),
                        shard=str(shard),
                    )
        else:
            snapshot = codec.unpack_fields(
                engine.SnapshotArrays, request.snapshot, cache=snap_cache
            )
            with self._lock:
                self.resident_fulls_served += 1
            self.metrics_resident.inc(upload="full")
        sess["resident"] = {
            "snapshot": snapshot, "epoch": int(request.resident_epoch),
        }
        return snapshot

    def _pick_sharded_fn(self, request, context, fn, fn_soft, what):
        """Validate the request against the options baked into the
        sharded engine at startup (fail loud, never silently override)
        and select the plain or soft variant."""
        asked = {
            "policy": request.policy,
            "assigner": request.assigner,
            "normalizer": request.normalizer,
        }
        for key, want in asked.items():
            # make_sharded_*_fn factories default to greedy, so an opts
            # dict that doesn't say otherwise still pins greedy
            default = "greedy" if key == "assigner" else None
            have = self._sharded_opts.get(key, default)
            if want and have and want != have:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"sidecar's sharded engine is fixed to "
                    f"{key}={have!r}; request asked for {want!r}",
                )
        # score_plugins are STRUCTURAL (baked into the compiled program,
        # like policy): a request's list must match the built one exactly
        want_sp = _score_plugins(request)
        have_sp = self._sharded_opts.get("score_plugins")
        if want_sp != have_sp and (want_sp or have_sp):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"sidecar's sharded engine is built with "
                f"score_plugins={have_sp!r}; request asked for {want_sp!r}",
            )
        # auction knobs are NOT baked: they are traced operands of the
        # sharded program (the round-loop bound and the price step), so
        # request-carried values are honored per call with no recompile —
        # the startup flags only set the defaults (proto3 zero = default)
        if request.soft:
            if fn_soft is None:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"request asked for soft (preferred-constraint) "
                    f"scoring but this sidecar's {what} was built "
                    f"without a soft variant",
                )
            return fn_soft
        return fn

    def schedule_batch(self, request: pb.ScheduleRequest, context) -> pb.ScheduleReply:
        tid, seq, ss = self._request_telemetry(context)
        snap_cache, pods_cache = self._session_caches(request, "batch")
        t_des = time.perf_counter()
        try:
            snapshot = self._resident_snapshot(
                request, context, snap_cache, ss
            )
            pods = codec.unpack_fields(
                engine.PodBatch, request.pods, cache=pods_cache,
                defaults=_POD_WIRE_DEFAULTS,
            )
        except codec.FieldCacheMiss as e:
            # sidecar restarted or the session was evicted: the client
            # clears its cache and resends everything in full
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except (ValueError, TypeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        t0 = time.perf_counter()
        try:
            with self._device_lock:
                if self._sharded_fn is not None:
                    # `fused` is a decision-identical optimization hint;
                    # this sidecar's sharded program is built once at
                    # startup (make_sharded_*_fn(fused=...) exists, but
                    # the choice is baked), so serve the built variant
                    # rather than degrade the deployment to the host's
                    # scalar fallback
                    fn = self._pick_sharded_fn(
                        request, context, self._sharded_fn,
                        self._sharded_fn_soft, "sharded engine",
                    )
                    res = self._maybe_profile(
                        lambda: fn(snapshot, pods, **_auction_kw(request)),
                        tid,
                    )
                    self.metrics_sharded.inc(rpc="schedule_batch")
                else:
                    kw = _auction_kw(request)
                    sp = _score_plugins(request)
                    if sp is not None:
                        kw["score_plugins"] = sp
                    res = self._maybe_profile(
                        lambda: self._engine.schedule_batch(
                            snapshot,
                            pods,
                            policy=request.policy or "balanced_cpu_diskio",
                            assigner=request.assigner or "greedy",
                            normalizer=request.normalizer or "min_max",
                            fused=request.fused,
                            affinity_aware=request.affinity_aware,
                            soft=request.soft,
                            **kw,
                        ),
                        tid,
                    )
                res = jax.tree_util.tree_map(np.asarray, res)
        except ValueError as e:  # unknown policy/assigner/normalizer
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        t1 = time.perf_counter()
        dt = t1 - t0
        with self._lock:
            self.cycles_served += 1
        # gang sentinels (<= GANG_MASKED_BASE, ops/gang.py) are
        # placements the device rescinded under the all-or-nothing rule
        # — surfaced on the sidecar's own /metrics beside the host's
        # admit/defer totals
        masked = int(
            (np.asarray(res.node_idx) <= GANG_MASKED_BASE).sum()
        )
        if masked:
            self.metrics_gang_masked.inc(masked)
        reply = pb.ScheduleReply(engine_seconds=dt)
        only = set(_DECISION_FIELDS) if request.decisions_only else None
        codec.pack_fields(res, reply.result, only=only)
        if ss is not None:
            ss.add("deserialize", t_des, t0, rpc="schedule_batch")
            ss.add("device_step", t0, t1, rpc="schedule_batch")
            ss.add("serialize", t1, time.perf_counter(), rpc="schedule_batch")
        self._finish_call("schedule_batch", dt, tid, seq, ss)
        return reply

    def schedule_windows(
        self, request: pb.ScheduleRequest, context
    ) -> pb.ScheduleReply:
        """Whole-backlog RPC: pods carry a leading [w, p, ...] window
        axis; the reply holds engine.WindowsResult fields. One device
        dispatch schedules every window with capacity + (anti)affinity
        carries threaded between them."""
        tid, seq, ss = self._request_telemetry(context)
        snap_cache, pods_cache = self._session_caches(request, "windows")
        if (
            bool(request.snapshot_delta.tensors) or request.resident_full
        ) and not self.windows_resident_enabled:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "resident-epoch-mismatch: this sidecar does not serve "
                "resident cluster state on ScheduleWindows",
            )
        t_des = time.perf_counter()
        try:
            # the resident protocol is shared with ScheduleBatch — same
            # session-retained snapshot, same epoch sequence (backlog
            # and single-window cycles interleave on one counter)
            snapshot = self._resident_snapshot(
                request, context, snap_cache, ss
            )
            pods_w = codec.unpack_fields(
                engine.PodBatch, request.pods, cache=pods_cache,
                defaults=_POD_WIRE_DEFAULTS,
            )
        except codec.FieldCacheMiss as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except (ValueError, TypeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        t0 = time.perf_counter()
        try:
            with self._device_lock:
                if self._sharded_windows_fn is not None:
                    fn = self._pick_sharded_fn(
                        request, context, self._sharded_windows_fn,
                        self._sharded_windows_fn_soft,
                        "sharded windows engine",
                    )
                    res = self._maybe_profile(
                        lambda: fn(
                            snapshot, pods_w, **_auction_kw(request)
                        ),
                        tid,
                    )
                    self.metrics_sharded.inc(rpc="schedule_windows")
                else:
                    kw = _auction_kw(request)
                    sp = _score_plugins(request)
                    if sp is not None:
                        kw["score_plugins"] = sp
                    res = self._maybe_profile(
                        lambda: self._engine.schedule_windows(
                            snapshot,
                            pods_w,
                            policy=request.policy or "balanced_cpu_diskio",
                            assigner=request.assigner or "auction",
                            normalizer=request.normalizer or "none",
                            fused=request.fused,
                            affinity_aware=request.affinity_aware,
                            soft=request.soft,
                            **kw,
                        ),
                        tid,
                    )
                res = jax.tree_util.tree_map(np.asarray, res)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        t1 = time.perf_counter()
        dt = t1 - t0
        with self._lock:
            self.cycles_served += 1
        masked = int(
            (np.asarray(res.node_idx) <= GANG_MASKED_BASE).sum()
        )
        if masked:
            self.metrics_gang_masked.inc(masked)
        reply = pb.ScheduleReply(engine_seconds=dt)
        codec.pack_fields(res, reply.result)
        if ss is not None:
            ss.add("deserialize", t_des, t0, rpc="schedule_windows")
            ss.add("device_step", t0, t1, rpc="schedule_windows")
            ss.add(
                "serialize", t1, time.perf_counter(), rpc="schedule_windows"
            )
        self._finish_call("schedule_windows", dt, tid, seq, ss)
        return reply

    def preempt(self, request: pb.ScheduleRequest, context) -> pb.ScheduleReply:
        """Preemption pass (upstream PostFilter) on the device: pending
        preemptors + victim arrays in, (node, victims, n_victims) out —
        engine.preempt_batch. Served dense even by mesh-sharded sidecars
        (the victim tables are [n, K] — small next to a score matrix)."""
        from kubernetes_scheduler_tpu.ops.preempt import VictimArrays

        try:
            snapshot = codec.unpack_fields(engine.SnapshotArrays, request.snapshot)
            pods = codec.unpack_fields(
                engine.PodBatch, request.pods, defaults=_POD_WIRE_DEFAULTS
            )
            victims = codec.unpack_fields(VictimArrays, request.victims)
            k_cap = int(request.preempt_k_cap)
            if k_cap <= 0:
                raise ValueError("preempt_k_cap must be positive")
        except codec.FieldCacheMiss as e:
            # the Preempt surface is uncached (victims churn per pass);
            # a marker here is a skewed/confused client — same recovery
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except (ValueError, TypeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        t0 = time.perf_counter()
        with self._device_lock:
            res = engine.preempt_batch(snapshot, pods, victims, k_cap=k_cap)
            res = jax.tree_util.tree_map(np.asarray, res)
        dt = time.perf_counter() - t0
        with self._lock:
            self.cycles_served += 1
        reply = pb.ScheduleReply(engine_seconds=dt)
        codec.pack_fields(res, reply.result)
        self.metrics_step.observe(dt, rpc="preempt")
        self.metrics_rpcs.inc(rpc="preempt")
        return reply

    def health(self, request: pb.HealthRequest, context) -> pb.HealthReply:
        devs = jax.devices()
        self.metrics_rpcs.inc(rpc="health")
        # every capability bit rides the one CAPABILITY_SWITCHES table:
        # a bit that exists in the proto but not in the table would be
        # silently False forever (capability-completeness lint pins the
        # two in sync)
        caps = {
            fieldname: bool(getattr(self, attr))
            for fieldname, attr in CAPABILITY_SWITCHES.items()
        }
        with self._lock:
            served = self.cycles_served
        return pb.HealthReply(
            status="SERVING",
            device_count=len(devs),
            platform=devs[0].platform if devs else "none",
            cycles_served=served,
            **caps,
        )


def make_server(
    address: str = "127.0.0.1:0",
    *,
    engine_override=None,
    sharded_fn=None,
    sharded_opts: dict | None = None,
    sharded_fn_soft=None,
    sharded_windows_fn=None,
    sharded_windows_fn_soft=None,
    max_workers: int = 2,
    span_path: str | None = None,
    profile_path: str | None = None,
    step_slo_ms: float = 0.0,
    mesh_devices: int = 0,
) -> tuple[grpc.Server, int, EngineService]:
    """Build (server, bound_port, service). Device access stays
    single-writer regardless of max_workers (EngineService._device_lock
    serializes the compute sections); the default of 2 workers keeps
    Health answering while a pipelined host's ScheduleBatch is in
    flight — with 1 worker every probe queues behind the cycle."""
    service = EngineService(
        engine_override=engine_override,
        sharded_fn=sharded_fn,
        sharded_opts=sharded_opts,
        sharded_fn_soft=sharded_fn_soft,
        sharded_windows_fn=sharded_windows_fn,
        sharded_windows_fn_soft=sharded_windows_fn_soft,
        span_path=span_path,
        profile_path=profile_path,
        step_slo_ms=step_slo_ms,
        mesh_devices=mesh_devices,
    )
    handlers = grpc.method_handlers_generic_handler(
        SERVICE,
        {
            "ScheduleBatch": grpc.unary_unary_rpc_method_handler(
                service.schedule_batch,
                request_deserializer=pb.ScheduleRequest.FromString,
                response_serializer=pb.ScheduleReply.SerializeToString,
            ),
            "ScheduleWindows": grpc.unary_unary_rpc_method_handler(
                service.schedule_windows,
                request_deserializer=pb.ScheduleRequest.FromString,
                response_serializer=pb.ScheduleReply.SerializeToString,
            ),
            "Preempt": grpc.unary_unary_rpc_method_handler(
                service.preempt,
                request_deserializer=pb.ScheduleRequest.FromString,
                response_serializer=pb.ScheduleReply.SerializeToString,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                service.health,
                request_deserializer=pb.HealthRequest.FromString,
                response_serializer=pb.HealthReply.SerializeToString,
            ),
        },
    )
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
            ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
        ],
    )
    server.add_generic_rpc_handlers((handlers,))
    port = server.add_insecure_port(address)
    if port == 0:
        raise RuntimeError(f"could not bind {address}")
    return server, port, service


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=50051)
    parser.add_argument(
        "--mesh-devices",
        type=int,
        default=0,
        help="shard the node axis over this many devices (0 = single device)",
    )
    parser.add_argument(
        "--mesh-hosts",
        type=int,
        default=1,
        help="with --mesh-devices: split the mesh into this many host groups "
        "(2-D dcn x node hierarchical collectives for multi-host slices)",
    )
    parser.add_argument("--policy", default="balanced_cpu_diskio")
    parser.add_argument(
        "--assigner",
        default="greedy",
        choices=["greedy", "auction"],
        help="assignment algorithm baked into the sharded engine when "
        "--mesh-devices is set (the dense engine honors the per-request "
        "assigner field instead)",
    )
    parser.add_argument(
        "--auction-rounds", type=int, default=1024,
        help="max auction rounds for the sharded auction assigner",
    )
    parser.add_argument(
        "--auction-price-frac", type=float, default=1.0,
        help="price step (fraction of the unit row range) for the sharded "
        "auction assigner",
    )
    parser.add_argument(
        "--normalizer", default="min_max",
        choices=["min_max", "softmax", "none"],
        help="score normalizer baked into the sharded engine when "
        "--mesh-devices is set",
    )
    parser.add_argument(
        "--fused", action="store_true",
        help="route score + resource fit through the fused Pallas kernel "
        "on the sharded engine (requires --normalizer none and the "
        "balanced_cpu_diskio policy)",
    )
    parser.add_argument(
        "--score-plugins",
        default=None,
        help='JSON list of {"name": ..., "weight": N} — weighted '
        "multi-plugin scoring baked into the sharded engine when "
        "--mesh-devices is set (the dense branch honors the request's "
        "score_plugins field instead)",
    )
    parser.add_argument(
        "--learned-checkpoint",
        default=None,
        help="serve the learned two-tower policy restored from this orbax "
        "checkpoint (policy name becomes 'learned'; shards over the mesh "
        "when --mesh-devices is set)",
    )
    parser.add_argument(
        "--metrics-port", type=int, default=0,
        help="serve the sidecar's own /metrics + /healthz + "
        "/debug/profile on this HTTP port (0 = disabled)",
    )
    parser.add_argument(
        "--metrics-host", default="0.0.0.0",
        help="bind host for --metrics-port",
    )
    parser.add_argument(
        "--span-path", default=None,
        help="write server-side Chrome-trace spans (deserialize, device "
        "step, delta apply, serialize) under this directory, joined to "
        "host spans by the trace id on gRPC metadata",
    )
    parser.add_argument(
        "--profile-path", default=None,
        help="where on-demand /debug/profile jax.profiler dumps land "
        "(default: a tempdir)",
    )
    parser.add_argument(
        "--step-slo-ms", type=float, default=0.0,
        help="device-step SLO budget in ms: steps slower than this bump "
        "slo_breaches_total{rpc} on the sidecar's /metrics and log the "
        "offending trace id (0 = off)",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    engine_override = None
    learned_params = None
    learned_model = None
    if args.learned_checkpoint:
        if args.policy not in ("balanced_cpu_diskio", "learned"):
            # fail loud, never silently override an explicit choice (the
            # same convention the pinned-opts request checks follow)
            raise SystemExit(
                f"--policy {args.policy!r} conflicts with "
                "--learned-checkpoint (which serves policy 'learned')"
            )
        from kubernetes_scheduler_tpu.models.learned import load_learned_engine

        engine_override = load_learned_engine(args.learned_checkpoint)
        learned_params = engine_override.params
        learned_model = engine_override.model
        args.policy = "learned"
    # --score-plugins parsed/validated for EVERY mode: a dense sidecar
    # silently ignoring the flag would advertise weighted scoring it
    # never serves (the dense branch honors the REQUEST's score_plugins
    # field instead — hosts carry their config on the wire)
    score_plugins = None
    if args.score_plugins:
        import json as _json

        from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

        # ONE validation implementation: names, weights and entry keys
        # are checked by the same code the host's config path uses
        try:
            score_plugins = SchedulerConfig.from_dict(
                {"score_plugins": _json.loads(args.score_plugins)}
            ).score_plugins_tuple()
        except ValueError as e:
            raise SystemExit(f"--score-plugins: {e}") from None
        if args.fused:
            # the fused kernel hardwires the single yoda formula; a
            # silently-fused "weighted" sidecar would advertise
            # score_plugins while serving single-policy placements
            raise SystemExit("--score-plugins is incompatible with --fused")
        if args.learned_checkpoint:
            raise SystemExit(
                "--score-plugins is incompatible with "
                "--learned-checkpoint (the learned scorer replaces "
                "the policy; it cannot be one weighted term yet)"
            )
        if args.mesh_devices <= 1:
            raise SystemExit(
                "--score-plugins only configures the SHARDED engine "
                "(--mesh-devices > 1); the dense engine honors the "
                "request-carried score_plugins field instead — set the "
                "host's score_plugins config"
            )
    sharded_fn = None
    if args.mesh_devices > 1:
        from jax.sharding import Mesh
        from kubernetes_scheduler_tpu.parallel.engine import (
            make_sharded_schedule_fn,
            make_sharded_windows_fn,
        )
        from kubernetes_scheduler_tpu.parallel.mesh import (
            DCN_AXIS, NODE_AXIS, make_mesh_multihost,
        )

        if args.mesh_hosts > 1:
            if args.mesh_devices % args.mesh_hosts:
                raise SystemExit("--mesh-devices must divide by --mesh-hosts")
            mesh = make_mesh_multihost(
                args.mesh_hosts, args.mesh_devices // args.mesh_hosts
            )
            node_axes: tuple[str, ...] | str = (DCN_AXIS, NODE_AXIS)
        else:
            mesh = Mesh(
                np.asarray(jax.devices()[: args.mesh_devices]), (NODE_AXIS,)
            )
            node_axes = NODE_AXIS
        assigner_kw = {
            "assigner": args.assigner,
            "normalizer": args.normalizer,
            "fused": args.fused,
        }
        if score_plugins is not None:
            assigner_kw["score_plugins"] = score_plugins
        if args.assigner == "auction":
            assigner_kw.update(
                auction_rounds=args.auction_rounds,
                auction_price_frac=args.auction_price_frac,
            )
        if learned_params is not None:
            from kubernetes_scheduler_tpu.models.learned import (
                make_sharded_learned_fn,
            )

            def _learned(**kw):
                return make_sharded_learned_fn(
                    learned_params, mesh, model=learned_model,
                    node_axes=node_axes, **assigner_kw, **kw,
                )

            sharded_fn = _learned()
            sharded_fn_soft = _learned(soft=True)
            sharded_windows_fn = _learned(windows=True)
            sharded_windows_fn_soft = _learned(windows=True, soft=True)
        else:
            sharded_fn = make_sharded_schedule_fn(
                mesh, policy=args.policy, node_axes=node_axes, **assigner_kw
            )
            sharded_fn_soft = make_sharded_schedule_fn(
                mesh, policy=args.policy, node_axes=node_axes, soft=True,
                **assigner_kw,
            )
            sharded_windows_fn = make_sharded_windows_fn(
                mesh, policy=args.policy, node_axes=node_axes, **assigner_kw
            )
            sharded_windows_fn_soft = make_sharded_windows_fn(
                mesh, policy=args.policy, node_axes=node_axes, soft=True,
                **assigner_kw,
            )
        # the assigner is baked into the sharded program at startup; a
        # host that asked for the other one must get an error, not
        # silently different placement semantics
        # auction knobs deliberately absent: they are per-request traced
        # operands (the startup flags only set the defaults baked into
        # the fn wrappers above), not pinned options. score_plugins ARE
        # pinned: the combination is compiled into the program.
        sharded_opts = {
            "policy": args.policy,
            "assigner": args.assigner,
            "normalizer": args.normalizer,
        }
        if score_plugins is not None:
            sharded_opts["score_plugins"] = score_plugins
    else:
        sharded_fn_soft = None
        sharded_windows_fn = None
        sharded_windows_fn_soft = None
        sharded_opts = None

    server, port, service = make_server(
        f"{args.host}:{args.port}",
        engine_override=engine_override,
        sharded_fn=sharded_fn,
        sharded_opts=sharded_opts,
        sharded_fn_soft=sharded_fn_soft,
        sharded_windows_fn=sharded_windows_fn,
        sharded_windows_fn_soft=sharded_windows_fn_soft,
        span_path=args.span_path,
        profile_path=args.profile_path,
        step_slo_ms=args.step_slo_ms,
        mesh_devices=args.mesh_devices if sharded_fn is not None else 0,
    )
    exporter = None
    if args.metrics_port:
        from kubernetes_scheduler_tpu.host.observe import HttpMetricsServer

        exporter = HttpMetricsServer(
            service.render_metrics, profile=service.arm_profile
        )
        mport = exporter.serve(args.metrics_port, host=args.metrics_host)
        log.info("sidecar metrics on %s:%d", args.metrics_host, mport)
    server.start()
    log.info(
        "engine sidecar serving on %s:%d (devices=%s)",
        args.host, port, jax.devices(),
    )
    try:
        server.wait_for_termination()
    except (KeyboardInterrupt, SystemExit):
        # drain in-flight RPCs before exiting (SIGTERM arrives via the
        # CLI's handler as SystemExit); a cut-off cycle would flip the
        # host to its scalar fallback for one window, which is fine but
        # unnecessary when shutdown can just finish the RPC
        log.info("shutting down; draining in-flight RPCs")
        # the grace bounds the drain; the event fires at most ~10s out,
        # and the belt-and-braces timeout keeps shutdown finite even if
        # the grpc core wedges
        server.stop(grace=10).wait(timeout=15)
    finally:
        if exporter is not None:
            exporter.close()
        if service.spans is not None:
            service.spans.close()


if __name__ == "__main__":
    main()
