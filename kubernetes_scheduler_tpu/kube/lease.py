"""coordination.k8s.io/v1 Lease backend for leader election.

The reference's HA comes from upstream kube-scheduler leader election on
cluster leases (deploy/yoda-scheduler.yaml:10-17, RBAC
deploy/yoda-scheduler.yaml:…/leases). This implements host.leader.Lease
against the real Lease API: compare-and-swap via resourceVersion-d PUTs
(the API server rejects stale writes with 409 Conflict), create via POST.

Time mapping: LeaseRecord carries epoch floats; the Lease spec carries
RFC3339 MicroTime (acquireTime/renewTime) + leaseDurationSeconds.
"""

from __future__ import annotations

import datetime
import logging
import math

from kubernetes_scheduler_tpu.host.leader import LeaseRecord
from kubernetes_scheduler_tpu.kube.client import KubeApiError, KubeClient

log = logging.getLogger("yoda_tpu.kube")

_MICRO = "%Y-%m-%dT%H:%M:%S.%fZ"


def _to_micro(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, tz=datetime.timezone.utc
    ).strftime(_MICRO)


def _from_micro(s: str | None) -> float:
    if not s:
        return 0.0
    # tolerate both MicroTime and second-resolution RFC3339
    base = s.rstrip("Z")
    fmt = "%Y-%m-%dT%H:%M:%S.%f" if "." in base else "%Y-%m-%dT%H:%M:%S"
    return (
        datetime.datetime.strptime(base, fmt)
        .replace(tzinfo=datetime.timezone.utc)
        .timestamp()
    )


class KubeLease:
    """host.leader.Lease over a cluster Lease object."""

    def __init__(
        self,
        client: KubeClient,
        *,
        name: str = "yoda-tpu-scheduler",
        namespace: str = "kube-system",
    ):
        self.client = client
        self.name = name
        self.namespace = namespace
        self._resource_version: str | None = None

    def _path(self) -> str:
        return (
            f"/apis/coordination.k8s.io/v1/namespaces/{self.namespace}"
            f"/leases/{self.name}"
        )

    def read(self) -> LeaseRecord | None:
        try:
            obj = self.client.get(self._path())
        except KubeApiError as e:
            if e.status == 404:
                self._resource_version = None
                return None
            raise
        self._resource_version = (obj.get("metadata") or {}).get("resourceVersion")
        spec = obj.get("spec") or {}
        holder = spec.get("holderIdentity")
        if not holder:
            return None
        return LeaseRecord(
            holder=holder,
            acquired_at=_from_micro(spec.get("acquireTime")),
            renewed_at=_from_micro(spec.get("renewTime")),
            duration=float(spec.get("leaseDurationSeconds") or 0),
        )

    def _body(self, record: LeaseRecord, resource_version: str | None) -> dict:
        meta: dict = {"name": self.name, "namespace": self.namespace}
        if resource_version:
            meta["resourceVersion"] = resource_version
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": meta,
            "spec": {
                "holderIdentity": record.holder,
                "acquireTime": _to_micro(record.acquired_at),
                "renewTime": _to_micro(record.renewed_at),
                # the API field is integer seconds; round UP so a
                # sub-second duration cannot truncate to an
                # instantly-expired lease
                "leaseDurationSeconds": max(1, math.ceil(record.duration)),
            },
        }

    def try_claim(
        self, record: LeaseRecord, previous: LeaseRecord | None
    ) -> bool:
        # re-read for the freshest resourceVersion AND to CAS against
        # `previous` the way FileLease does; the 409 path then catches
        # writers racing between this read and the PUT
        current = self.read()
        cur_key = (current.holder, current.renewed_at) if current else None
        prev_key = (previous.holder, previous.renewed_at) if previous else None
        if cur_key != prev_key:
            return False
        try:
            if self._resource_version is None:
                self.client.post(
                    f"/apis/coordination.k8s.io/v1/namespaces/{self.namespace}/leases",
                    self._body(record, None),
                )
            else:
                self.client.put(
                    self._path(), self._body(record, self._resource_version)
                )
            return True
        except KubeApiError as e:
            if e.status in (409, 422):   # conflict: lost the race
                return False
            raise

    def clear(self, holder: str) -> None:
        """Release by PUTting an empty holderIdentity (client-go's release
        semantics) — the shipped RBAC grants update but not delete, and an
        empty holder reads back as an unheld lease either way."""
        current = self.read()
        if current and current.holder == holder:
            body = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {
                    "name": self.name,
                    "namespace": self.namespace,
                    "resourceVersion": self._resource_version,
                },
                "spec": {"holderIdentity": ""},
            }
            try:
                self.client.put(self._path(), body)
            except KubeApiError as e:
                if e.status not in (404, 409):
                    raise
