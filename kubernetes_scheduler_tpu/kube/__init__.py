"""Kubernetes API boundary: the live-cluster half of the host.

The reference is a drop-in scheduler for a real cluster: it embeds the
upstream kube-scheduler binary (cmd/scheduler/main.go:12-21), talks to the
API server via client-go with QPS/Burst 1000 (pkg/yoda/scheduler.go:58-60),
and ships RBAC for nodes/pods/bindings/leases
(deploy/yoda-scheduler.yaml:91-251). This package is that boundary rebuilt
on the stdlib (no client-go, no vendored client): a rate-limited REST
client, list/watch cluster sources feeding host.Scheduler's injectable
callables, a Binding POST binder, and a coordination.k8s.io/v1 Lease
backend for host.leader.LeaderElector.
"""

from kubernetes_scheduler_tpu.kube.client import KubeApiError, KubeClient, KubeConfig
from kubernetes_scheduler_tpu.kube.convert import node_from_api, pdb_from_api, pod_from_api
from kubernetes_scheduler_tpu.kube.source import KubeBinder, KubeClusterSource, KubeEvictor
from kubernetes_scheduler_tpu.kube.lease import KubeLease

__all__ = [
    "KubeApiError",
    "KubeBinder",
    "KubeEvictor",
    "KubeClient",
    "KubeClusterSource",
    "KubeConfig",
    "KubeLease",
    "node_from_api",
    "pdb_from_api",
    "pod_from_api",
]
