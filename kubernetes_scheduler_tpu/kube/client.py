"""Minimal Kubernetes REST client (stdlib only).

The reference reaches the API server through client-go with QPS and Burst
raised to 1000 (pkg/yoda/scheduler.go:58-60, ctrl.GetConfigOrDie). This
client reproduces that contract — bearer-token/CA auth, in-cluster and
kubeconfig bootstraps, a 1000/1000 token-bucket limiter — on urllib, so
the scheduler binary needs no vendored client library.

Streaming watches use the API server's `?watch=true` endpoint, which
returns newline-delimited JSON events over a chunked response
(WatchEvent: {"type": "ADDED"|"MODIFIED"|"DELETED", "object": {...}}).
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass

log = logging.getLogger("yoda_tpu.kube")

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiError(RuntimeError):
    def __init__(self, status: int, method: str, path: str, body: str = ""):
        self.status = status
        self.path = path
        super().__init__(f"{method} {path} -> HTTP {status}: {body[:300]}")


@dataclass
class KubeConfig:
    """Connection parameters for one API server."""

    base_url: str                      # e.g. https://10.0.0.1:443
    token: str | None = None           # static bearer token
    # path to a (projected, kubelet-rotated) token file: re-read per
    # request like client-go, so the scheduler survives the ~1h bound
    # service-account token rotation instead of 401-ing forever
    token_path: str | None = None
    ca_path: str | None = None         # CA bundle file for TLS verification
    ca_data: str | None = None         # inline PEM CA bundle
    insecure: bool = False             # skip TLS verification
    namespace: str = "default"

    @classmethod
    def in_cluster(cls) -> "KubeConfig":
        """Pod-mounted service account (what GetConfigOrDie resolves to
        when running inside the cluster)."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError(
                "not in cluster: KUBERNETES_SERVICE_HOST unset and no "
                "kubeconfig given"
            )
        ns_path = f"{SERVICE_ACCOUNT_DIR}/namespace"
        namespace = "default"
        if os.path.exists(ns_path):
            with open(ns_path) as f:
                namespace = f.read().strip() or "default"
        return cls(
            base_url=f"https://{host}:{port}",
            token_path=f"{SERVICE_ACCOUNT_DIR}/token",
            ca_path=f"{SERVICE_ACCOUNT_DIR}/ca.crt",
            namespace=namespace,
        )

    @classmethod
    def from_kubeconfig(cls, path: str | None = None) -> "KubeConfig":
        """Parse the (current-context of a) kubeconfig file. Supports the
        common token / client-less auth fields; client-cert auth is out of
        scope for the scheduler's service-account deployment."""
        import yaml

        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config")
        )
        with open(path) as f:
            doc = yaml.safe_load(f)
        ctx_name = doc.get("current-context")
        ctx = next(
            c["context"] for c in doc.get("contexts", [])
            if c["name"] == ctx_name
        )
        cluster = next(
            c["cluster"] for c in doc.get("clusters", [])
            if c["name"] == ctx["cluster"]
        )
        user = next(
            (u["user"] for u in doc.get("users", []) if u["name"] == ctx.get("user")),
            {},
        )
        ca_data = cluster.get("certificate-authority-data")
        if ca_data:
            # the form generated kubeconfigs (kind/kubeadm/cloud) use:
            # base64-embedded PEM rather than a file path
            import base64

            ca_data = base64.b64decode(ca_data).decode()
        return cls(
            base_url=cluster["server"].rstrip("/"),
            token=user.get("token"),
            token_path=user.get("tokenFile"),
            ca_path=cluster.get("certificate-authority"),
            ca_data=ca_data,
            insecure=bool(cluster.get("insecure-skip-tls-verify", False)),
            namespace=ctx.get("namespace", "default"),
        )


class _TokenBucket:
    """client-go flowcontrol analog: qps refill, burst capacity."""

    def __init__(self, qps: float, burst: int):
        self.qps = float(qps)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def take(self) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)


class KubeClient:
    """Rate-limited JSON REST client for one API server.

    qps/burst default to the reference's 1000/1000
    (pkg/yoda/scheduler.go:59-60).
    """

    def __init__(
        self,
        config: KubeConfig,
        *,
        qps: float = 1000.0,
        burst: int = 1000,
        timeout: float = 30.0,
    ):
        self.config = config
        self.timeout = timeout
        self._bucket = _TokenBucket(qps, burst)
        self._token_cache: tuple[float, str] | None = None
        self._ssl_ctx: ssl.SSLContext | None = None
        if config.base_url.startswith("https"):
            if config.insecure:
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            elif config.ca_path or config.ca_data:
                ctx = ssl.create_default_context(
                    cafile=config.ca_path, cadata=config.ca_data
                )
            else:
                ctx = ssl.create_default_context()
            self._ssl_ctx = ctx

    def _token(self) -> str | None:
        """Current bearer token. File-backed tokens are re-read (with a
        60s cache) so kubelet rotation of projected tokens takes effect
        without a restart — client-go behavior."""
        if self.config.token_path:
            now = time.monotonic()
            if self._token_cache is None or now - self._token_cache[0] > 60.0:
                with open(self.config.token_path) as f:
                    self._token_cache = (now, f.read().strip())
            return self._token_cache[1]
        return self.config.token

    # -- plumbing --------------------------------------------------------

    def _url(self, path: str, params: dict | None = None) -> str:
        url = self.config.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        return url

    def _request(
        self,
        method: str,
        path: str,
        params: dict | None = None,
        body: dict | None = None,
        *,
        timeout: float | None = None,
        stream: bool = False,
        content_type: str = "application/json",
    ):
        self._bucket.take()
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            self._url(path, params), data=data, method=method
        )
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        token = self._token()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            resp = urllib.request.urlopen(
                req,
                timeout=self.timeout if timeout is None else timeout,
                context=self._ssl_ctx,
            )
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")
            raise KubeApiError(e.code, method, path, detail) from None
        if stream:
            return resp
        with resp:
            payload = resp.read()
        return json.loads(payload) if payload else None

    # -- verbs -----------------------------------------------------------

    def get(self, path: str, params: dict | None = None):
        return self._request("GET", path, params)

    def post(self, path: str, body: dict):
        return self._request("POST", path, body=body)

    def put(self, path: str, body: dict):
        return self._request("PUT", path, body=body)

    def patch(self, path: str, body: dict):
        """Strategic-merge PATCH (the content type kubectl uses for
        annotation updates like VolumeBinding's selected-node)."""
        return self._request(
            "PATCH", path, body=body,
            content_type="application/strategic-merge-patch+json",
        )

    def delete(self, path: str, body: dict | None = None):
        return self._request("DELETE", path, body=body)

    def list_all(self, path: str, params: dict | None = None) -> list[dict]:
        """GET a List object, following `continue` pagination."""
        return self.list_with_rv(path, params)[0]

    def list_with_rv(
        self, path: str, params: dict | None = None
    ) -> tuple[list[dict], str | None]:
        """list_all plus the List's resourceVersion — the token a
        subsequent watch resumes from (the informer list-then-watch
        handshake)."""
        params = dict(params or {})
        items: list[dict] = []
        while True:
            doc = self.get(path, params) or {}
            items.extend(doc.get("items", []))
            meta = doc.get("metadata") or {}
            cont = meta.get("continue")
            if not cont:
                return items, meta.get("resourceVersion")
            params["continue"] = cont

    def watch(
        self,
        path: str,
        params: dict | None = None,
        *,
        timeout_seconds: float = 60.0,
    ):
        """Yield watch events (dicts with 'type' and 'object') until the
        server closes the stream or timeout_seconds elapses server-side."""
        params = dict(params or {})
        params["watch"] = "true"
        params.setdefault("timeoutSeconds", str(int(timeout_seconds)))
        resp = self._request(
            "GET", path, params, timeout=timeout_seconds + 10.0, stream=True
        )
        with resp:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    log.warning("undecodable watch line: %.120r", line)
