"""Live-cluster source + binder: the scheduler's API-server I/O.

KubeClusterSource supplies host.Scheduler's injectable callables
(list_nodes / list_running_pods) from the real API server and feeds the
queue from a pending-pod watch — the role the embedded upstream
framework's informers play for the reference (SURVEY.md §1 L6).
KubeBinder closes the cycle with the Binding POST the upstream binding
cycle performs after PreBind (SURVEY.md §3.2: POST
/api/v1/.../pods/<p>/binding).
"""

from __future__ import annotations

import http.client
import logging
import threading
import time

from kubernetes_scheduler_tpu.host.types import Node, Pod
from kubernetes_scheduler_tpu.kube.client import KubeApiError, KubeClient
from kubernetes_scheduler_tpu.kube.convert import node_from_api, pod_from_api

log = logging.getLogger("yoda_tpu.kube")

FINISHED_PHASES = ("Succeeded", "Failed")


class InformerCache:
    """Watch-backed local cache of nodes and assigned pods.

    The upstream framework feeds its snapshot from informer caches, not
    per-cycle full LISTs; re-listing every assigned pod cluster-wide each
    cycle is O(cluster) API-server load and multi-second overhead at 5k+
    nodes. Each resource runs list -> replace -> bounded watch -> apply
    in a daemon thread, with relist as the error/expiry recovery (the
    informer resync pattern). Readers get point-in-time copies."""

    def __init__(
        self,
        client: KubeClient,
        *,
        watch_timeout: float = 60.0,
        resync_interval: float = 300.0,
        volumes: bool = True,
        on_event=None,
    ):
        self.client = client
        self.watch_timeout = watch_timeout
        # streaming-ingestion hook (host/mirror.SnapshotMirror):
        # on_event(resource, etype, obj) fires AFTER the store update,
        # outside the cache lock, with the CONVERTED object the store
        # now holds (Node/Pod; None on RESYNC — a full relist replaced
        # the store and the consumer must reseed). Only the node and
        # assigned-pod streams emit: they are the snapshot's inputs.
        self.on_event = on_event
        # volumes=False skips the PVC/PV loops (no list+watch streams, no
        # resident stores) for deployments that disable volume topology
        self.volumes = volumes
        # periodic full relist (client-go resyncPeriod): the correctness
        # backstop for missed deletes on servers that don't honor
        # resourceVersion-d watches; rv-tracked streams carry the load
        # in between
        self.resync_interval = resync_interval
        self._nodes: dict[str, Node] = {}
        self._pods: dict[str, Pod] = {}
        self._pdbs: dict[str, object] = {}
        self._pvcs: dict[str, object] = {}
        self._pvs: dict[str, object] = {}
        # namespace name -> labels, for exact namespaceSelector
        # resolution (convert.resolve_namespace_selectors); None until
        # synced or when the list is denied (RBAC) — readers then fall
        # back to the ALL-namespaces approximation
        self._namespaces: dict[str, dict] | None = None
        # (kind, namespace, name) -> spec.replicas of workload
        # controllers, for the PDB percentage math's expected-count
        # lookup (upstream disruption-controller semantics)
        self._controllers: dict[tuple, int] = {}
        # StorageClass name -> volumeBindingMode, for the WFFC
        # selected-node handoff (VolumeBinding's active half)
        self._storage_classes: dict[str, str] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._synced = {
            "nodes": threading.Event(),
            "pods": threading.Event(),
            "pdbs": threading.Event(),
            "pvcs": threading.Event(),
            "pvs": threading.Event(),
            "namespaces": threading.Event(),
            "replicasets": threading.Event(),
            "statefulsets": threading.Event(),
            "storageclasses": threading.Event(),
        }
        self._threads: list[threading.Thread] = []

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "InformerCache":
        loops = [
            self._node_loop, self._pod_loop, self._pdb_loop, self._ns_loop,
            self._rs_loop, self._sts_loop,
        ]
        if self.volumes:
            loops += [self._sc_loop, self._pvc_loop, self._pv_loop]
        else:
            for name in ("storageclasses", "pvcs", "pvs"):
                self._synced[name].set()
        for target in loops:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()

    def wrap_events(self, wrapper) -> None:
        """Compose a wrapper around the on_event hook — the informer-
        stream fault-injection surface (sim/faults.py chaos runs gate
        delivery here: partition buffers, error drops) and a seam for
        any other event-tap. `wrapper(inner)` receives the current
        callback (possibly None) and returns the replacement."""
        self.on_event = wrapper(self.on_event)

    def wait_synced(self, timeout: float = 30.0) -> bool:
        return all(ev.wait(timeout) for ev in self._synced.values())

    # -- readers ---------------------------------------------------------

    def nodes(self) -> list[Node]:
        with self._lock:
            return list(self._nodes.values())

    def running_pods(self) -> list[Pod]:
        with self._lock:
            return list(self._pods.values())

    def pdbs(self) -> list:
        with self._lock:
            return list(self._pdbs.values())

    def pvc_map(self) -> dict:
        """'ns/name' -> PersistentVolumeClaim, watch-fed (full copy —
        prefer get_pvc on per-pod paths)."""
        with self._lock:
            return dict(self._pvcs)

    def pv_map(self) -> dict:
        """PV name -> PersistentVolume, watch-fed (full copy — prefer
        get_pv on per-pod paths)."""
        with self._lock:
            return dict(self._pvs)

    def get_pvc(self, key: str):
        """Point lookup, 'ns/name' — no map copy."""
        with self._lock:
            return self._pvcs.get(key)

    def get_pv(self, name: str):
        """Point lookup by PV name — no map copy."""
        with self._lock:
            return self._pvs.get(name)

    def controller_replicas(self, kind: str, namespace: str, name: str):
        """spec.replicas of a workload controller (ReplicaSet/
        StatefulSet), watch-fed; None = unknown (callers fall back to
        current-count PDB math)."""
        with self._lock:
            return self._controllers.get((kind, namespace, name))

    def storage_class_mode(self, name: str) -> str | None:
        """volumeBindingMode of a StorageClass (None = unknown class or
        no data — callers then skip the WFFC handoff)."""
        with self._lock:
            return self._storage_classes.get(name)

    def namespace_labels(self) -> dict[str, dict] | None:
        """name -> labels of every namespace, watch-fed; None when the
        namespace list is unavailable (callers then approximate
        namespaceSelectors as ALL namespaces)."""
        with self._lock:
            return dict(self._namespaces) if self._namespaces is not None else None

    def assume(self, pod: Pod) -> None:
        """Record a just-bound pod before the watch echoes it back —
        upstream's assume-cache: without this, back-to-back cycles read
        a running set that misses the previous cycle's bindings and
        over-commit node capacity. A later relist reconciles either way
        (confirms the binding, or removes a pod that raced away)."""
        with self._lock:
            self._pods[f"{pod.namespace}/{pod.name}"] = pod

    # -- node loop -------------------------------------------------------

    def _node_loop(self) -> None:
        self._resource_loop(
            "nodes",
            "/api/v1/nodes",
            params=None,
            replace=self._replace_nodes,
            apply=self._apply_node_event,
        )

    def _emit(self, resource: str, etype: str, obj) -> None:
        if self.on_event is None:
            return
        try:
            self.on_event(resource, etype, obj)
        except Exception:
            # a consumer bug must never kill an informer loop. A missed
            # pod/node event is bounded by the periodic RESYNC relist
            # (resync_interval), which reseeds the consumer from the
            # fresh stores — the mirror's verify pass cannot catch it
            # (it cross-checks against the mirror's OWN lists)
            log.exception("informer on_event hook failed (%s)", resource)

    def _replace_nodes(self, items: list[dict]) -> None:
        fresh = {o["metadata"]["name"]: node_from_api(o) for o in items}
        with self._lock:
            first = not self._synced["nodes"].is_set()
            self._nodes = fresh
        if not first:
            self._emit("nodes", "RESYNC", None)

    def _apply_node_event(self, ev: dict) -> None:
        obj = ev.get("object") or {}
        name = (obj.get("metadata") or {}).get("name")
        if not name:
            return
        etype = ev.get("type")
        node = None
        with self._lock:
            if etype == "DELETED":
                node = self._nodes.pop(name, None)
            elif etype in ("ADDED", "MODIFIED"):
                node = self._nodes[name] = node_from_api(obj)
        if node is not None:
            self._emit("nodes", etype, node)

    # -- assigned-pod loop ----------------------------------------------

    def _pod_loop(self) -> None:
        self._resource_loop(
            "pods",
            "/api/v1/pods",
            params={"fieldSelector": "spec.nodeName!="},
            replace=self._replace_pods,
            apply=self._apply_pod_event,
        )

    def _replace_pods(self, items: list[dict]) -> None:
        fresh: dict[str, Pod] = {}
        for o in items:
            if (o.get("status") or {}).get("phase") in FINISHED_PHASES:
                continue
            meta = o.get("metadata") or {}
            fresh[f"{meta.get('namespace', 'default')}/{meta.get('name')}"] = (
                pod_from_api(o)
            )
        with self._lock:
            first = not self._synced["pods"].is_set()
            self._pods = fresh
        if not first:
            self._emit("pods", "RESYNC", None)

    def _apply_pod_event(self, ev: dict) -> None:
        obj = ev.get("object") or {}
        meta = obj.get("metadata") or {}
        key = f"{meta.get('namespace', 'default')}/{meta.get('name')}"
        finished = (obj.get("status") or {}).get("phase") in FINISHED_PHASES
        etype = ev.get("type")
        pod = None
        deleted = False
        with self._lock:
            if etype == "DELETED" or finished:
                pod = self._pods.pop(key, None)
                deleted = True
            elif etype in ("ADDED", "MODIFIED"):
                pod = self._pods[key] = pod_from_api(obj)
        if pod is not None:
            self._emit("pods", "DELETED" if deleted else etype, pod)

    # -- PDB loop --------------------------------------------------------

    def _pdb_loop(self) -> None:
        """PodDisruptionBudgets ride the informer pattern like nodes/pods
        (round-3 verdict: per-preemption-pass LISTs were the exact
        per-cycle O(cluster) pattern the cache exists to kill); a watch
        also closes the TTL staleness window — a just-created or
        tightened budget reaches the next preemption pass as soon as its
        event lands, not after a TTL expiry."""
        self._resource_loop(
            "pdbs",
            "/apis/policy/v1/poddisruptionbudgets",
            params=None,
            replace=self._replace_pdbs,
            apply=self._apply_pdb_event,
            optional=True,
        )

    def _replace_pdbs(self, items: list[dict]) -> None:
        from kubernetes_scheduler_tpu.kube.convert import pdb_from_api

        fresh = {}
        for o in items:
            meta = o.get("metadata") or {}
            fresh[f"{meta.get('namespace', 'default')}/{meta.get('name')}"] = (
                pdb_from_api(o)
            )
        with self._lock:
            self._pdbs = fresh

    def _apply_pdb_event(self, ev: dict) -> None:
        from kubernetes_scheduler_tpu.kube.convert import pdb_from_api

        obj = ev.get("object") or {}
        meta = obj.get("metadata") or {}
        key = f"{meta.get('namespace', 'default')}/{meta.get('name')}"
        with self._lock:
            if ev.get("type") == "DELETED":
                self._pdbs.pop(key, None)
            elif ev.get("type") in ("ADDED", "MODIFIED"):
                self._pdbs[key] = pdb_from_api(obj)

    # -- namespace loop --------------------------------------------------

    def _ns_loop(self) -> None:
        """Namespace names + labels, for exact namespaceSelector
        resolution on inter-pod (anti)affinity terms (k8s >= 1.21
        semantics). Optional: a control plane denying the list (RBAC)
        flips the store to None and selector-carrying terms degrade to
        the logged ALL-namespaces approximation instead of silently
        matching nothing."""
        self._resource_loop(
            "namespaces",
            "/api/v1/namespaces",
            params=None,
            replace=self._replace_namespaces,
            apply=self._apply_ns_event,
            optional=True,
            unavailable=self._namespaces_unavailable,
        )

    def _replace_namespaces(self, items: list[dict]) -> None:
        fresh = {
            (o.get("metadata") or {}).get("name", ""): dict(
                (o.get("metadata") or {}).get("labels") or {}
            )
            for o in items
        }
        fresh.pop("", None)
        with self._lock:
            self._namespaces = fresh

    def _namespaces_unavailable(self) -> None:
        with self._lock:
            self._namespaces = None

    def _apply_ns_event(self, ev: dict) -> None:
        obj = ev.get("object") or {}
        name = (obj.get("metadata") or {}).get("name")
        if not name:
            return
        with self._lock:
            if self._namespaces is None:
                self._namespaces = {}
            if ev.get("type") == "DELETED":
                self._namespaces.pop(name, None)
            elif ev.get("type") in ("ADDED", "MODIFIED"):
                self._namespaces[name] = dict(
                    (obj.get("metadata") or {}).get("labels") or {}
                )

    # -- workload-controller loops (PDB expected counts) -----------------

    def _rs_loop(self) -> None:
        self._controller_loop(
            "replicasets", "ReplicaSet", "/apis/apps/v1/replicasets"
        )

    def _sts_loop(self) -> None:
        self._controller_loop(
            "statefulsets", "StatefulSet", "/apis/apps/v1/statefulsets"
        )

    def _controller_loop(self, sync_name: str, kind: str, path: str) -> None:
        """spec.replicas per workload controller, for the PDB
        percentage math (upstream resolves expected counts through the
        owning controllers' scale). Optional: RBAC denial degrades to
        current-count math, the documented conservative fallback."""

        def replace(items: list[dict]) -> None:
            fresh = {}
            for o in items:
                meta = o.get("metadata") or {}
                fresh[(kind, meta.get("namespace", "default"),
                       meta.get("name", ""))] = int(
                    (o.get("spec") or {}).get("replicas") or 0
                )
            with self._lock:
                self._controllers = {
                    k: v for k, v in self._controllers.items() if k[0] != kind
                } | fresh

        def apply(ev: dict) -> None:
            obj = ev.get("object") or {}
            meta = obj.get("metadata") or {}
            key = (kind, meta.get("namespace", "default"),
                   meta.get("name", ""))
            with self._lock:
                if ev.get("type") == "DELETED":
                    self._controllers.pop(key, None)
                elif ev.get("type") in ("ADDED", "MODIFIED"):
                    self._controllers[key] = int(
                        (obj.get("spec") or {}).get("replicas") or 0
                    )

        self._resource_loop(
            sync_name, path, params=None, replace=replace, apply=apply,
            optional=True,
        )

    # -- volume loops ----------------------------------------------------

    def _sc_loop(self) -> None:
        """StorageClass volumeBindingMode, for VolumeBinding's active
        half: a pod binding with an unbound WaitForFirstConsumer claim
        gets the claim annotated with the chosen node (KubeBinder)."""

        def replace(items: list[dict]) -> None:
            fresh = {
                (o.get("metadata") or {}).get("name", ""):
                    o.get("volumeBindingMode") or "Immediate"
                for o in items
            }
            fresh.pop("", None)
            with self._lock:
                self._storage_classes = fresh

        def apply(ev: dict) -> None:
            obj = ev.get("object") or {}
            name = (obj.get("metadata") or {}).get("name")
            if not name:
                return
            with self._lock:
                if ev.get("type") == "DELETED":
                    self._storage_classes.pop(name, None)
                elif ev.get("type") in ("ADDED", "MODIFIED"):
                    self._storage_classes[name] = (
                        obj.get("volumeBindingMode") or "Immediate"
                    )

        self._resource_loop(
            "storageclasses", "/apis/storage.k8s.io/v1/storageclasses",
            params=None, replace=replace, apply=apply, optional=True,
        )

    def _pvc_loop(self) -> None:
        self._resource_loop(
            "pvcs",
            "/api/v1/persistentvolumeclaims",
            params=None,
            replace=self._replace_pvcs,
            apply=self._apply_pvc_event,
            optional=True,
        )

    def _pv_loop(self) -> None:
        self._resource_loop(
            "pvs",
            "/api/v1/persistentvolumes",
            params=None,
            replace=self._replace_pvs,
            apply=self._apply_pv_event,
            optional=True,
        )

    def _replace_pvcs(self, items: list[dict]) -> None:
        from kubernetes_scheduler_tpu.kube.convert import pvc_from_api

        fresh = {}
        for o in items:
            c = pvc_from_api(o)
            fresh[f"{c.namespace}/{c.name}"] = c
        with self._lock:
            self._pvcs = fresh

    def _apply_pvc_event(self, ev: dict) -> None:
        from kubernetes_scheduler_tpu.kube.convert import pvc_from_api

        obj = ev.get("object") or {}
        c = pvc_from_api(obj)
        key = f"{c.namespace}/{c.name}"
        with self._lock:
            if ev.get("type") == "DELETED":
                self._pvcs.pop(key, None)
            elif ev.get("type") in ("ADDED", "MODIFIED"):
                self._pvcs[key] = c

    def _replace_pvs(self, items: list[dict]) -> None:
        from kubernetes_scheduler_tpu.kube.convert import pv_from_api

        fresh = {(v := pv_from_api(o)).name: v for o in items}
        with self._lock:
            self._pvs = fresh

    def _apply_pv_event(self, ev: dict) -> None:
        from kubernetes_scheduler_tpu.kube.convert import pv_from_api

        v = pv_from_api(ev.get("object") or {})
        with self._lock:
            if ev.get("type") == "DELETED":
                self._pvs.pop(v.name, None)
            elif ev.get("type") in ("ADDED", "MODIFIED"):
                self._pvs[v.name] = v

    # -- shared loop -----------------------------------------------------

    def _resource_loop(
        self, name, path, *, params, replace, apply, optional: bool = False,
        unavailable=None,
    ) -> None:
        """list -> watch-from-resourceVersion -> apply, relisting only on
        410 Gone (rv expired), errors, or the periodic resync — NOT on
        every routine stream close, which would be a full O(cluster) LIST
        plus event replay per watch_timeout.

        optional=True: a 404 (API group absent — e.g. policy/v1 on a
        minimal control plane) or 403 (ServiceAccount lacks the grant —
        e.g. an upgrade that didn't reapply the ClusterRole) on the LIST
        degrades to an empty, SYNCED set re-probed at the resync
        interval: an optional resource must never hang wait_synced or
        error-backoff-spam — the scheduler runs on, with the dependent
        feature (preemption budgets) inert."""
        backoff = 0.5
        rv: str | None = None
        listed_at = 0.0
        while not self._stop.is_set():
            try:
                if rv is None or (
                    time.monotonic() - listed_at > self.resync_interval
                ):
                    items, rv = self.client.list_with_rv(path, params)
                    replace(items)
                    listed_at = time.monotonic()
                    self._synced[name].set()
                wparams = dict(params or {})
                if rv:
                    wparams["resourceVersion"] = rv
                    wparams["allowWatchBookmarks"] = "true"
                for ev in self.client.watch(
                    path, wparams, timeout_seconds=self.watch_timeout
                ):
                    etype = ev.get("type")
                    obj = ev.get("object") or {}
                    if etype == "ERROR":
                        # 410 Gone: our rv fell off the server's window
                        rv = None
                        break
                    new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                    if new_rv:
                        rv = new_rv
                    if etype in ("ADDED", "MODIFIED", "DELETED"):
                        apply(ev)
                    if self._stop.is_set():
                        return
                backoff = 0.5
            except KubeApiError as e:
                if optional and e.status in (403, 404):
                    log.warning(
                        "%s unavailable (HTTP %s); continuing without",
                        name, e.status,
                    )
                    # default: empty-but-synced; resources distinguishing
                    # "none exist" from "cannot know" (namespaces) supply
                    # their own unavailable state
                    (unavailable or (lambda: replace([])))()
                    self._synced[name].set()
                    rv = None
                    self._stop.wait(self.resync_interval)
                    continue
                rv = None if e.status == 410 else rv
                log.warning("%s informer error (%s); backing off", name, e)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 30.0)
            except Exception as e:
                log.warning("%s informer error (%s); relisting", name, e)
                rv = None
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 30.0)
            # bounded streams close routinely; brief pause avoids a hot
            # rewatch loop against servers with instant-closing watches
            self._stop.wait(0.2)


class KubeClusterSource:
    """List/watch nodes and pods for the scheduling loop.

    scheduler_name filters the pending stream the way upstream's
    profile-based queue admission does: only pods whose spec.schedulerName
    names this scheduler are ours to place
    (deploy/yoda-scheduler.yaml:48, example/test-pod.yaml:10).
    """

    def __init__(
        self,
        client: KubeClient,
        *,
        scheduler_name: str = "yoda-tpu",
        namespace: str | None = None,   # None = all namespaces
        cache: InformerCache | None = None,
        pdb_ttl: float = 15.0,
        volume_topology: bool = True,
    ):
        from kubernetes_scheduler_tpu.kube.volumes import VolumeTopology

        self.client = client
        self.scheduler_name = scheduler_name
        self.namespace = namespace
        self.cache = cache
        self.pdb_ttl = pdb_ttl
        self._pdb_cache: list | None = None
        self._pdb_expiry = 0.0
        # cache-less namespace snapshot for namespaceSelector resolution
        # (TTL like the PDB list); the informer path reads its watch-fed
        # namespace store instead
        self._ns_cache: dict | None = None
        self._ns_expiry = 0.0
        # monotonic time before which a denied (403/404) namespace LIST
        # is not retried — a TTL, not a permanent latch: transient RBAC
        # propagation must not degrade selectors for the process lifetime
        self._ns_denied_until = 0.0
        # bound PVs constrain placement (VolumeZone/VolumeBinding parity):
        # the pending stream hands the scheduler pods whose node-affinity
        # already carries their volumes' topology (kube/volumes.py). With
        # an informer cache the resolver reads its watch-fed PVC/PV
        # stores; otherwise a TTL LIST pair
        self.volumes = (
            VolumeTopology(client, cache=cache) if volume_topology else None
        )

    def _fold_volumes(self, pod: Pod) -> Pod:
        if self.volumes is None or not pod.volume_claims:
            return pod
        return self.volumes.fold(pod)

    def _namespace_labels(self) -> dict[str, dict] | None:
        """Namespace name -> labels for namespaceSelector resolution.
        Informer-cached when available; else a TTL LIST; None (= degrade
        to the ALL-namespaces approximation) when the list is denied."""
        if self.cache is not None:
            return self.cache.namespace_labels()
        now = time.monotonic()
        if now < self._ns_denied_until:
            return None
        if self._ns_cache is not None and now < self._ns_expiry:
            return self._ns_cache
        try:
            items = self.client.list_all("/api/v1/namespaces")
        except KubeApiError as e:
            if e.status in (403, 404):
                log.warning(
                    "namespace list unavailable (HTTP %s); "
                    "namespaceSelectors approximate ALL namespaces "
                    "(retrying in 60s)",
                    e.status,
                )
                self._ns_denied_until = now + 60.0
                return None
            raise
        self._ns_cache = {
            (o.get("metadata") or {}).get("name", ""): dict(
                (o.get("metadata") or {}).get("labels") or {}
            )
            for o in items
        }
        self._ns_cache.pop("", None)
        self._ns_expiry = now + self.pdb_ttl
        return self._ns_cache

    def _resolve_ns(self, pods: list[Pod]) -> list[Pod]:
        """Exact namespaceSelector resolution (lazy: the namespace set is
        only consulted when some pod actually carries a selector)."""
        from kubernetes_scheduler_tpu.kube.convert import (
            resolve_namespace_selectors,
        )

        if not any(
            t.namespace_selector for p in pods for t in p.pod_affinity
        ):
            return pods
        nss = self._namespace_labels()
        return [resolve_namespace_selectors(p, nss) for p in pods]

    def _pods_path(self) -> str:
        if self.namespace:
            return f"/api/v1/namespaces/{self.namespace}/pods"
        return "/api/v1/pods"

    def list_nodes(self) -> list[Node]:
        if self.cache is not None:
            return self.cache.nodes()
        return [node_from_api(o) for o in self.client.list_all("/api/v1/nodes")]

    def list_pdbs(self) -> list:
        """policy/v1 PodDisruptionBudgets, cluster-wide — consulted by
        the preemption pass so evictions never overdraw a budget. With an
        informer cache attached, budgets come from its watch-fed PDB
        store (no per-pass LIST, and a new/tightened budget is visible as
        soon as its event lands). Without one, the list is TTL-cached
        (refreshed at most every pdb_ttl seconds) — documented trade-off:
        a budget created or tightened inside the TTL window is invisible
        to up to pdb_ttl seconds of preemption passes; deployments that
        care run the informer (the CLI's --source=kube mode always does).
        Overdraw across cycles is independently prevented by the
        scheduler's pending-eviction accounting
        (host/scheduler._run_preemption)."""
        from kubernetes_scheduler_tpu.kube.convert import pdb_from_api

        if self.cache is not None:
            return self.cache.pdbs()
        now = time.monotonic()
        if self._pdb_cache is not None and now < self._pdb_expiry:
            return self._pdb_cache
        self._pdb_cache = [
            pdb_from_api(o)
            for o in self.client.list_all(
                "/apis/policy/v1/poddisruptionbudgets"
            )
        ]
        self._pdb_expiry = now + self.pdb_ttl
        return self._pdb_cache

    def controller_replicas(self, kind: str, namespace: str, name: str):
        """Workload-controller replicas for the PDB percentage math;
        informer-backed only (None without a cache — callers then use
        the conservative current-count fallback)."""
        if self.cache is not None:
            return self.cache.controller_replicas(kind, namespace, name)
        return None

    def list_running_pods(self) -> list[Pod]:
        """Assigned, unfinished pods — the capacity + affinity base state
        (what the upstream snapshot's NodeInfo.Pods aggregates).

        Always CLUSTER-WIDE, even under a namespace filter: node capacity
        is consumed by every namespace's pods, so scoping this list would
        schedule onto effectively-full nodes. Only the pending stream is
        namespace-scoped."""
        if self.cache is not None:
            return self._resolve_attach(
                self._resolve_ns(self.cache.running_pods())
            )
        items = self.client.list_all(
            "/api/v1/pods", {"fieldSelector": "spec.nodeName!="}
        )
        return self._resolve_attach(self._resolve_ns([
            pod_from_api(o)
            for o in items
            if (o.get("status") or {}).get("phase") not in FINISHED_PHASES
        ]))

    def _resolve_attach(self, pods: list[Pod]) -> list[Pod]:
        """NodeVolumeLimits usage accounting: running pods' bound CSI
        volumes consume attach units on their nodes — resolved here (the
        pending stream gets demands from fold()) and only for the rare
        claim-carrying pods."""
        if self.volumes is None:
            return pods
        import dataclasses

        out = []
        for p in pods:
            if p.volume_claims and not p.attach_demands:
                d = self.volumes.attach_demands(p)
                if d:
                    p = dataclasses.replace(p, attach_demands=d)
            out.append(p)
        return out

    def list_pending_pods(self) -> list[Pod]:
        """Unassigned pods addressed to this scheduler, bound volumes'
        topology folded into their node affinity."""
        items = self.client.list_all(
            self._pods_path(),
            {"fieldSelector": f"spec.nodeName=,spec.schedulerName={self.scheduler_name}"},
        )
        return self._resolve_ns(
            [self._fold_volumes(pod_from_api(o)) for o in items]
        )

    def watch_pending_events(self, *, timeout_seconds: float = 60.0):
        """Yield (event_type, Pod) for this scheduler's pending stream —
        DELETED included, so consumers can retire queue/dedup state when a
        pod is deleted while still pending. One bounded stream; callers
        loop to re-watch (the informer relist pattern)."""
        events = self.client.watch(
            self._pods_path(),
            {"fieldSelector": f"spec.nodeName=,spec.schedulerName={self.scheduler_name}"},
            timeout_seconds=timeout_seconds,
        )
        for ev in events:
            etype = ev.get("type")
            if etype in ("ADDED", "MODIFIED", "DELETED"):
                pod = pod_from_api(ev.get("object") or {})
                if etype != "DELETED":
                    pod = self._resolve_ns([self._fold_volumes(pod)])[0]
                yield etype, pod

    def watch_pending(self, *, timeout_seconds: float = 60.0):
        """Yield Pods as they become pending (ADDED/MODIFIED only)."""
        for etype, pod in self.watch_pending_events(
            timeout_seconds=timeout_seconds
        ):
            if etype != "DELETED" and pod.node_name is None:
                yield pod


def pod_key(pod: Pod) -> str:
    """Scheduling identity: UID when the API provided one (survives
    delete-and-recreate under the same name — upstream keys its queue by
    UID for exactly that reason), ns/name for simulated pods."""
    return pod.uid or f"{pod.namespace}/{pod.name}"


class KubeBinder:
    """POST pods/<name>/binding — the upstream bind step. With a
    VolumeTopology attached, unbound WaitForFirstConsumer claims are
    annotated with the chosen node FIRST (upstream VolumeBinding's
    PreBind handoff: the external provisioner reads
    volume.kubernetes.io/selected-node and provisions in that node's
    topology)."""

    def __init__(
        self,
        client: KubeClient,
        *,
        cache: InformerCache | None = None,
        volumes=None,
    ):
        self.client = client
        self.cache = cache
        self.volumes = volumes
        self.bound: list[tuple[str, str]] = []

    def _annotate_wffc(self, pod: Pod, node_name: str) -> None:
        for pvc in self.volumes.wffc_unbound(pod):
            if pvc.selected_node == node_name:
                continue  # idempotent retry
            try:
                self.client.patch(
                    f"/api/v1/namespaces/{pvc.namespace}"
                    f"/persistentvolumeclaims/{pvc.name}",
                    {"metadata": {"annotations": {
                        "volume.kubernetes.io/selected-node": node_name
                    }}},
                )
            except KubeApiError as e:
                if e.status == 404:
                    # claim deleted underfoot; the Binding POST settles
                    # the pod's own fate
                    continue
                # abort the bind: a pod placed without its volume
                # handoff would wait on provisioning that never targets
                # its node — the scheduler requeues with backoff instead
                raise

    def bind(self, pod: Pod, node_name: str) -> None:
        if self.volumes is not None and pod.volume_claims:
            self._annotate_wffc(pod, node_name)
        meta = {"name": pod.name, "namespace": pod.namespace}
        if pod.uid:
            # UID precondition: the API server rejects the bind (409) if
            # the name now belongs to a recreated pod — a stale queued
            # Pod must never place its successor
            meta["uid"] = pod.uid
        body = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": meta,
            "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
        }
        self.client.post(
            f"/api/v1/namespaces/{pod.namespace}/pods/{pod.name}/binding", body
        )
        pod.node_name = node_name
        if self.cache is not None:
            self.cache.assume(pod)
        self.bound.append((pod_key(pod), node_name))


class KubeEvictor:
    """DELETE the victim pod — the eviction step of the preemption pass
    (upstream PostFilter; host/scheduler._run_preemption). A UID
    precondition makes the delete a no-op (409) when the name has been
    recreated since the snapshot, so a stale proposal can never kill an
    unrelated pod; 404/409 are swallowed (the victim is already gone or
    already replaced — either way capacity resolves by the next cycle).
    """

    def __init__(self, client: KubeClient):
        self.client = client
        self.evicted: list[str] = []

    def evict(self, victim: Pod, *, preemptor: Pod) -> None:
        body: dict = {"apiVersion": "v1", "kind": "DeleteOptions"}
        if victim.uid:
            body["preconditions"] = {"uid": victim.uid}
        try:
            self.client.delete(
                f"/api/v1/namespaces/{victim.namespace}/pods/{victim.name}",
                body,
            )
        except KubeApiError as e:
            if e.status not in (404, 409):
                raise
            return
        self.evicted.append(pod_key(victim))


class _Feeder(threading.Thread):
    """Background pending-pod watcher feeding the scheduling queue.

    Decouples event ingestion from cycle execution so pods are scheduled
    on ARRIVAL (upstream behavior) instead of after a full bounded watch
    stream closes (~watch_timeout of added bind latency). The queue is
    thread-safe (host/queue.py); `seen` mutations here are guarded by
    `lock` and tolerate the benign race where a just-bound pod is
    re-submitted from a stale relist — the second bind 409s and is
    dropped by Scheduler._bind."""

    def __init__(self, sched, source, *, watch_timeout, idle_sleep, elector=None):
        super().__init__(daemon=True)
        self.sched = sched
        self.source = source
        self.watch_timeout = watch_timeout
        self.idle_sleep = idle_sleep
        self.elector = elector
        self.lock = threading.Lock()
        self.seen: set[str] = set()
        self.wake = threading.Event()      # signals the cycle loop
        self.stop_evt = threading.Event()
        self.idle_rounds = 0               # consecutive zero-submit rounds

    def _submit_new(self, pod) -> bool:
        # a STANDBY must not accumulate the cluster's pod churn in its
        # queue (unbounded growth + a flood of stale binds on failover):
        # skip without marking seen, so promotion's next watch/relist
        # round submits whatever is genuinely still pending
        if self.elector is not None and not self.elector.is_leader():
            return False
        key = pod_key(pod)
        with self.lock:
            if key in self.seen:
                return False
            self.seen.add(key)
        self.sched.submit(pod)
        self.wake.set()
        return True

    def discard(self, key: str) -> None:
        with self.lock:
            self.seen.discard(key)

    def run(self) -> None:
        # connection-level failures (reset/timeout mid-stream) arrive as
        # OSError/URLError/IncompleteRead, not KubeApiError — all retry;
        # nothing may kill a serve-forever feeder
        retryable = (KubeApiError, OSError, http.client.HTTPException)
        while not self.stop_evt.is_set():
            submitted = 0
            try:
                for etype, pod in self.source.watch_pending_events(
                    timeout_seconds=self.watch_timeout
                ):
                    if etype == "DELETED":
                        # deleted while pending: forget it, so a
                        # recreation under the same name (new UID) is
                        # submitted; the stale queued copy can't hurt —
                        # its UID-preconditioned bind 409s and drops
                        self.discard(pod_key(pod))
                    elif pod.node_name is None:
                        submitted += self._submit_new(pod)
                    if self.stop_evt.is_set():
                        return
                # relist safety net: watches can miss events across
                # restarts; a periodic list reconciles (informer resync).
                # Every pod still in our queue is still pending
                # server-side, so pruning `seen` to the server pending set
                # drops bound/deleted entries without touching queued ones
                # — keeps `seen` bounded over a long run.
                pending_keys = set()
                for pod in self.source.list_pending_pods():
                    pending_keys.add(pod_key(pod))
                    submitted += self._submit_new(pod)
                with self.lock:
                    self.seen &= pending_keys
            except retryable as e:
                log.warning("pending watch failed (%s); retrying", e)
                self.stop_evt.wait(self.idle_sleep)
                # an ERROR round proves nothing about the server's pending
                # set — it must not count as idle, or one-shot mode would
                # exit 0 during an API outage with pods still unscheduled
                continue
            self.idle_rounds = 0 if submitted else self.idle_rounds + 1
            self.stop_evt.wait(0.02)   # yield between bounded streams


def _idle_wait(sched, feeder: "_Feeder", idle_sleep: float) -> None:
    """One idle wait of the serving loop: with config.cycle_trigger=
    "event" the scheduler's CycleTrigger is the wake source (queue
    pushes notify it from Scheduler.submit — including the feeder's —
    and mirror events do too, so a utilization shift alone can start a
    cycle); otherwise the feeder's wake event, the tick-polling
    default. Either way idle_sleep is the watchdog timeout — the loop
    re-checks on silence."""
    trigger = getattr(sched, "trigger", None)
    if trigger is not None:
        trigger.wait(idle_sleep)
    else:
        feeder.wake.wait(timeout=idle_sleep)
        feeder.wake.clear()


def attach_mirror(cache: InformerCache, sched) -> None:
    """Wire an InformerCache's node/pod streams into a mirror-enabled
    Scheduler (config.snapshot_mirror): watch events become mirror row
    updates, and a RESYNC (periodic full relist — the missed-event
    backstop) reseeds the mirror from the cache's fresh stores (the
    next emit flushes to a full rebuild). Utilization events ride the
    advisor's fetch_changed drain on the cycle path, not this hook.

    Eventual-consistency bound: an event landing between the seed's
    cache-store reads and the mirror becoming seeded is dropped (and
    the mirror's verify pass cannot see it — it cross-checks against
    the mirror's own lists); the next RESYNC reconciles, so staleness
    is bounded by the cache's resync_interval — the same bound the
    informer pattern itself gives the pre-mirror list reads."""
    mirror = getattr(sched, "mirror", None)
    if mirror is None:
        raise ValueError(
            "scheduler has no snapshot mirror (set config.snapshot_mirror)"
        )

    def on_event(resource: str, etype: str, obj) -> None:
        if not mirror.seeded:
            return  # the scheduler's first cycle seeds from the cache
        if etype == "RESYNC":
            mirror.seed(
                cache.nodes(), cache.running_pods(), dict(mirror.utils)
            )
        elif resource == "nodes":
            mirror.apply_node_event(etype, obj)
        elif resource == "pods":
            mirror.apply_pod_event(etype, obj)

    cache.on_event = on_event


def run_kube_loop(
    sched,
    source: KubeClusterSource,
    *,
    max_cycles: int | None = None,
    idle_sleep: float = 0.5,
    watch_timeout: float = 30.0,
    elector=None,
    stop=None,
    exit_when_idle: bool = False,
) -> int:
    """The live scheduling loop: watch pending pods -> queue -> cycles.

    A feeder thread streams pending pods into the (thread-safe) queue;
    this loop runs a cycle whenever work is queued — bind-on-arrival like
    upstream, with whole-window batching for free because the queue
    accumulates while a cycle runs. A standby replica (elector held by
    another identity) keeps watching but never schedules — the
    active/passive failover contract of lease leader election
    (deploy/yoda-scheduler.yaml:10-17).

    Returns the number of cycles run. `stop` is an optional callable
    polled between iterations (tests; signal handlers).
    exit_when_idle=True returns once a full watch+relist round delivered
    nothing and the queue is drained — the one-shot "schedule what's
    pending" mode (CLI without --serve-forever).
    """
    cycles = 0
    feeder = _Feeder(
        sched, source, watch_timeout=watch_timeout, idle_sleep=idle_sleep,
        elector=elector,
    )
    feeder.start()
    was_leader = True
    try:
        while not (stop and stop()):
            if elector is not None and not elector.is_leader():
                if was_leader:
                    log.warning("not leader; pausing scheduling")
                    was_leader = False
                # drain anything queued before leadership was lost and
                # forget it (the feeder is gated while standby; promotion
                # re-submits from the server's pending set). A pipelined
                # scheduler may hold a prefetched window OUTSIDE the
                # queue — restore it first so the same drain covers it
                # (a stale window surviving standby would be scheduled
                # on re-promotion, double-binding pods the new leader
                # already placed)
                if hasattr(sched, "drain_pipeline"):
                    sched.drain_pipeline()
                for pod in sched.queue.pop_window(1 << 20):
                    feeder.discard(pod_key(pod))
                time.sleep(idle_sleep)
                continue
            if not was_leader:
                log.info("leadership (re)gained; resuming scheduling")
                was_leader = True
            # a pipelined scheduler's prefetched window counts as queued
            # work: parking on the feeder with it in hand would strand
            # real popped pods until an unrelated arrival
            if (
                len(sched.queue) == 0
                and getattr(sched, "_prefetched", None) is None
            ):
                if exit_when_idle and feeder.idle_rounds >= 1:
                    return cycles
                _idle_wait(sched, feeder, idle_sleep)
                continue
            try:
                m = sched.run_cycle()
            except Exception:
                # run_cycle requeues its window on source/advisor outages;
                # anything still escaping must not kill the loop
                log.exception("scheduling cycle failed; continuing")
                time.sleep(idle_sleep)
                continue
            cycles += 1
            bound = getattr(sched.binder, "bound", [])
            for b in bound:
                feeder.discard(b[0])
            del bound[:]   # drained: keeps per-cycle work O(this cycle)
            if max_cycles is not None and cycles >= max_cycles:
                return cycles
            if m.pods_in == 0:
                # only backoff pods remain: wait a full idle period (new
                # arrivals cut it short via the feeder's wake event)
                # rather than spinning empty cycles at 20Hz
                _idle_wait(sched, feeder, idle_sleep)
    finally:
        feeder.stop_evt.set()
        # any exit (stop(), max_cycles) with a prefetched window in hand
        # returns it to the queue, so len(queue) reflects reality and a
        # restarted loop (or a promoted replica sharing the scheduler)
        # reschedules the pods instead of stranding them
        if hasattr(sched, "drain_pipeline"):
            sched.drain_pipeline()
    return cycles
