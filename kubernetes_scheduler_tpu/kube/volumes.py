"""Volume-topology feasibility: bound PVs constrain pod placement.

The reference inherits VolumeZone / VolumeBinding from the embedded
upstream scheduler (/root/reference/go.mod:13); this framework folds the
same facts into the node-affinity tensors the engine already evaluates:
a pod whose PVC is Bound to a PV carrying node-affinity terms or
zone/region labels may only land on nodes satisfying them. The fold is a
pure OR-of-ANDs conjunction —

    (pod term_1 OR ...) AND (pv term_1 OR ...) = OR over the cross
    product of (pod term_i AND pv term_j)

— expressed with the per-expression OR-group ids PodBatch.na_term
carries, so the engine needs NO new kernel: VolumeZone rides the
node-affinity contraction.

WaitForFirstConsumer / unbound claims contribute no constraint
(constrain-at-bind: the volume follows the pod, upstream VolumeBinding's
WFFC stance). Claims are resolved when the pod is handed to the
scheduling queue (KubeClusterSource folds on the pending stream); a PVC
that binds while the pod is already queued is picked up on the next
relist round's resubmission.
"""

from __future__ import annotations

import dataclasses
import logging
import time

from kubernetes_scheduler_tpu.host.types import MatchExpression, Pod
from kubernetes_scheduler_tpu.kube.client import KubeApiError, KubeClient
from kubernetes_scheduler_tpu.kube.convert import pv_from_api, pvc_from_api

log = logging.getLogger("yoda_tpu.kube")


def fold_volume_terms(
    pod: Pod, pv_term_sets: list[list[list[MatchExpression]]]
) -> Pod:
    """Return a pod whose node_affinity is the conjunction of its own
    OR-of-ANDs requirement with every PV's OR-of-ANDs term set, via the
    cross-product expansion. Expressions are copied with fresh term ids;
    the input pod is not mutated."""
    if not pv_term_sets:
        return pod
    by_term: dict[int, list[MatchExpression]] = {}
    for e in pod.node_affinity:
        by_term.setdefault(e.term, []).append(e)
    base: list[list[MatchExpression]] = list(by_term.values()) or [[]]
    for terms in pv_term_sets:
        if not terms:
            continue
        base = [bt + et for bt in base for et in terms]
    merged: list[MatchExpression] = []
    for t_i, exprs in enumerate(base):
        for e in exprs:
            merged.append(
                MatchExpression(
                    key=e.key, operator=e.operator, values=list(e.values),
                    term=t_i,
                )
            )
    return dataclasses.replace(pod, node_affinity=merged)


class VolumeTopology:
    """PVC->PV resolution.

    With an InformerCache attached (the CLI kube path), claims/volumes
    come from its watch-fed stores — always current within watch lag
    (the same currency upstream's VolumeBinding plugin gets from ITS
    informers), and no LIST ever lands on the pending-pod path. Without
    one, a TTL-cached pair of cluster-wide LISTs serves the same facts
    (errors keep stale data and retry after a short backoff, never a
    full TTL of flying blind). A cluster without the PV API (or RBAC
    for it) degrades to no volume constraints."""

    ERROR_RETRY_SECONDS = 5.0

    def __init__(self, client: KubeClient, *, ttl: float = 30.0, cache=None):
        self.client = client
        self.ttl = ttl
        self.cache = cache
        self._pvcs: dict[str, object] = {}
        self._pvs: dict[str, object] = {}
        self._expiry = 0.0
        # StorageClass name -> volumeBindingMode (TTL path; the informer
        # path reads the cache's watch-fed store)
        self._classes: dict[str, str] = {}
        self._classes_expiry = 0.0

    def _refresh(self) -> None:
        now = time.monotonic()
        if now < self._expiry:
            return
        try:
            pvcs = self.client.list_all("/api/v1/persistentvolumeclaims")
            pvs = self.client.list_all("/api/v1/persistentvolumes")
        except KubeApiError as e:
            # keep whatever view we have; re-probe soon (a full TTL of
            # no-constraints after a transient blip risks out-of-zone
            # binds the kubelet then rejects)
            self._expiry = now + self.ERROR_RETRY_SECONDS
            log.warning(
                "volume topology LIST failed (%s); keeping the previous "
                "view and retrying in %.0fs", e, self.ERROR_RETRY_SECONDS,
            )
            return
        self._expiry = now + self.ttl
        fresh_pvcs = {}
        for o in pvcs:
            c = pvc_from_api(o)
            fresh_pvcs[f"{c.namespace}/{c.name}"] = c
        self._pvcs = fresh_pvcs
        self._pvs = {
            (v := pv_from_api(o)).name: v for o in pvs
        }

    def _maps(self) -> tuple[dict, dict]:
        if self.cache is not None:
            return self.cache.pvc_map(), self.cache.pv_map()
        self._refresh()
        return self._pvcs, self._pvs

    def _lookup(self, pvc_key: str, pv_name: str | None):
        """(pvc, pv) by key — point lookups against the informer stores
        (no full-map copies on the per-pod path), TTL maps otherwise."""
        if self.cache is not None:
            pvc = self.cache.get_pvc(pvc_key)
            pv = self.cache.get_pv(pv_name) if pv_name else None
            return pvc, pv
        self._refresh()
        pvc = self._pvcs.get(pvc_key)
        pv = self._pvs.get(pv_name) if pv_name else None
        return pvc, pv

    def storage_class_mode(self, name: str | None) -> str | None:
        """volumeBindingMode of a StorageClass; None = unknown (the WFFC
        handoff is then skipped — conservative)."""
        if not name:
            return None
        if self.cache is not None:
            return self.cache.storage_class_mode(name)
        now = time.monotonic()
        if now >= self._classes_expiry:
            try:
                items = self.client.list_all(
                    "/apis/storage.k8s.io/v1/storageclasses"
                )
                self._classes = {
                    (o.get("metadata") or {}).get("name", ""):
                        o.get("volumeBindingMode") or "Immediate"
                    for o in items
                }
                self._classes_expiry = now + self.ttl
            except KubeApiError as e:
                self._classes_expiry = now + self.ERROR_RETRY_SECONDS
                log.warning("storageclass LIST failed (%s)", e)
        return self._classes.get(name)

    def wffc_unbound(self, pod: Pod) -> list:
        """The pod's UNBOUND WaitForFirstConsumer claims — the set the
        binder must annotate with the chosen node before the Binding
        POST (upstream VolumeBinding's PreBind handoff)."""
        out = []
        for claim in pod.volume_claims:
            pvc, _ = self._lookup(f"{pod.namespace}/{claim}", None)
            if pvc is None or pvc.volume_name:
                continue
            if self.storage_class_mode(pvc.storage_class) == "WaitForFirstConsumer":
                out.append(pvc)
        return out

    def attach_demands(self, pod: Pod) -> dict[str, float]:
        """NodeVolumeLimits input: attachable-volumes-csi-<driver> units
        this pod's BOUND CSI volumes consume (one per volume), matching
        the capacity keys kubelet publishes in status.allocatable."""
        demands: dict[str, float] = {}
        for claim in pod.volume_claims:
            key = f"{pod.namespace}/{claim}"
            pvc, _ = self._lookup(key, None)
            if pvc is None or not pvc.volume_name:
                continue
            _, pv = self._lookup(key, pvc.volume_name)
            if pv is not None and pv.csi_driver:
                res = f"attachable-volumes-csi-{pv.csi_driver}"
                demands[res] = demands.get(res, 0.0) + 1.0
        return demands

    def fold(self, pod: Pod) -> Pod:
        """Pod with every bound claim's PV topology ANDed into its
        node-affinity requirement; claims that are unbound (WFFC) or
        reference unknown volumes contribute nothing. ReadWriteOncePod
        claims are recorded on Pod.exclusive_claims — the SCHEDULER
        enforces their exclusivity per cycle (host/scheduler.run_cycle),
        because a fold-time check races: two pods pending together would
        both fold before either holds the claim."""
        if not pod.volume_claims:
            return pod
        term_sets = []
        exclusive: list[str] = []
        for claim in pod.volume_claims:
            key = f"{pod.namespace}/{claim}"
            pvc, _ = self._lookup(key, None)
            if pvc is None:
                continue
            if "ReadWriteOncePod" in pvc.access_modes:
                exclusive.append(key)
            if not pvc.volume_name:
                continue  # unbound: constrain-at-bind
            _, pv = self._lookup(key, pvc.volume_name)
            if pv is not None and pv.terms:
                term_sets.append(pv.terms)
        # NodeVolumeLimits: the ONE accounting implementation
        demands = self.attach_demands(pod)
        out = fold_volume_terms(pod, term_sets)
        if (exclusive or demands) and out is pod:
            out = dataclasses.replace(pod)
        if exclusive:
            out.exclusive_claims = exclusive
        if demands:
            out.attach_demands = demands
        return out
