"""v1.Pod / v1.Node JSON -> host types.

The reference consumes these through client-go structs and schedutil
(CalculatePodResourceRequest, score/algorithm.go:238-262 reads
container/initContainer requests + overhead); here the same fields are
extracted from raw API JSON into host.types objects, with quantities
canonicalized the way the snapshot builder expects (cpu in millicores,
memory/storage in bytes, counts as floats).

Documented simplifications (each is a capability note, not an accident):
- node-affinity `nodeSelectorTerms` are OR-of-ANDs upstream; the host
  model is a single AND list, so the FIRST term's expressions are taken
  (plus `nodeSelector`, which upstream also ANDs in).
- pod-(anti)affinity label selectors support matchLabels (the form the
  SCV-era workloads use); matchExpressions on pod selectors are skipped.
- GPU cards come from the SCV CRD in the reference (filter.go:8); the
  core API carries no card inventory, so nodes converted here have no
  cards unless an SCV-style annotation ("scv/cards": JSON list) is set.
"""

from __future__ import annotations

import json
import logging

from kubernetes_scheduler_tpu.host.types import (
    Card,
    Container,
    MatchExpression,
    Node,
    Pod,
    PodAffinityTerm,
    SpreadConstraint,
    Taint,
    Toleration,
    WeightedExpression,
    parse_cpu_milli,
    parse_quantity,
)

log = logging.getLogger("yoda_tpu.kube")

BYTES_RESOURCES = ("memory", "ephemeral-storage", "storage")


def _requests(resources: dict | None) -> dict[str, float]:
    reqs = (resources or {}).get("requests") or {}
    out: dict[str, float] = {}
    for name, q in reqs.items():
        if name == "cpu":
            out[name] = parse_cpu_milli(q)
        else:
            out[name] = parse_quantity(q)
    return out


def _container(c: dict) -> Container:
    return Container(requests=_requests(c.get("resources")))


def _match_expr(e: dict) -> MatchExpression:
    return MatchExpression(
        key=e["key"], operator=e["operator"], values=list(e.get("values") or [])
    )


def _pod_affinity_terms(spec: dict, *, anti: bool) -> list[PodAffinityTerm]:
    sect = (spec.get("affinity") or {}).get(
        "podAntiAffinity" if anti else "podAffinity"
    ) or {}
    out: list[PodAffinityTerm] = []
    for term in sect.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
        labels = (term.get("labelSelector") or {}).get("matchLabels") or {}
        if labels:
            out.append(
                PodAffinityTerm(
                    match_labels=dict(labels),
                    topology_key=term.get("topologyKey", "kubernetes.io/hostname"),
                    anti=anti,
                )
            )
    for wt in sect.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        term = wt.get("podAffinityTerm") or {}
        labels = (term.get("labelSelector") or {}).get("matchLabels") or {}
        if labels:
            out.append(
                PodAffinityTerm(
                    match_labels=dict(labels),
                    topology_key=term.get("topologyKey", "kubernetes.io/hostname"),
                    anti=anti,
                    preferred=True,
                    weight=int(wt.get("weight", 1)),
                )
            )
    return out


def pod_from_api(obj: dict) -> Pod:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    node_aff = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    required: list[MatchExpression] = [
        MatchExpression(key=k, operator="In", values=[v])
        for k, v in (spec.get("nodeSelector") or {}).items()
    ]
    terms = (
        node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    ).get("nodeSelectorTerms") or []
    if terms:
        required.extend(_match_expr(e) for e in terms[0].get("matchExpressions") or [])
        if len(terms) > 1:
            log.debug(
                "pod %s: %d nodeSelectorTerms; only the first is enforced",
                meta.get("name"), len(terms),
            )
    preferred = [
        WeightedExpression(expr=_match_expr(e), weight=int(wt.get("weight", 1)))
        for wt in node_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []
        for e in (wt.get("preference") or {}).get("matchExpressions") or []
    ]
    spread = [
        SpreadConstraint(
            match_labels=dict(
                (c.get("labelSelector") or {}).get("matchLabels") or {}
            ),
            topology_key=c.get("topologyKey", "kubernetes.io/hostname"),
            max_skew=int(c.get("maxSkew", 1)),
        )
        for c in spec.get("topologySpreadConstraints") or []
        if c.get("whenUnsatisfiable", "DoNotSchedule") == "DoNotSchedule"
        and (c.get("labelSelector") or {}).get("matchLabels")
    ]
    host_ports = [
        int(p["hostPort"])
        for c in spec.get("containers") or []
        for p in c.get("ports") or []
        if p.get("hostPort")
    ]
    node_name = spec.get("nodeName") or None
    phase = (obj.get("status") or {}).get("phase", "")
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid"),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        containers=[_container(c) for c in spec.get("containers") or []],
        init_containers=[_container(c) for c in spec.get("initContainers") or []],
        overhead=_requests({"requests": spec.get("overhead") or {}}),
        tolerations=[
            Toleration(
                key=t.get("key"),
                value=t.get("value", ""),
                operator=t.get("operator", "Equal"),
                effect=t.get("effect", ""),
            )
            for t in spec.get("tolerations") or []
        ],
        node_affinity=required,
        pod_affinity=(
            _pod_affinity_terms(spec, anti=False)
            + _pod_affinity_terms(spec, anti=True)
        ),
        preferred_node_affinity=preferred,
        topology_spread=spread,
        # a PENDING pod carrying spec.nodeName is pinned (upstream
        # NodeName filter); once running the same field records placement
        target_node=node_name if phase in ("", "Pending") else None,
        host_ports=host_ports,
        node_name=node_name,
        scheduler_name=spec.get("schedulerName", "default-scheduler"),
    )


def pdb_from_api(obj: dict) -> "PodDisruptionBudget":
    """policy/v1 PodDisruptionBudget JSON -> host type (matchLabels AND
    matchExpressions, with k8s label-selector operator semantics)."""
    from kubernetes_scheduler_tpu.host.types import PodDisruptionBudget

    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    selector = spec.get("selector") or {}
    return PodDisruptionBudget(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        match_labels=dict(selector.get("matchLabels") or {}),
        match_expressions=[
            _match_expr(e) for e in selector.get("matchExpressions") or []
        ],
        min_available=spec.get("minAvailable"),
        max_unavailable=spec.get("maxUnavailable"),
        disruptions_allowed=status.get("disruptionsAllowed"),
    )


def node_from_api(obj: dict) -> Node:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    allocatable: dict[str, float] = {}
    for name, q in (status.get("allocatable") or {}).items():
        allocatable[name] = (
            parse_cpu_milli(q) if name == "cpu" else parse_quantity(q)
        )
    cards: list[Card] = []
    raw = (meta.get("annotations") or {}).get("scv/cards")
    if raw:
        try:
            cards = [Card(**c) for c in json.loads(raw)]
        except (json.JSONDecodeError, TypeError) as e:
            log.warning("node %s: bad scv/cards annotation: %s", meta.get("name"), e)
    return Node(
        name=meta.get("name", ""),
        labels=dict(meta.get("labels") or {}),
        taints=[
            Taint(
                key=t["key"],
                value=t.get("value", ""),
                effect=t.get("effect", "NoSchedule"),
            )
            for t in spec.get("taints") or []
        ],
        allocatable=allocatable,
        cards=cards,
    )
