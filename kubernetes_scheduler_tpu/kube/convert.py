"""v1.Pod / v1.Node JSON -> host types.

The reference consumes these through client-go structs and schedutil
(CalculatePodResourceRequest, score/algorithm.go:238-262 reads
container/initContainer requests + overhead); here the same fields are
extracted from raw API JSON into host.types objects, with quantities
canonicalized the way the snapshot builder expects (cpu in millicores,
memory/storage in bytes, counts as floats).

Documented simplifications (each is a capability note, not an accident):
- node-affinity `matchExpressions` AND `matchFields` (metadata.name
  selectors — the snapshot synthesizes a `metadata.name` label per
  node) carry full upstream OR-of-ANDs term semantics (pod_from_api).
- pod-(anti)affinity and spread label selectors support matchLabels AND
  matchExpressions (host/types.labels_match) with upstream namespace
  scoping (own namespace by default, explicit `namespaces` honored;
  a non-empty namespaceSelector resolves EXACTLY against the live
  namespace set via resolve_namespace_selectors — the k8s >= 1.21
  union-with-explicit-list semantics; only when namespace data is
  unavailable does it degrade to ALL namespaces, logged); spread
  carries both whenUnsatisfiable modes (DoNotSchedule hard,
  ScheduleAnyway soft).
- GPU cards come from the SCV CRD in the reference (filter.go:8); the
  core API carries no card inventory, so nodes converted here have no
  cards unless an SCV-style annotation ("scv/cards": JSON list) is set.
"""

from __future__ import annotations

import json
import logging

from kubernetes_scheduler_tpu.host.types import (
    Card,
    Container,
    MatchExpression,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodAffinityTerm,
    SpreadConstraint,
    Taint,
    Toleration,
    WeightedExpression,
    parse_cpu_milli,
    parse_quantity,
)

log = logging.getLogger("yoda_tpu.kube")

BYTES_RESOURCES = ("memory", "ephemeral-storage", "storage")


def _requests(resources: dict | None) -> dict[str, float]:
    reqs = (resources or {}).get("requests") or {}
    out: dict[str, float] = {}
    for name, q in reqs.items():
        if name == "cpu":
            out[name] = parse_cpu_milli(q)
        else:
            out[name] = parse_quantity(q)
    return out


def _container(c: dict) -> Container:
    return Container(
        requests=_requests(c.get("resources")), image=c.get("image") or ""
    )


def _match_expr(e: dict) -> MatchExpression:
    return MatchExpression(
        key=e["key"], operator=e["operator"], values=list(e.get("values") or [])
    )


def _term_namespaces(
    term: dict, own_namespace: str
) -> tuple[list[str] | None, tuple | None]:
    """Upstream PodAffinityTerm namespace scope -> (namespaces,
    namespace_selector). `{}` as namespaceSelector selects ALL
    namespaces (None); a NON-empty selector is captured as
    (match_labels, match_expressions) for
    `resolve_namespace_selectors` to union with the explicit list
    against the live namespace set — exact k8s >= 1.21 semantics (the
    round-4 ALL-namespaces approximation is gone). Without a selector:
    the explicit list, or the owning pod's own namespace."""
    sel = term.get("namespaceSelector")
    if sel is not None:
        if sel:
            captured = (
                dict(sel.get("matchLabels") or {}),
                [_match_expr(e) for e in sel.get("matchExpressions") or []],
            )
            return list(term.get("namespaces") or []), captured
        return None, None  # {} = all namespaces (exact)
    if term.get("namespaces"):
        return list(term["namespaces"]), None
    return [own_namespace], None


def resolve_namespace_selectors(
    pod: Pod, namespace_labels: dict[str, dict] | None
) -> Pod:
    """Resolve every pod-affinity term's namespaceSelector against the
    live namespace set (name -> labels): term.namespaces becomes the
    UNION of the explicit entries and the selector-matched namespaces —
    upstream InterPodAffinity's namespace scoping. A selector matching
    nothing (and no explicit entries) leaves an empty list, which
    matches no pods: required affinity is then unsatisfiable and anti
    trivially satisfied, as upstream.

    namespace_labels=None means no namespace data is available (informer
    unavailable / RBAC missing): degrade to the ALL-namespaces
    approximation, logged — over-admits affinity and over-constrains
    anti-affinity, the conservative pre-informer stance."""
    import dataclasses

    if not any(t.namespace_selector for t in pod.pod_affinity):
        return pod
    terms = []
    for t in pod.pod_affinity:
        if not t.namespace_selector:
            terms.append(t)
            continue
        if namespace_labels is None:
            log.warning(
                "pod %s/%s: no namespace data; namespaceSelector "
                "approximated as ALL namespaces",
                pod.namespace, pod.name,
            )
            terms.append(dataclasses.replace(t, namespaces=None))
            continue
        from kubernetes_scheduler_tpu.host.types import labels_match

        ml, mx = t.namespace_selector
        matched = {
            name
            for name, labels in namespace_labels.items()
            if labels_match(labels, ml, mx)
        }
        terms.append(
            dataclasses.replace(
                t, namespaces=sorted(matched | set(t.namespaces or ()))
            )
        )
    return dataclasses.replace(pod, pod_affinity=terms)


def _pod_affinity_terms(
    spec: dict, *, anti: bool, namespace: str
) -> list[PodAffinityTerm]:
    sect = (spec.get("affinity") or {}).get(
        "podAntiAffinity" if anti else "podAffinity"
    ) or {}
    out: list[PodAffinityTerm] = []

    def selector(term):
        sel = term.get("labelSelector") or {}
        labels = dict(sel.get("matchLabels") or {})
        exprs = [_match_expr(e) for e in sel.get("matchExpressions") or []]
        return (labels, exprs) if labels or exprs else None

    for term in sect.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
        got = selector(term)
        if got:
            ns, ns_sel = _term_namespaces(term, namespace)
            out.append(
                PodAffinityTerm(
                    match_labels=got[0],
                    match_expressions=got[1],
                    topology_key=term.get("topologyKey", "kubernetes.io/hostname"),
                    anti=anti,
                    namespaces=ns,
                    namespace_selector=ns_sel,
                )
            )
    for wt in sect.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        term = wt.get("podAffinityTerm") or {}
        got = selector(term)
        if got:
            ns, ns_sel = _term_namespaces(term, namespace)
            out.append(
                PodAffinityTerm(
                    match_labels=got[0],
                    match_expressions=got[1],
                    topology_key=term.get("topologyKey", "kubernetes.io/hostname"),
                    anti=anti,
                    preferred=True,
                    weight=int(wt.get("weight", 1)),
                    namespaces=ns,
                    namespace_selector=ns_sel,
                )
            )
    return out


def pod_from_api(obj: dict) -> Pod:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    node_aff = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    # upstream semantics: `nodeSelector` (a plain AND map) and
    # `nodeSelectorTerms` (OR of AND-lists) must BOTH pass. The host model
    # is a flat expression list with per-expression OR-group ids
    # (MatchExpression.term: AND within a group, OR across groups), so
    # the nodeSelector conjunct is replicated into every term — exactly
    # "nodeSelector AND (term_0 OR term_1 OR ...)". A term with no
    # matchExpressions matches NOTHING upstream ("a null or empty node
    # selector term matches no objects"): encoded as In with an empty
    # value set, which no node satisfies.
    ns_exprs: list[MatchExpression] = [
        MatchExpression(key=k, operator="In", values=[v])
        for k, v in (spec.get("nodeSelector") or {}).items()
    ]
    terms = (
        node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    ).get("nodeSelectorTerms") or []
    required: list[MatchExpression] = []
    if terms:
        for t_i, term in enumerate(terms):
            # matchFields (metadata.name selectors) evaluate through the
            # same expression kernel: the snapshot synthesizes a
            # `metadata.name` label per node
            t_exprs = [
                _match_expr(e)
                for e in (term.get("matchExpressions") or [])
                + (term.get("matchFields") or [])
            ]
            if not t_exprs:
                t_exprs = [MatchExpression(key="", operator="In", values=[])]
            t_exprs += [
                MatchExpression(key=x.key, operator=x.operator, values=list(x.values))
                for x in ns_exprs
            ]
            for e in t_exprs:
                e.term = t_i
                required.append(e)
    else:
        required = ns_exprs
    # preferred terms keep upstream weighted-AND-list semantics: every
    # expression of a preference entry shares one group id, so the weight
    # is granted once iff the whole entry matches. Group ids are DENSE
    # over non-empty entries (an empty entry must not shift later ids
    # past the builder's expression-count bound)
    preferred: list[WeightedExpression] = []
    t_dense = 0
    for wt in node_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        pref = wt.get("preference") or {}
        exprs = (pref.get("matchExpressions") or []) + (
            pref.get("matchFields") or []
        )
        if not exprs:
            continue
        for e in exprs:
            preferred.append(
                WeightedExpression(
                    expr=_match_expr(e),
                    weight=int(wt.get("weight", 1)),
                    term=t_dense,
                )
            )
        t_dense += 1
    spread = [
        SpreadConstraint(
            match_labels=dict(
                (c.get("labelSelector") or {}).get("matchLabels") or {}
            ),
            match_expressions=[
                _match_expr(e)
                for e in (c.get("labelSelector") or {}).get("matchExpressions")
                or []
            ],
            topology_key=c.get("topologyKey", "kubernetes.io/hostname"),
            max_skew=int(c.get("maxSkew", 1)),
            # ScheduleAnyway = a soft score term (engine soft spread);
            # DoNotSchedule = a hard filter
            soft=c.get("whenUnsatisfiable", "DoNotSchedule") == "ScheduleAnyway",
            # upstream spread selectors match only the pod's own namespace
            namespaces=[meta.get("namespace", "default")],
        )
        for c in spec.get("topologySpreadConstraints") or []
        if (c.get("labelSelector") or {}).get("matchLabels")
        or (c.get("labelSelector") or {}).get("matchExpressions")
    ]
    host_ports = [
        int(p["hostPort"])
        for c in spec.get("containers") or []
        for p in c.get("ports") or []
        if p.get("hostPort")
    ]
    volume_claims = [
        v["persistentVolumeClaim"]["claimName"]
        for v in spec.get("volumes") or []
        if (v.get("persistentVolumeClaim") or {}).get("claimName")
    ]
    node_name = spec.get("nodeName") or None
    status = obj.get("status") or {}
    phase = status.get("phase", "")
    start_time = None
    raw_start = status.get("startTime")
    if raw_start:
        try:
            import datetime

            start_time = datetime.datetime.fromisoformat(
                raw_start.replace("Z", "+00:00")
            ).timestamp()
        except ValueError:
            log.warning(
                "pod %s: unparsable status.startTime %r", meta.get("name"),
                raw_start,
            )
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid"),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        containers=[_container(c) for c in spec.get("containers") or []],
        init_containers=[_container(c) for c in spec.get("initContainers") or []],
        overhead=_requests({"requests": spec.get("overhead") or {}}),
        tolerations=[
            Toleration(
                key=t.get("key"),
                value=t.get("value", ""),
                operator=t.get("operator", "Equal"),
                effect=t.get("effect", ""),
            )
            for t in spec.get("tolerations") or []
        ],
        node_affinity=required,
        pod_affinity=(
            _pod_affinity_terms(
                spec, anti=False, namespace=meta.get("namespace", "default")
            )
            + _pod_affinity_terms(
                spec, anti=True, namespace=meta.get("namespace", "default")
            )
        ),
        preferred_node_affinity=preferred,
        topology_spread=spread,
        # a PENDING pod carrying spec.nodeName is pinned (upstream
        # NodeName filter); once running the same field records placement
        target_node=node_name if phase in ("", "Pending") else None,
        host_ports=host_ports,
        node_name=node_name,
        scheduler_name=spec.get("schedulerName", "default-scheduler"),
        start_time=start_time,
        volume_claims=volume_claims,
        # spec.priority is the API-server-resolved PriorityClass value;
        # host/queue.pod_priority prefers it over the scv/priority label
        priority=spec.get("priority"),
        owner=next(
            (
                (o.get("kind", ""), o.get("name", ""))
                for o in meta.get("ownerReferences") or []
                if o.get("controller")
            ),
            None,
        ),
    )


# topology labels the VolumeZone family matches between a PV and nodes
_ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)


def pv_from_api(obj: dict) -> PersistentVolume:
    """PV -> scheduling constraint: spec.nodeAffinity.required terms
    (local volumes) with the PV's zone/region labels (VolumeZone) ANDed
    into every term — exactly how the pod-side OR-of-ANDs conversion
    treats nodeSelector. A PV with neither contributes no constraint."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    raw_terms = (
        (spec.get("nodeAffinity") or {}).get("required") or {}
    ).get("nodeSelectorTerms") or []
    terms: list[list[MatchExpression]] = []
    for t in raw_terms:
        exprs = [_match_expr(e) for e in t.get("matchExpressions") or []]
        if not exprs:
            exprs = [MatchExpression(key="", operator="In", values=[])]
        terms.append(exprs)
    zone_exprs = [
        MatchExpression(key=k, operator="In", values=[v])
        for k, v in (meta.get("labels") or {}).items()
        if k in _ZONE_LABELS
    ]
    if zone_exprs:
        terms = (
            [t + zone_exprs for t in terms] if terms else [zone_exprs]
        )
    return PersistentVolume(
        name=meta.get("name", ""),
        terms=terms,
        csi_driver=((spec.get("csi") or {}).get("driver") or ""),
    )


def pvc_from_api(obj: dict) -> PersistentVolumeClaim:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    return PersistentVolumeClaim(
        namespace=meta.get("namespace", "default"),
        name=meta.get("name", ""),
        volume_name=spec.get("volumeName") or None,
        access_modes=list(spec.get("accessModes") or []),
        storage_class=spec.get("storageClassName") or None,
        selected_node=(meta.get("annotations") or {}).get(
            "volume.kubernetes.io/selected-node"
        ),
    )


def pdb_from_api(obj: dict) -> "PodDisruptionBudget":
    """policy/v1 PodDisruptionBudget JSON -> host type (matchLabels AND
    matchExpressions, with k8s label-selector operator semantics)."""
    from kubernetes_scheduler_tpu.host.types import PodDisruptionBudget

    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    selector = spec.get("selector") or {}
    return PodDisruptionBudget(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        match_labels=dict(selector.get("matchLabels") or {}),
        match_expressions=[
            _match_expr(e) for e in selector.get("matchExpressions") or []
        ],
        min_available=spec.get("minAvailable"),
        max_unavailable=spec.get("maxUnavailable"),
        disruptions_allowed=status.get("disruptionsAllowed"),
    )


def node_from_api(obj: dict) -> Node:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    allocatable: dict[str, float] = {}
    for name, q in (status.get("allocatable") or {}).items():
        allocatable[name] = (
            parse_cpu_milli(q) if name == "cpu" else parse_quantity(q)
        )
    cards: list[Card] = []
    raw = (meta.get("annotations") or {}).get("scv/cards")
    if raw:
        try:
            cards = [Card(**c) for c in json.loads(raw)]
        except (json.JSONDecodeError, TypeError) as e:
            log.warning("node %s: bad scv/cards annotation: %s", meta.get("name"), e)
    taints = [
        Taint(
            key=t["key"],
            value=t.get("value", ""),
            effect=t.get("effect", "NoSchedule"),
        )
        for t in spec.get("taints") or []
    ]
    # node.status.images -> ImageLocality input: every name alias of an
    # image entry maps to its size (upstream keys its image states by
    # every listed name too)
    images: dict[str, float] = {}
    for entry in status.get("images") or []:
        size = float(entry.get("sizeBytes") or 0)
        for alias in entry.get("names") or []:
            images[alias] = size
    # cordoned node (kubectl cordon sets spec.unschedulable): upstream's
    # NodeUnschedulable plugin filters it, tolerable via the well-known
    # taint key — expressed here as exactly that taint, so the existing
    # toleration machinery carries the semantics (a pod tolerating
    # node.kubernetes.io/unschedulable still lands, like upstream)
    if spec.get("unschedulable") and not any(
        t.key == "node.kubernetes.io/unschedulable" for t in taints
    ):
        taints.append(
            Taint(key="node.kubernetes.io/unschedulable", effect="NoSchedule")
        )
    return Node(
        name=meta.get("name", ""),
        labels=dict(meta.get("labels") or {}),
        taints=taints,
        allocatable=allocatable,
        cards=cards,
        images=images,
    )
