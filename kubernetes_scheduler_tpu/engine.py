"""The batch scheduling engine: one jitted program per cycle.

This is the TPU replacement for the reference's entire scheduling cycle
(pkg/yoda/scheduler.go:91-196 plus the upstream per-node fan-out): for a
window of pending pods and a cluster snapshot, one device program computes

    utilization stats  ->  feasibility masks  ->  policy scores
    ->  normalization  ->  capacity-aware assignment

and returns pod->node bindings. What the reference does with O(pods x nodes)
plugin calls, 5.(N+1) Prometheus HTTP requests per pod (scheduler.go:104,126)
and O(N) Redis round-trips per score (algorithm.go:57-89), this does with
one host->device transfer and one XLA executable launch.

All shapes are static per (pod-bucket, node-bucket) pair — the host pads
with masks (utils/padding.py) so recompiles happen only at bucket
boundaries.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kubernetes_scheduler_tpu.ops import (
    balanced_cpu_diskio,
    balanced_diskio,
    card_fit,
    card_score,
    collect_max_card_values,
    free_capacity,
    min_max_normalize,
    resource_fit,
    utilization_stats,
)
from kubernetes_scheduler_tpu.ops.assign import AssignResult, auction_assign, greedy_assign
from kubernetes_scheduler_tpu.ops.normalize import softmax_normalize

POLICIES = ("balanced_cpu_diskio", "balanced_diskio", "free_capacity", "card")
ASSIGNERS = ("greedy", "auction")
NORMALIZERS = ("min_max", "softmax", "none")


class SnapshotArrays(NamedTuple):
    """Dense node-side cluster state, built by host.snapshot each cycle.

    The advisor's five Prometheus series (advisor/advisor.go:16-20) land in
    disk_io/cpu_pct/mem_pct/net_up/net_down; the scheduler-framework node
    snapshot (Allocatable / NonZeroRequested, algorithm.go:209-233) lands in
    allocatable/requested; the SCV card list becomes the cards tensor.
    """

    allocatable: jnp.ndarray   # [n, r] float32
    requested: jnp.ndarray     # [n, r] float32 (non-zero defaults applied)
    disk_io: jnp.ndarray       # [n] float32 MB/s
    cpu_pct: jnp.ndarray       # [n] float32 %
    mem_pct: jnp.ndarray       # [n] float32 %
    net_up: jnp.ndarray        # [n] float32 MB/s
    net_down: jnp.ndarray      # [n] float32 MB/s
    node_mask: jnp.ndarray     # [n] bool
    cards: jnp.ndarray         # [n, c, 6] float32
    card_mask: jnp.ndarray     # [n, c] bool
    card_healthy: jnp.ndarray  # [n, c] bool


class PodBatch(NamedTuple):
    """Dense pending-pod window, built by host.snapshot each cycle."""

    request: jnp.ndarray      # [p, r] float32 (non-zero defaults applied)
    r_io: jnp.ndarray         # [p] float32, `diskIO` annotation MB/s
    priority: jnp.ndarray     # [p] int32, `scv/priority` label (sort.go:12-18)
    pod_mask: jnp.ndarray     # [p] bool
    want_number: jnp.ndarray  # [p] int32 (0 = no GPU demand)
    want_memory: jnp.ndarray  # [p] float32 (-1 = label absent)
    want_clock: jnp.ndarray   # [p] float32 (-1 = label absent)


class ScheduleResult(NamedTuple):
    node_idx: jnp.ndarray     # [p] int32 assigned node, -1 = unschedulable
    scores: jnp.ndarray       # [p, n] normalized scores
    raw_scores: jnp.ndarray   # [p, n] policy scores before normalization
    feasible: jnp.ndarray     # [p, n] bool
    free_after: jnp.ndarray   # [n, r]
    n_assigned: jnp.ndarray   # [] int32


def compute_scores(
    snapshot: SnapshotArrays, pods: PodBatch, policy: str
) -> jnp.ndarray:
    """Policy dispatch (static): the reference's commented-out alternates in
    CalculateScore (algorithm.go:90-96) become first-class selectable
    kernels."""
    stats = utilization_stats(snapshot.disk_io, snapshot.cpu_pct, snapshot.node_mask)
    if policy == "balanced_cpu_diskio":
        return balanced_cpu_diskio(stats, pods.request[:, 0], pods.r_io)
    if policy == "balanced_diskio":
        return balanced_diskio(stats, snapshot.disk_io, pods.r_io, snapshot.node_mask)
    if policy == "free_capacity":
        s = free_capacity(snapshot.cpu_pct, snapshot.mem_pct, snapshot.disk_io)
        return jnp.broadcast_to(s[None, :], (pods.request.shape[0], s.shape[0]))
    if policy == "card":
        node_fits, per_card = card_fit(
            snapshot.cards, snapshot.card_mask, snapshot.card_healthy,
            pods.want_number, pods.want_memory, pods.want_clock,
        )
        maxima = collect_max_card_values(
            snapshot.cards, per_card & node_fits[:, :, None]
        )
        return card_score(snapshot.cards, snapshot.card_mask, per_card, maxima)
    raise ValueError(f"unknown policy {policy!r}; expected one of {POLICIES}")


def compute_feasibility(snapshot: SnapshotArrays, pods: PodBatch) -> jnp.ndarray:
    """All filter masks ANDed: resource fit (NodeResourcesFit semantics,
    algorithm.go:209-262) and GPU-card predicates (filter.go:11-58)."""
    fits = resource_fit(
        snapshot.allocatable, snapshot.requested, pods.request, snapshot.node_mask
    )
    gpu_fits, _ = card_fit(
        snapshot.cards, snapshot.card_mask, snapshot.card_healthy,
        pods.want_number, pods.want_memory, pods.want_clock,
    )
    return fits & gpu_fits & pods.pod_mask[:, None]


def compute_free_capacity(snapshot: SnapshotArrays) -> jnp.ndarray:
    """[n, r] free capacity for assignment; padded nodes get 0."""
    return jnp.where(
        snapshot.node_mask[:, None],
        snapshot.allocatable - snapshot.requested,
        0.0,
    )


@functools.partial(
    jax.jit, static_argnames=("policy", "assigner", "normalizer")
)
def schedule_batch(
    snapshot: SnapshotArrays,
    pods: PodBatch,
    *,
    policy: str = "balanced_cpu_diskio",
    assigner: str = "greedy",
    normalizer: str = "min_max",
) -> ScheduleResult:
    """One scheduling cycle for the whole pending window, on device."""
    raw = compute_scores(snapshot, pods, policy)
    feasible = compute_feasibility(snapshot, pods)
    if normalizer == "min_max":
        norm = min_max_normalize(raw, snapshot.node_mask)
    elif normalizer == "softmax":
        norm = softmax_normalize(raw, snapshot.node_mask)
    elif normalizer == "none":
        norm = raw
    else:
        raise ValueError(f"unknown normalizer {normalizer!r}")

    free = compute_free_capacity(snapshot)
    assign_fn = {"greedy": greedy_assign, "auction": auction_assign}[assigner]
    res: AssignResult = assign_fn(
        norm, feasible, pods.request, free, pods.priority, pods.pod_mask
    )
    return ScheduleResult(
        node_idx=res.node_idx,
        scores=norm,
        raw_scores=raw,
        feasible=feasible,
        free_after=res.free_after,
        n_assigned=res.n_assigned,
    )
