"""The batch scheduling engine: one jitted program per cycle.

This is the TPU replacement for the reference's entire scheduling cycle
(pkg/yoda/scheduler.go:91-196 plus the upstream per-node fan-out): for a
window of pending pods and a cluster snapshot, one device program computes

    utilization stats  ->  feasibility masks  ->  policy scores
    ->  normalization  ->  capacity-aware assignment

and returns pod->node bindings. What the reference does with O(pods x nodes)
plugin calls, 5.(N+1) Prometheus HTTP requests per pod (scheduler.go:104,126)
and O(N) Redis round-trips per score (algorithm.go:57-89), this does with
one host->device transfer and one XLA executable launch.

All shapes are static per (pod-bucket, node-bucket) pair — the host pads
with masks (utils/padding.py) so recompiles happen only at bucket
boundaries.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_scheduler_tpu.ops import (
    balanced_cpu_diskio,
    balanced_diskio,
    card_fit,
    card_score,
    collect_max_card_values,
    free_capacity,
    min_max_normalize,
    resource_fit,
    utilization_stats,
)
from kubernetes_scheduler_tpu.ops.score import (
    balanced_allocation,
    image_locality,
    least_allocated,
)
from kubernetes_scheduler_tpu.ops.assign import (
    AffinityState,
    AssignResult,
    anti_reverse_bad,
    auction_assign,
    greedy_assign,
    pod_has_anti_onehot,
)
from kubernetes_scheduler_tpu.ops.constraints import (
    node_affinity_fit,
    node_affinity_preference,
    node_name_fit,
    pod_affinity_fit,
    pod_affinity_preference,
    prefer_no_schedule_penalty,
    taint_toleration_fit,
    topology_spread_fit,
)
from kubernetes_scheduler_tpu.ops.normalize import softmax_normalize
from kubernetes_scheduler_tpu.ops.assign import NEG

POLICIES = (
    "balanced_cpu_diskio", "balanced_diskio", "free_capacity", "card",
    "least_allocated", "balanced_allocation", "image_locality",
)
ASSIGNERS = ("greedy", "auction")
NORMALIZERS = ("min_max", "softmax", "none")
# plugins whose raw output is already on the framework's [0, 100]
# MaxNodeScore scale (upstream runs NO NormalizeScore extension for
# them); everything else min-max normalizes per pod before weighting,
# like the framework runtime does for plugins with ScoreExtensions
PRESCALED_PLUGINS = (
    "least_allocated", "balanced_allocation", "image_locality",
    "balanced_diskio",
)


class SnapshotArrays(NamedTuple):
    """Dense node-side cluster state, built by host.snapshot each cycle.

    The advisor's five Prometheus series (advisor/advisor.go:16-20) land in
    disk_io/cpu_pct/mem_pct/net_up/net_down; the scheduler-framework node
    snapshot (Allocatable / NonZeroRequested, algorithm.go:209-233) lands in
    allocatable/requested; the SCV card list becomes the cards tensor.
    """

    allocatable: jnp.ndarray   # [n, r] float32
    requested: jnp.ndarray     # [n, r] float32 (non-zero defaults applied)
    disk_io: jnp.ndarray       # [n] float32 MB/s
    cpu_pct: jnp.ndarray       # [n] float32 %
    mem_pct: jnp.ndarray       # [n] float32 %
    net_up: jnp.ndarray        # [n] float32 MB/s
    net_down: jnp.ndarray      # [n] float32 MB/s
    node_mask: jnp.ndarray     # [n] bool
    cards: jnp.ndarray         # [n, c, 6] float32
    card_mask: jnp.ndarray     # [n, c] bool
    card_healthy: jnp.ndarray  # [n, c] bool
    # constraint state (ops/constraints.py encodings; empty via make_snapshot)
    taints: jnp.ndarray           # [n, T, 3] int32 (key, value, effect)
    taint_mask: jnp.ndarray       # [n, T] bool
    node_labels: jnp.ndarray      # [n, Ln, 2] int32 (key, value)
    node_label_mask: jnp.ndarray  # [n, Ln] bool
    domain_counts: jnp.ndarray    # [n, S] float32 selector match counts
    domain_id: jnp.ndarray        # [n, S] int32 topology-domain id per selector
    # [n, S] float32: running pods in node n's domain whose REQUIRED
    # anti-affinity terms use selector s ("avoiders"). k8s checks both
    # directions: an incoming pod matching s may not land in a domain
    # holding an avoider of s (upstream InterPodAffinity's
    # existing-anti-affinity check), symmetric to domain_counts gating
    # the incoming pod's own anti terms.
    avoid_counts: jnp.ndarray
    # [n, S] float32 summed WEIGHTS of running pods' PREFERRED
    # (anti-)affinity terms using selector s in node n's domain — the
    # symmetric half of upstream InterPodAffinity scoring: an incoming pod
    # matching s gains pref_attract[n, s] and loses pref_avoid[n, s]
    # (engine.compute_soft_scores).
    pref_attract: jnp.ndarray
    pref_avoid: jnp.ndarray
    # [n, V] float32 image-locality signal (upstream ImageLocality via
    # go.mod:13): present(n, v) * sizeBytes * (nodes holding v) / n — the
    # spread ratio is resolved host-side (host/snapshot) so the kernel
    # shards along the node axis with no collective. V = interned image
    # vocabulary (bucketed); all-zeros [n, 1] when image data is absent.
    image_scaled: jnp.ndarray


class PodBatch(NamedTuple):
    """Dense pending-pod window, built by host.snapshot each cycle."""

    request: jnp.ndarray      # [p, r] float32 (non-zero defaults applied)
    r_io: jnp.ndarray         # [p] float32, `diskIO` annotation MB/s
    priority: jnp.ndarray     # [p] int32, `scv/priority` label (sort.go:12-18)
    pod_mask: jnp.ndarray     # [p] bool
    want_number: jnp.ndarray  # [p] int32 (0 = no GPU demand)
    want_memory: jnp.ndarray  # [p] float32 (-1 = label absent)
    want_clock: jnp.ndarray   # [p] float32 (-1 = label absent)
    # constraint demands (ops/constraints.py encodings; empty via make_pod_batch)
    tolerations: jnp.ndarray       # [p, L, 4] int32 (key, value, op, effect)
    tol_mask: jnp.ndarray          # [p, L] bool
    na_key: jnp.ndarray            # [p, E] int32 node-affinity expr keys
    na_op: jnp.ndarray             # [p, E] int32 (In/NotIn/Exists/DoesNotExist)
    na_vals: jnp.ndarray           # [p, E, V] int32 value-id sets
    na_val_mask: jnp.ndarray       # [p, E, V] bool
    na_mask: jnp.ndarray           # [p, E] bool
    na_term: jnp.ndarray           # [p, E] int32 OR-group ids (upstream
    #                                nodeSelectorTerms: AND within a group,
    #                                OR across groups; all-zeros = one AND
    #                                list)
    affinity_sel: jnp.ndarray      # [p, K] int32 selector ids, -1 pad
    anti_affinity_sel: jnp.ndarray  # [p, K] int32 selector ids, -1 pad
    pod_matches: jnp.ndarray       # [p, S] bool — pod's labels match selector s
    # soft (preferred) constraints — score terms, never masks
    # (compute_soft_scores; upstream preferredDuringScheduling semantics)
    pna_key: jnp.ndarray           # [p, Ep] preferred node-affinity expr keys
    pna_op: jnp.ndarray            # [p, Ep]
    pna_vals: jnp.ndarray          # [p, Ep, V]
    pna_val_mask: jnp.ndarray      # [p, Ep, V] bool
    pna_mask: jnp.ndarray          # [p, Ep] bool
    pna_weight: jnp.ndarray        # [p, Ep] float32 term weights
    pna_term: jnp.ndarray          # [p, Ep] int32 preferred-term group ids
    #                                (AND within a group, weight granted
    #                                once per satisfied group; default =
    #                                each expression its own term)
    pref_affinity_sel: jnp.ndarray   # [p, K] int32 selector ids, -1 pad
    pref_affinity_weight: jnp.ndarray  # [p, K] float32
    pref_anti_sel: jnp.ndarray       # [p, K] int32 selector ids, -1 pad
    pref_anti_weight: jnp.ndarray    # [p, K] float32
    # upstream NodeName / PodTopologySpread filters (hostPort conflicts —
    # upstream NodePorts — are capacity-1 pseudo-resource columns built by
    # host.snapshot, needing no engine support)
    target_node: jnp.ndarray         # [p] int32: -1 unpinned, else node idx
    spread_sel: jnp.ndarray          # [p, Ks] int32 selector ids, -1 pad
    spread_max: jnp.ndarray          # [p, Ks] int32 maxSkew per constraint
    # ScheduleAnyway spread constraints: a score term, never a filter
    # (upstream PodTopologySpread scoring; compute_soft_scores)
    soft_spread_sel: jnp.ndarray     # [p, Kss] int32 selector ids, -1 pad
    # ImageLocality inputs (ops/score.image_locality): the pod's container
    # image ids into the snapshot's image vocabulary, and the container
    # count scaling the upstream 23MB..1000MB-per-container ramp
    image_ids: jnp.ndarray           # [p, Ki] int32 image ids, -1 pad
    n_containers: jnp.ndarray        # [p] int32
    # gang co-scheduling (ops/gang.py): window-local gang slot (-1 = not
    # in a gang) and the gang's declared member count — finish_cycle
    # rescinds every placement of a gang that did not fully fit
    gang_id: jnp.ndarray             # [p] int32, -1 = no gang
    gang_size: jnp.ndarray           # [p] int32


def make_snapshot(
    allocatable,
    requested,
    disk_io,
    cpu_pct,
    mem_pct,
    *,
    net_up=None,
    net_down=None,
    node_mask=None,
    cards=None,
    card_mask=None,
    card_healthy=None,
    taints=None,
    taint_mask=None,
    node_labels=None,
    node_label_mask=None,
    domain_counts=None,
    domain_id=None,
    avoid_counts=None,
    pref_attract=None,
    pref_avoid=None,
    image_scaled=None,
) -> SnapshotArrays:
    """SnapshotArrays with no-op defaults for everything optional (no cards,
    no taints, no labels, no selector counts)."""
    n = allocatable.shape[0]
    z = lambda *shape: jnp.zeros(shape, jnp.float32)  # noqa: E731
    zi = lambda *shape: jnp.zeros(shape, jnp.int32)  # noqa: E731
    zb = lambda *shape: jnp.zeros(shape, bool)  # noqa: E731
    return SnapshotArrays(
        allocatable=jnp.asarray(allocatable, jnp.float32),
        requested=jnp.asarray(requested, jnp.float32),
        disk_io=jnp.asarray(disk_io, jnp.float32),
        cpu_pct=jnp.asarray(cpu_pct, jnp.float32),
        mem_pct=jnp.asarray(mem_pct, jnp.float32),
        net_up=z(n) if net_up is None else jnp.asarray(net_up, jnp.float32),
        net_down=z(n) if net_down is None else jnp.asarray(net_down, jnp.float32),
        node_mask=jnp.ones(n, bool) if node_mask is None else jnp.asarray(node_mask, bool),
        cards=z(n, 1, 6) if cards is None else jnp.asarray(cards, jnp.float32),
        # a provided payload with an omitted mask defaults to all-valid —
        # a zero-mask default would silently disable the constraint
        card_mask=(
            (zb(n, 1) if cards is None else jnp.ones(jnp.asarray(cards).shape[:2], bool))
            if card_mask is None else jnp.asarray(card_mask, bool)
        ),
        card_healthy=(
            (zb(n, 1) if cards is None else jnp.ones(jnp.asarray(cards).shape[:2], bool))
            if card_healthy is None else jnp.asarray(card_healthy, bool)
        ),
        taints=zi(n, 1, 3) if taints is None else jnp.asarray(taints, jnp.int32),
        taint_mask=(
            (zb(n, 1) if taints is None else jnp.ones(jnp.asarray(taints).shape[:2], bool))
            if taint_mask is None else jnp.asarray(taint_mask, bool)
        ),
        node_labels=zi(n, 1, 2) if node_labels is None else jnp.asarray(node_labels, jnp.int32),
        node_label_mask=(
            (zb(n, 1) if node_labels is None
             else jnp.ones(jnp.asarray(node_labels).shape[:2], bool))
            if node_label_mask is None else jnp.asarray(node_label_mask, bool)
        ),
        domain_counts=z(n, 1) if domain_counts is None else jnp.asarray(domain_counts, jnp.float32),
        # default: every node its own domain (hostname topology)
        domain_id=(
            jnp.broadcast_to(
                jnp.arange(n, dtype=jnp.int32)[:, None],
                (n, 1 if domain_counts is None else jnp.asarray(domain_counts).shape[1]),
            )
            if domain_id is None
            else jnp.asarray(domain_id, jnp.int32)
        ),
        avoid_counts=(
            z(n, 1 if domain_counts is None else jnp.asarray(domain_counts).shape[1])
            if avoid_counts is None
            else jnp.asarray(avoid_counts, jnp.float32)
        ),
        pref_attract=(
            z(n, 1 if domain_counts is None else jnp.asarray(domain_counts).shape[1])
            if pref_attract is None
            else jnp.asarray(pref_attract, jnp.float32)
        ),
        pref_avoid=(
            z(n, 1 if domain_counts is None else jnp.asarray(domain_counts).shape[1])
            if pref_avoid is None
            else jnp.asarray(pref_avoid, jnp.float32)
        ),
        image_scaled=(
            z(n, 1) if image_scaled is None
            else jnp.asarray(image_scaled, jnp.float32)
        ),
    )


def make_pod_batch(
    request,
    *,
    r_io=None,
    priority=None,
    pod_mask=None,
    want_number=None,
    want_memory=None,
    want_clock=None,
    tolerations=None,
    tol_mask=None,
    na_key=None,
    na_op=None,
    na_vals=None,
    na_val_mask=None,
    na_mask=None,
    na_term=None,
    affinity_sel=None,
    anti_affinity_sel=None,
    pod_matches=None,
    pna_key=None,
    pna_op=None,
    pna_vals=None,
    pna_val_mask=None,
    pna_mask=None,
    pna_weight=None,
    pna_term=None,
    pref_affinity_sel=None,
    pref_affinity_weight=None,
    pref_anti_sel=None,
    pref_anti_weight=None,
    target_node=None,
    spread_sel=None,
    spread_max=None,
    soft_spread_sel=None,
    image_ids=None,
    n_containers=None,
    gang_id=None,
    gang_size=None,
) -> PodBatch:
    """PodBatch with no-op defaults (no GPU demand, no tolerations, no
    affinity requirements, no preferences)."""
    p = request.shape[0]
    z = lambda *shape: jnp.zeros(shape, jnp.float32)  # noqa: E731
    zi = lambda *shape: jnp.zeros(shape, jnp.int32)  # noqa: E731
    zb = lambda *shape: jnp.zeros(shape, bool)  # noqa: E731
    return PodBatch(
        request=jnp.asarray(request, jnp.float32),
        r_io=z(p) if r_io is None else jnp.asarray(r_io, jnp.float32),
        priority=zi(p) if priority is None else jnp.asarray(priority, jnp.int32),
        pod_mask=jnp.ones(p, bool) if pod_mask is None else jnp.asarray(pod_mask, bool),
        want_number=zi(p) if want_number is None else jnp.asarray(want_number, jnp.int32),
        want_memory=jnp.full((p,), -1.0, jnp.float32) if want_memory is None else jnp.asarray(want_memory, jnp.float32),
        want_clock=jnp.full((p,), -1.0, jnp.float32) if want_clock is None else jnp.asarray(want_clock, jnp.float32),
        tolerations=zi(p, 1, 4) if tolerations is None else jnp.asarray(tolerations, jnp.int32),
        tol_mask=(
            (zb(p, 1) if tolerations is None
             else jnp.ones(jnp.asarray(tolerations).shape[:2], bool))
            if tol_mask is None else jnp.asarray(tol_mask, bool)
        ),
        na_key=zi(p, 1) if na_key is None else jnp.asarray(na_key, jnp.int32),
        na_op=zi(p, 1) if na_op is None else jnp.asarray(na_op, jnp.int32),
        na_vals=zi(p, 1, 1) if na_vals is None else jnp.asarray(na_vals, jnp.int32),
        na_val_mask=(
            (zb(p, 1, 1) if na_vals is None
             else jnp.ones(jnp.asarray(na_vals).shape, bool))
            if na_val_mask is None else jnp.asarray(na_val_mask, bool)
        ),
        na_mask=(
            (zb(p, 1) if na_key is None
             else jnp.ones(jnp.asarray(na_key).shape, bool))
            if na_mask is None else jnp.asarray(na_mask, bool)
        ),
        na_term=(
            (zi(p, 1) if na_key is None
             else jnp.zeros(jnp.asarray(na_key).shape, jnp.int32))
            if na_term is None else jnp.asarray(na_term, jnp.int32)
        ),
        affinity_sel=jnp.full((p, 1), -1, jnp.int32) if affinity_sel is None else jnp.asarray(affinity_sel, jnp.int32),
        anti_affinity_sel=jnp.full((p, 1), -1, jnp.int32) if anti_affinity_sel is None else jnp.asarray(anti_affinity_sel, jnp.int32),
        pod_matches=zb(p, 1) if pod_matches is None else jnp.asarray(pod_matches, bool),
        pna_key=zi(p, 1) if pna_key is None else jnp.asarray(pna_key, jnp.int32),
        pna_op=zi(p, 1) if pna_op is None else jnp.asarray(pna_op, jnp.int32),
        pna_vals=zi(p, 1, 1) if pna_vals is None else jnp.asarray(pna_vals, jnp.int32),
        pna_val_mask=(
            (zb(p, 1, 1) if pna_vals is None
             else jnp.ones(jnp.asarray(pna_vals).shape, bool))
            if pna_val_mask is None else jnp.asarray(pna_val_mask, bool)
        ),
        pna_mask=(
            (zb(p, 1) if pna_key is None
             else jnp.ones(jnp.asarray(pna_key).shape, bool))
            if pna_mask is None else jnp.asarray(pna_mask, bool)
        ),
        pna_weight=(
            (z(p, 1) if pna_key is None
             else jnp.ones(jnp.asarray(pna_key).shape, jnp.float32))
            if pna_weight is None else jnp.asarray(pna_weight, jnp.float32)
        ),
        # default: each expression its own preferred term (per-expression
        # weighting, the pre-grouping behavior)
        pna_term=(
            jnp.broadcast_to(
                jnp.arange(
                    1 if pna_key is None else jnp.asarray(pna_key).shape[1],
                    dtype=jnp.int32,
                )[None, :],
                (p, 1 if pna_key is None else jnp.asarray(pna_key).shape[1]),
            )
            if pna_term is None else jnp.asarray(pna_term, jnp.int32)
        ),
        pref_affinity_sel=jnp.full((p, 1), -1, jnp.int32) if pref_affinity_sel is None else jnp.asarray(pref_affinity_sel, jnp.int32),
        pref_affinity_weight=(
            (z(p, 1) if pref_affinity_sel is None
             else jnp.ones(jnp.asarray(pref_affinity_sel).shape, jnp.float32))
            if pref_affinity_weight is None
            else jnp.asarray(pref_affinity_weight, jnp.float32)
        ),
        pref_anti_sel=jnp.full((p, 1), -1, jnp.int32) if pref_anti_sel is None else jnp.asarray(pref_anti_sel, jnp.int32),
        pref_anti_weight=(
            (z(p, 1) if pref_anti_sel is None
             else jnp.ones(jnp.asarray(pref_anti_sel).shape, jnp.float32))
            if pref_anti_weight is None
            else jnp.asarray(pref_anti_weight, jnp.float32)
        ),
        target_node=jnp.full((p,), -1, jnp.int32) if target_node is None else jnp.asarray(target_node, jnp.int32),
        spread_sel=jnp.full((p, 1), -1, jnp.int32) if spread_sel is None else jnp.asarray(spread_sel, jnp.int32),
        spread_max=(
            (jnp.ones((p, 1), jnp.int32) if spread_sel is None
             else jnp.ones(jnp.asarray(spread_sel).shape, jnp.int32))
            if spread_max is None else jnp.asarray(spread_max, jnp.int32)
        ),
        soft_spread_sel=(
            jnp.full((p, 1), -1, jnp.int32)
            if soft_spread_sel is None
            else jnp.asarray(soft_spread_sel, jnp.int32)
        ),
        image_ids=(
            jnp.full((p, 1), -1, jnp.int32)
            if image_ids is None
            else jnp.asarray(image_ids, jnp.int32)
        ),
        n_containers=(
            jnp.ones((p,), jnp.int32)
            if n_containers is None
            else jnp.asarray(n_containers, jnp.int32)
        ),
        gang_id=(
            jnp.full((p,), -1, jnp.int32)
            if gang_id is None
            else jnp.asarray(gang_id, jnp.int32)
        ),
        gang_size=(
            jnp.zeros((p,), jnp.int32)
            if gang_size is None
            else jnp.asarray(gang_size, jnp.int32)
        ),
    )


class ScheduleResult(NamedTuple):
    node_idx: jnp.ndarray     # [p] int32 assigned node, -1 = unschedulable
    scores: jnp.ndarray       # [p, n] normalized scores
    raw_scores: jnp.ndarray   # [p, n] policy scores before normalization
    feasible: jnp.ndarray     # [p, n] bool
    free_after: jnp.ndarray   # [n, r]
    n_assigned: jnp.ndarray   # [] int32


class SnapshotDelta(NamedTuple):
    """Cycle-over-cycle change to a retained SnapshotArrays: changed rows
    BY VALUE (set, never add — re-applying the host's exact float32 row
    contents keeps the resident matrices bitwise identical to a full
    rebuild, which the PARITY.md delta/full guarantee depends on).

    Row index arrays are bucket-padded with an out-of-range index (the
    node-axis length), dropped by the device scatter (`mode="drop"`) and
    filtered by the numpy applier — so delta shapes stay stable across
    cycles and the jitted `apply_snapshot_delta` rarely recompiles.

    Only the leaves that change in steady state are expressible:
    `requested` rows (the engine's own assignments plus running-set
    churn), the five utilization series, the four float domain-count
    tables (binds of selector-matching pods move whole-domain rows —
    `domain_id` itself is layout and never rides a delta), and the node
    mask. Any change to the static block (allocatable, labels, taints,
    cards, images, the selector axis) or any shape/layout churn makes
    the host emit a full upload instead (host.snapshot.snapshot_delta
    returns None)."""

    req_rows: jnp.ndarray   # [k] int32 changed `requested` rows; pad = n
    req_vals: jnp.ndarray   # [k, r] float32 full new row contents
    util_rows: jnp.ndarray  # [j] int32 changed utilization rows; pad = n
    # [j, 5] float32 columns: disk_io, cpu_pct, mem_pct, net_up, net_down
    util_vals: jnp.ndarray
    dom_rows: jnp.ndarray   # [d] int32 changed domain-table rows; pad = n
    # [d, S, 4] float32 stacked columns: domain_counts, avoid_counts,
    # pref_attract, pref_avoid
    dom_vals: jnp.ndarray
    node_mask: jnp.ndarray  # [n] bool (cheap; shipped whole every delta)


def _delta_row_chunks(rows, vals, sentinel: int, chunk: int):
    """Split a changed-row vector (+ its value block) into fixed-`chunk`
    slices, the short tail sentinel-padded. The fleet applier scatters
    per chunk so its jit cache keys on ONE shape per leaf family — a
    growing cluster walks the power-of-two delta buckets upward and an
    unchunked eager apply would recompile every scatter at every
    crossing (seconds per coalesced dispatch on a cold bucket), while
    chunked slices hit the cache forever after first use. Sentinel rows
    are out of range and dropped by the scatter's mode="drop"."""
    rows = np.asarray(rows)
    vals = np.asarray(vals)
    k = len(rows)
    out = []
    for i in range(0, max(k, 1), chunk):
        r, v = rows[i : i + chunk], vals[i : i + chunk]
        if len(r) < chunk:
            rp = np.full(chunk, sentinel, np.int32)
            rp[: len(r)] = r
            vp = np.zeros((chunk,) + v.shape[1:], vals.dtype)
            vp[: len(v)] = v
            r, v = rp, vp
        out.append((r, v))
    return out


def _apply_delta_rows_chunked(
    snapshot: SnapshotArrays, delta: SnapshotDelta, *, chunk: int = 128
) -> SnapshotArrays:
    """Bitwise twin of `_apply_delta_rows` for the EAGER fleet path
    (schedule_batch_fleet): same row sets by value, but scattered in
    fixed-shape chunks so per-element deltas of any bucket size reuse
    one compiled scatter per leaf. Row indices within a delta are
    unique by construction and sentinel pads drop, so chunk boundaries
    cannot change the result."""
    n = int(snapshot.node_mask.shape[0])
    requested = snapshot.requested
    for r, v in _delta_row_chunks(delta.req_rows, delta.req_vals, n, chunk):
        requested = requested.at[r].set(v, mode="drop")
    util = [
        snapshot.disk_io, snapshot.cpu_pct, snapshot.mem_pct,
        snapshot.net_up, snapshot.net_down,
    ]
    for r, v in _delta_row_chunks(delta.util_rows, delta.util_vals, n, chunk):
        for col in range(5):
            util[col] = util[col].at[r].set(v[:, col], mode="drop")
    dom = [
        snapshot.domain_counts, snapshot.avoid_counts,
        snapshot.pref_attract, snapshot.pref_avoid,
    ]
    for r, v in _delta_row_chunks(delta.dom_rows, delta.dom_vals, n, chunk):
        for col in range(4):
            dom[col] = dom[col].at[r].set(v[:, :, col], mode="drop")
    return snapshot._replace(
        requested=requested,
        disk_io=util[0], cpu_pct=util[1], mem_pct=util[2],
        net_up=util[3], net_down=util[4],
        domain_counts=dom[0], avoid_counts=dom[1],
        pref_attract=dom[2], pref_avoid=dom[3],
        node_mask=jnp.asarray(delta.node_mask),
    )


def _apply_delta_rows(
    snapshot: SnapshotArrays, delta: SnapshotDelta
) -> SnapshotArrays:
    """The row-scatter body shared by the dense `apply_snapshot_delta`
    and the mesh-sharded per-shard applier (parallel/engine.py's
    make_sharded_apply_delta_fn): ONE definition, so a sharded shard's
    fold is bitwise the dense fold restricted to its rows."""
    return snapshot._replace(
        requested=snapshot.requested.at[delta.req_rows].set(
            delta.req_vals, mode="drop"
        ),
        disk_io=snapshot.disk_io.at[delta.util_rows].set(
            delta.util_vals[:, 0], mode="drop"
        ),
        cpu_pct=snapshot.cpu_pct.at[delta.util_rows].set(
            delta.util_vals[:, 1], mode="drop"
        ),
        mem_pct=snapshot.mem_pct.at[delta.util_rows].set(
            delta.util_vals[:, 2], mode="drop"
        ),
        net_up=snapshot.net_up.at[delta.util_rows].set(
            delta.util_vals[:, 3], mode="drop"
        ),
        net_down=snapshot.net_down.at[delta.util_rows].set(
            delta.util_vals[:, 4], mode="drop"
        ),
        domain_counts=snapshot.domain_counts.at[delta.dom_rows].set(
            delta.dom_vals[:, :, 0], mode="drop"
        ),
        avoid_counts=snapshot.avoid_counts.at[delta.dom_rows].set(
            delta.dom_vals[:, :, 1], mode="drop"
        ),
        pref_attract=snapshot.pref_attract.at[delta.dom_rows].set(
            delta.dom_vals[:, :, 2], mode="drop"
        ),
        pref_avoid=snapshot.pref_avoid.at[delta.dom_rows].set(
            delta.dom_vals[:, :, 3], mode="drop"
        ),
        node_mask=delta.node_mask,
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def apply_snapshot_delta(
    snapshot: SnapshotArrays, delta: SnapshotDelta
) -> SnapshotArrays:
    """Fold a SnapshotDelta into the device-resident snapshot in place:
    the snapshot tree is DONATED, so in the common case no [n, r] matrix
    crosses the host<->device boundary and XLA reuses the resident
    buffers for the output. Callers must drop every reference to the
    donated tree and hold only the returned one (graftlint's dtype-shape
    family flags a donated leaf that is re-read)."""
    return _apply_delta_rows(snapshot, delta)


def apply_snapshot_delta_np(snapshot: SnapshotArrays, delta: SnapshotDelta):
    """The numpy mirror of apply_snapshot_delta, for hosts that retain
    the resident state off-device (the bridge server keys one per
    session): row sets by value, so the result is BITWISE the snapshot
    the client would have shipped in full. Returns a new SnapshotArrays;
    the input's leaves are not mutated."""
    import numpy as np

    n = snapshot.node_mask.shape[0]
    req = np.array(snapshot.requested, np.float32, copy=True)
    rows = np.asarray(delta.req_rows)
    keep = (rows >= 0) & (rows < n)
    req[rows[keep]] = np.asarray(delta.req_vals, np.float32)[keep]
    series = []
    urows = np.asarray(delta.util_rows)
    ukeep = (urows >= 0) & (urows < n)
    uvals = np.asarray(delta.util_vals, np.float32)
    for col, name in enumerate(
        ("disk_io", "cpu_pct", "mem_pct", "net_up", "net_down")
    ):
        s = np.array(getattr(snapshot, name), np.float32, copy=True)
        s[urows[ukeep]] = uvals[ukeep, col]
        series.append(s)
    domains = []
    drows = np.asarray(delta.dom_rows)
    dkeep = (drows >= 0) & (drows < n)
    dvals = np.asarray(delta.dom_vals, np.float32)
    for col, name in enumerate(
        ("domain_counts", "avoid_counts", "pref_attract", "pref_avoid")
    ):
        t = np.array(getattr(snapshot, name), np.float32, copy=True)
        t[drows[dkeep]] = dvals[dkeep, :, col]
        domains.append(t)
    return snapshot._replace(
        requested=req,
        disk_io=series[0],
        cpu_pct=series[1],
        mem_pct=series[2],
        net_up=series[3],
        net_down=series[4],
        domain_counts=domains[0],
        avoid_counts=domains[1],
        pref_attract=domains[2],
        pref_avoid=domains[3],
        node_mask=np.asarray(delta.node_mask, bool),
    )


def snapshot_nbytes(nt) -> int:
    """Total payload bytes of a NamedTuple of arrays (host-side shapes
    and dtypes only — never forces a device sync)."""
    import numpy as np

    total = 0
    for a in nt:
        size = 1
        for d in a.shape:
            size *= int(d)
        total += size * np.dtype(a.dtype).itemsize
    return total


class FusedLayout(NamedTuple):
    """Device-resident KERNEL-LAYOUT node operands for the fused Pallas
    megakernel: the transposed/padded/stacked buffers
    ops.pallas_fused.prep_node_operands derives per call, retained
    across resident cycles so a delta upload rewrites only the changed
    columns instead of re-deriving the whole prep every step.

    Built by build_fused_layout on a full resident upload and folded
    forward by apply_layout_delta — both jitted, both writing the exact
    float32 values the per-call prep would compute (same expressions on
    the same row values), so resident-layout and re-pad cycles are
    bitwise identical (PARITY round 12)."""

    node_ft: jnp.ndarray  # [3, nn] rows = (u, v, node_mask) f32
    alloc_t: jnp.ndarray  # [r, nn] allocatable, resource-major
    reqd_t: jnp.ndarray   # [r, nn] requested, resource-major


@jax.jit
def build_fused_layout(snapshot: SnapshotArrays) -> FusedLayout:
    """FusedLayout from a freshly-uploaded resident snapshot — ONE prep
    per full upload; later delta cycles ship straight into the layout."""
    from kubernetes_scheduler_tpu.ops.pallas_fused import prep_node_operands

    stats = utilization_stats(
        snapshot.disk_io, snapshot.cpu_pct, snapshot.node_mask
    )
    node_ft, alloc_t, reqd_t = prep_node_operands(
        stats.u, stats.v, snapshot.node_mask,
        snapshot.allocatable, snapshot.requested,
    )
    return FusedLayout(node_ft=node_ft, alloc_t=alloc_t, reqd_t=reqd_t)


def _apply_layout_rows(layout: FusedLayout, delta: SnapshotDelta) -> FusedLayout:
    """The kernel-layout fold body shared by the dense
    `apply_layout_delta` and the mesh-sharded per-shard applier — the
    delta's row space and the layout's column space are whatever the
    caller shards them to (dense: global; sharded: one shard's slice),
    so the per-shard fold is bitwise the dense fold on its columns."""
    from kubernetes_scheduler_tpu.ops.stats import (
        CPU_DIVISOR,
        DISK_IO_DIVISOR,
    )

    n = delta.node_mask.shape[0]
    nn = layout.node_ft.shape[1]
    # the delta's padded row indices use sentinel `n` (the NODE axis
    # length) — in range of these TILE-padded (nn >= n) buffers, so
    # remap to nn for mode="drop" to actually drop them (a sentinel
    # write would zero a padding column: benign today, silently wrong
    # for any future non-zero-padded layout leaf)
    util_rows = jnp.where(delta.util_rows >= n, jnp.int32(nn), delta.util_rows)
    req_rows = jnp.where(delta.req_rows >= n, jnp.int32(nn), delta.req_rows)
    node_ft = layout.node_ft.at[0, util_rows].set(
        delta.util_vals[:, 0] / DISK_IO_DIVISOR, mode="drop"
    )
    node_ft = node_ft.at[1, util_rows].set(
        delta.util_vals[:, 1] / CPU_DIVISOR, mode="drop"
    )
    node_ft = node_ft.at[2, :].set(
        jnp.pad(delta.node_mask.astype(jnp.float32), (0, nn - n))
    )
    reqd_t = layout.reqd_t.at[:, req_rows].set(
        delta.req_vals.T, mode="drop"
    )
    return FusedLayout(node_ft=node_ft, alloc_t=layout.alloc_t, reqd_t=reqd_t)


@functools.partial(jax.jit, donate_argnums=(0,))
def apply_layout_delta(layout: FusedLayout, delta: SnapshotDelta) -> FusedLayout:
    """Fold a SnapshotDelta into the retained kernel-layout buffers in
    place (donated, like apply_snapshot_delta): changed `requested` rows
    become column writes into reqd_t, utilization rows become u/v cell
    writes (the same divisor expressions utilization_stats applies, on
    the same row values — bitwise what a re-prep would produce), and the
    node-mask row is refreshed whole. `allocatable` never rides a delta,
    so alloc_t passes through untouched."""
    return _apply_layout_rows(layout, delta)


class ResidentMismatch(RuntimeError):
    """A SnapshotDelta arrived for resident state this engine does not
    hold (wrong epoch, shape/layout churn, or no state at all); the
    caller must re-upload in full."""


class ResidentState:
    """Device-owned steady-state cluster arrays: the retained snapshot
    tree plus the epoch the host tags its deltas with. The snapshot
    leaves are PRIVATE device buffers (never the shared uniform-constant
    cache) because apply_snapshot_delta donates them."""

    __slots__ = ("snapshot", "epoch", "layout")

    def __init__(self, snapshot: SnapshotArrays, epoch: int):
        self.snapshot = snapshot
        self.epoch = epoch
        # kernel-layout twin of the snapshot for the fused megakernel
        # (FusedLayout); built lazily on the first fused dispatch
        # against this state, then delta-folded in lockstep
        self.layout: FusedLayout | None = None

    def accepts(self, delta: SnapshotDelta, epoch: int) -> bool:
        """Is `delta` (tagged to produce `epoch`) applicable to this
        state? Epoch must be the immediate successor and the delta's
        node/resource axes must match the retained shapes — anything
        else is layout churn requiring a full upload."""
        snap = self.snapshot
        return (
            epoch == self.epoch + 1
            and delta.node_mask.shape == snap.node_mask.shape
            and delta.req_vals.shape[1:] == snap.requested.shape[1:]
            and delta.dom_vals.shape[1] == snap.domain_counts.shape[1]
        )


class _UniformDeviceCache:
    """Device-resident constants for uniform-valued tensor leaves.

    A host-built cycle ships ~56 arrays to the device; on a remote/
    tunneled chip each leaf pays ~1 ms of transfer latency, and for a
    typical (constraint-free) window MOST leaves are uniform defaults
    (-1 selector pads, zero tolerations, False masks) identical cycle
    after cycle. Swapping those for memoized device arrays removes their
    transfers from the critical path; value-varying leaves pass through
    untouched, so results are bit-identical. Local engines only — a
    REMOTE engine's codec would pay a device readback per swapped leaf.
    """

    MAX_ENTRIES = 256

    def __init__(self):
        self._cache: dict = {}
        # field name -> (host copy, device array) of the last NON-uniform
        # value seen: advisor series, allocatable rows, label tables etc.
        # are typically identical cycle after cycle — a bytewise compare
        # (~us/MB) is far cheaper than a per-leaf tunnel transfer (~ms)
        self._last: dict = {}

    def swap(self, nt):
        import numpy as np

        out = []
        for name, arr in zip(type(nt)._fields, nt):
            if isinstance(arr, jnp.ndarray):
                out.append(arr)
                continue
            # graftlint: disable=host-sync -- leaves here are host numpy (jnp filtered above); no device sync
            a = np.asarray(arr)
            if a.size:
                v = a.flat[0]
                if (a == v).all():
                    # graftlint: disable=host-sync -- numpy scalar .item(); the array never left the host
                    key = (name, a.shape, a.dtype.str, v.item())
                    dev = self._cache.get(key)
                    if dev is None:
                        if len(self._cache) >= self.MAX_ENTRIES:
                            self._cache.clear()
                        dev = jax.device_put(a)
                        self._cache[key] = dev
                    out.append(dev)
                    continue
            prev = self._last.get(name)
            if (
                prev is not None
                and prev[0].shape == a.shape
                and prev[0].dtype == a.dtype
                and np.array_equal(prev[0], a)
            ):
                out.append(prev[1])
                continue
            dev = jax.device_put(a)
            # own copy: the compare must never read a buffer the caller
            # later mutates
            self._last[name] = (a.copy(), dev)
            out.append(dev)
        return type(nt)(*out)


class PendingSchedule:
    """Handle for an in-flight schedule_batch dispatch (the pipelined
    host loop's async surface): `result()` returns the ScheduleResult
    whose leaves force on first host read. For the local engine the
    jitted call is already enqueued when the handle is constructed —
    JAX async dispatch returns before the device finishes, so the ONLY
    blocking point is the caller's eventual `np.asarray(res.node_idx)`.
    Remote engines return a thread-backed equivalent
    (bridge.client._FutureSchedule) with the same one-method surface."""

    __slots__ = ("_result",)

    def __init__(self, result: "ScheduleResult"):
        self._result = result

    def result(self) -> "ScheduleResult":
        return self._result


class LocalEngine:
    """In-process engine with the bridge's call surface, so the host
    scheduler swaps Local/Remote behind one attribute (grpc-free — the
    no-bridge configuration must not import grpc)."""

    def __init__(self):
        self._consts = _UniformDeviceCache()
        # device-resident cluster state (config.resident_state): retained
        # snapshot + epoch; None until the first full resident upload
        self._resident: ResidentState | None = None
        # did the LAST schedule_resident call apply a delta (True) or
        # fall back to / receive a full upload (False)? The host reads
        # this after forcing the result to attribute its metrics.
        self.resident_used_delta = False
        # span/profile context (host/observe): the host's per-cycle
        # trace id, and the outstanding /debug/profile arm (capture the
        # next N schedule calls under jax.profiler)
        self._trace_id = 0
        self._profile_left = 0
        self._profile_dir: str | None = None

    # ---- telemetry context --------------------------------------------

    def set_trace_id(self, trace_id: int, seq: int = -1) -> None:
        """Span context for the NEXT schedule call (the host cycle's
        trace id). The local engine only uses it to name on-demand
        profile dumps; RemoteEngine's twin propagates it to the sidecar
        as gRPC metadata so server-side spans join the host timeline."""
        self._trace_id = int(trace_id)

    def arm_profile(self, cycles: int, out_dir: str | None = None) -> dict:
        """Capture the next `cycles` schedule calls under jax.profiler
        (/debug/profile?cycles=N). Each captured call dumps under
        <out_dir>/step-<trace_id> — named after the trace id it covers,
        so a profile pairs with its spans and flight-recorder record."""
        if out_dir is None:
            import tempfile

            out_dir = tempfile.mkdtemp(prefix="yoda-profile-")
        self._profile_dir = out_dir
        self._profile_left = int(cycles)
        return {"armed": self._profile_left, "out_dir": out_dir}

    def _maybe_profile(self, call):
        """Run one engine dispatch under jax.profiler when an arm is
        outstanding; otherwise dispatch untouched (zero cost)."""
        if self._profile_left <= 0:
            return call()
        import os

        from kubernetes_scheduler_tpu.host.observe import profile_device_step

        self._profile_left -= 1
        tag = (
            "step-%08d" % self._trace_id
            if self._trace_id
            else "step-unlabeled"
        )
        return profile_device_step(
            call, os.path.join(self._profile_dir, tag)
        )

    def schedule_batch(self, snapshot, pods, **kw) -> "ScheduleResult":
        return self._maybe_profile(
            lambda: schedule_batch(
                self._consts.swap(snapshot), self._consts.swap(pods), **kw
            )
        )

    # ---- resident cluster state (delta uploads) -----------------------

    def supports_resident(self) -> bool:
        return True

    def invalidate_resident(self) -> None:
        """Drop the retained state; the next schedule_resident call does
        a full upload regardless of what the host sends."""
        self._resident = None

    def _resident_dispatch(self, snapshot, delta, epoch: int, kw: dict):
        """Shared resident front half of schedule_resident and
        schedule_windows_resident (ONE implementation, so the two
        surfaces cannot drift on accept/fold/flush or layout-injection
        semantics — the same factoring ShardedEngine uses): fold an
        applicable delta into the retained state, flush to a full
        upload otherwise, and on fused paths inject the retained
        kernel layout (built on first need, delta-folded thereafter).
        Returns (state, kw)."""
        st = self._resident
        if delta is not None and st is not None and st.accepts(delta, epoch):
            new_snap = apply_snapshot_delta(st.snapshot, delta)
            # the donated tree is dead: rebind before anything can read it
            st.snapshot = new_snap
            if st.layout is not None:
                # the kernel-layout twin folds the SAME delta (donated):
                # fused resident cycles ship changed rows straight into
                # kernel layout, no per-call transpose/pad/stack
                st.layout = apply_layout_delta(st.layout, delta)
            st.epoch = epoch
            self.resident_used_delta = True
        else:
            # full upload into PRIVATE buffers — the uniform-constant
            # cache's shared device arrays must never be donated
            self._resident = st = ResidentState(jax.device_put(snapshot), epoch)
            self.resident_used_delta = False
        if kw.get("fused"):
            if st.layout is None:
                st.layout = build_fused_layout(st.snapshot)
            kw = dict(kw, layout=st.layout)
        return st, kw

    def schedule_resident(
        self, snapshot, pods, *, delta=None, epoch=0, **kw
    ) -> "ScheduleResult":
        """Schedule against device-resident cluster state. `snapshot` is
        ALWAYS the full host build (the fallback payload); when `delta`
        is given and matches the retained epoch/shape it is applied by
        the jitted donated-buffer apply_snapshot_delta instead — no
        [n, r] matrix crosses the host<->device boundary. Any mismatch
        (engine restart, epoch desync, layout churn) transparently
        degrades to a full upload of `snapshot`; `resident_used_delta`
        reports which path served the call."""
        st, kw = self._resident_dispatch(snapshot, delta, epoch, kw)
        return self._maybe_profile(
            lambda: schedule_batch(
                st.snapshot, self._consts.swap(pods), **kw
            )
        )

    def schedule_resident_async(
        self, snapshot, pods, *, delta=None, epoch=0, **kw
    ) -> "PendingSchedule":
        """Async-dispatch twin of schedule_resident (the delta apply and
        the cycle program are enqueued without forcing; see
        schedule_batch_async)."""
        return PendingSchedule(
            self.schedule_resident(
                snapshot, pods, delta=delta, epoch=epoch, **kw
            )
        )

    def schedule_batch_async(self, snapshot, pods, **kw) -> PendingSchedule:
        """Dispatch without forcing: the jit call enqueues the program
        and returns lazy device arrays (compilation, on a cold cache,
        still blocks — that is a one-time cost per bucket shape). The
        pipelined host loop does next-cycle host work between this call
        and `handle.result()`'s first array read."""
        return PendingSchedule(self.schedule_batch(snapshot, pods, **kw))

    def schedule_windows(self, snapshot, pods_windows, **kw) -> "WindowsResult":
        return self._maybe_profile(
            lambda: schedule_windows(
                self._consts.swap(snapshot),
                self._consts.swap(pods_windows),
                **kw,
            )
        )

    def supports_windows_resident(self) -> bool:
        return True

    def schedule_windows_resident(
        self, snapshot, pods_windows, *, delta=None, epoch=0, **kw
    ) -> "WindowsResult":
        """schedule_windows against device-resident cluster state — the
        multi-window twin of schedule_resident, sharing the SAME
        retained snapshot/epoch (backlog and single-window cycles
        interleave on one epoch sequence). The scan's cross-window
        capacity/affinity carries stay internal to the call; the
        retained state remains the PRE-backlog snapshot, exactly as the
        host's delta base accounting assumes."""
        # shared front half with schedule_resident; on fused paths the
        # injected layout makes the scan reuse the retained node_ft/
        # alloc_t and rebuild only the reqd_t leaf per window from its
        # capacity carry (prep_requested) — the PR-8 "scan still
        # re-preps" cost is gone; bitwise the re-prep path (PARITY
        # round 15)
        st, kw = self._resident_dispatch(snapshot, delta, epoch, kw)
        return self._maybe_profile(
            lambda: schedule_windows(
                st.snapshot,
                self._consts.swap(pods_windows),
                **kw,
            )
        )

    def schedule_batch_fleet(
        self, snapshot, requests, *, delta=None, epoch=None, **kw
    ) -> tuple:
        """Coalesced fleet dispatch (host/engine_pool.SharedEnginePool):
        one invocation schedules every (delta | None, pods) request in
        `requests` against the shared base `snapshot`, each element's
        delta applied functionally inside the program (see the free
        schedule_batch_fleet). With `epoch` given the base rides the
        resident front half — an applicable `delta` folds into the
        retained state (donated scatter, no [n, r] upload) and a
        mismatch flushes to a full upload, exactly the
        schedule_resident semantics; epoch=None schedules against the
        uploaded `snapshot` without retaining it. The retained layout
        is never injected: per-element deltas would invalidate it, and
        in-kernel prep is parity-pinned (PARITY round 15)."""
        if epoch is None:
            snap = self._consts.swap(snapshot)
        else:
            st, kw = self._resident_dispatch(snapshot, delta, epoch, kw)
            snap = st.snapshot
        kw.pop("layout", None)
        reqs = tuple((d, self._consts.swap(p)) for d, p in requests)
        return self._maybe_profile(
            lambda: schedule_batch_fleet(snap, reqs, **kw)
        )

    def preempt(self, snapshot, pods, victims, *, k_cap: int):
        return preempt_batch(snapshot, pods, victims, k_cap=k_cap)

    def supports_gangs(self) -> bool:
        """Gang co-scheduling capability (ops/gang.py): the in-process
        engine always applies the all-or-nothing mask in finish_cycle.
        RemoteEngine's twin latches the sidecar's advertised bit and
        strips the gang tensors off the wire when it is absent."""
        return True

    def healthy(self) -> bool:
        return True

    def close(self) -> None:
        pass


def compute_scores(
    snapshot: SnapshotArrays, pods: PodBatch, policy: str
) -> jnp.ndarray:
    """Policy dispatch (static): the reference's commented-out alternates in
    CalculateScore (algorithm.go:90-96) become first-class selectable
    kernels."""
    stats = utilization_stats(snapshot.disk_io, snapshot.cpu_pct, snapshot.node_mask)
    if policy == "balanced_cpu_diskio":
        return balanced_cpu_diskio(stats, pods.request[:, 0], pods.r_io)
    if policy == "balanced_diskio":
        return balanced_diskio(stats, snapshot.disk_io, pods.r_io, snapshot.node_mask)
    if policy == "free_capacity":
        s = free_capacity(snapshot.cpu_pct, snapshot.mem_pct, snapshot.disk_io)
        return jnp.broadcast_to(s[None, :], (pods.request.shape[0], s.shape[0]))
    if policy == "card":
        node_fits, per_card = card_fit(
            snapshot.cards, snapshot.card_mask, snapshot.card_healthy,
            pods.want_number, pods.want_memory, pods.want_clock,
        )
        maxima = collect_max_card_values(
            snapshot.cards, per_card & node_fits[:, :, None]
        )
        return card_score(snapshot.cards, snapshot.card_mask, per_card, maxima)
    if policy == "least_allocated":
        return least_allocated(
            snapshot.allocatable, snapshot.requested, pods.request
        )
    if policy == "balanced_allocation":
        return balanced_allocation(
            snapshot.allocatable, snapshot.requested, pods.request
        )
    if policy == "image_locality":
        return image_locality(
            snapshot.image_scaled, pods.image_ids, pods.n_containers
        )
    raise ValueError(f"unknown policy {policy!r}; expected one of {POLICIES}")


def combine_scores(
    snapshot: SnapshotArrays,
    pods: PodBatch,
    score_plugins: tuple,
) -> jnp.ndarray:
    """The upstream framework runtime's weighted multi-plugin score
    (RunScorePlugins via /root/reference/go.mod:13): each plugin scores
    every node, plugins with a NormalizeScore extension are min-max
    rescaled to [0, MaxNodeScore] per pod (scheduler.go:158-183 is
    yoda's), and the framework sums weight * score — the production
    combination the reference's deployed config produces by enabling
    yoda BESIDE the k8s 1.22 defaults
    (/root/reference/deploy/yoda-scheduler.yaml:21-47 disables nothing;
    example/config:25-27 sets yoda's weight).

    score_plugins: tuple of (policy_name, weight) pairs, static under
    jit. Returns the combined S[p, n] float32 (NOT re-normalized — the
    framework never rescales the weighted sum).
    """
    if not score_plugins:
        raise ValueError("score_plugins must name at least one plugin")
    total = None
    for name, weight in score_plugins:
        raw = compute_scores(snapshot, pods, name)
        if name not in PRESCALED_PLUGINS:
            raw = min_max_normalize(raw, snapshot.node_mask)
        term = raw * float(weight)
        total = term if total is None else total + term
    return total


def compute_feasibility(
    snapshot: SnapshotArrays,
    pods: PodBatch,
    *,
    include_pod_affinity: bool = True,
) -> jnp.ndarray:
    """All filter masks ANDed: resource fit (NodeResourcesFit semantics,
    algorithm.go:209-262), GPU-card predicates (filter.go:11-58),
    taint/toleration, node affinity, and inter-pod (anti)affinity
    (ops/constraints.py; capabilities required by BASELINE.md config 4).

    include_pod_affinity=False leaves inter-pod (anti)affinity out of the
    static mask: the greedy assigner evaluates it dynamically per placement
    (ops/assign.py AffinityState) so pods within one window see each
    other's placements, exactly like upstream's per-pod re-snapshot."""
    fits = resource_fit(
        snapshot.allocatable, snapshot.requested, pods.request, snapshot.node_mask
    )
    gpu_fits, _ = card_fit(
        snapshot.cards, snapshot.card_mask, snapshot.card_healthy,
        pods.want_number, pods.want_memory, pods.want_clock,
    )
    taint_ok = taint_toleration_fit(
        snapshot.taints, snapshot.taint_mask, pods.tolerations, pods.tol_mask
    )
    na_ok = node_affinity_fit(
        snapshot.node_labels, snapshot.node_label_mask,
        pods.na_key, pods.na_op, pods.na_vals, pods.na_val_mask, pods.na_mask,
        pods.na_term,
    )
    out = fits & gpu_fits & taint_ok & na_ok & pods.pod_mask[:, None]
    out = out & node_name_fit(pods.target_node, snapshot.allocatable.shape[0])
    if include_pod_affinity:
        # domain-count-based families evaluated statically against
        # pre-window counts (the affinity_aware=True paths instead thread
        # live counts through the assigners)
        out = out & pod_affinity_fit(
            snapshot.domain_counts, pods.affinity_sel, pods.anti_affinity_sel
        )
        # reverse direction vs. pre-existing avoiders (upstream
        # InterPodAffinity checks existing pods' anti terms too)
        matches = match_matrix(pods, snapshot.avoid_counts.shape[1])
        out = out & ~anti_reverse_bad(matches, snapshot.avoid_counts)
        out = out & topology_spread_fit(
            snapshot.domain_counts, snapshot.node_mask,
            pods.spread_sel, pods.spread_max,
        )
    return out


def match_matrix(pods: PodBatch, s: int) -> jnp.ndarray:
    """pods.pod_matches aligned to the snapshot's selector dimension `s`
    (a default-constructed PodBatch carries a [p, 1] placeholder)."""
    m = pods.pod_matches
    if m.shape[1] < s:
        return jnp.pad(m, ((0, 0), (0, s - m.shape[1])))
    return m[:, :s]


def make_affinity_state(snapshot: SnapshotArrays, pods: PodBatch) -> AffinityState:
    """Live inter-pod (anti)affinity state for the assigners: base domain
    match/avoider counts from the snapshot plus the pod-side selector
    structure, selector dimensions aligned."""
    s = snapshot.domain_counts.shape[1]
    return AffinityState(
        domain_counts=snapshot.domain_counts,
        domain_id=snapshot.domain_id,
        pod_matches=match_matrix(pods, s),
        affinity_sel=pods.affinity_sel,
        anti_affinity_sel=pods.anti_affinity_sel,
        avoid_counts=snapshot.avoid_counts,
        pod_has_anti=pod_has_anti_onehot(pods.anti_affinity_sel, s),
        spread_sel=pods.spread_sel,
        spread_max=pods.spread_max,
        node_mask=snapshot.node_mask,
    )


def compute_soft_scores(
    snapshot: SnapshotArrays,
    pods: PodBatch,
    *,
    taint_penalty_weight: float = 1.0,
    spread_dmin: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """[p, n] float32 soft-constraint score term: upstream's preferred
    (scoring, never filtering) constraint families —

    - preferred node affinity: +weight per satisfied preferred expression
      (NodeAffinity scoring)
    - preferred inter-pod (anti)affinity: ±weight per preferred selector
      with a topology-domain match (InterPodAffinity scoring)
    - PreferNoSchedule taints: −taint_penalty_weight per untolerated soft
      taint (TaintToleration scoring)
    - ScheduleAnyway topology spread: −(count − min count) marginal-skew
      penalty per soft constraint, steering toward the least-loaded
      domain without ever filtering (PodTopologySpread scoring)

    Added onto the normalized policy score when schedule_batch runs with
    soft=True; weights are interpreted relative to the active score range
    (min_max → [0, 100]), mirroring upstream's weighted score summation.
    """
    na = node_affinity_preference(
        snapshot.node_labels, snapshot.node_label_mask,
        pods.pna_key, pods.pna_op, pods.pna_vals, pods.pna_val_mask,
        pods.pna_mask, pods.pna_weight, pods.pna_term,
    )
    pa = pod_affinity_preference(
        snapshot.domain_counts,
        pods.pref_affinity_sel, pods.pref_affinity_weight,
        pods.pref_anti_sel, pods.pref_anti_weight,
    )
    pen = prefer_no_schedule_penalty(
        snapshot.taints, snapshot.taint_mask, pods.tolerations, pods.tol_mask
    )
    # symmetric half: EXISTING pods' preferred terms scored against the
    # incoming pod's labels (upstream InterPodAffinity's existing-term
    # scoring) — the incoming pod gains/loses the summed weights of
    # attracting/avoiding preferred terms whose selector it matches
    matches = match_matrix(pods, snapshot.pref_attract.shape[1]).astype(jnp.float32)
    sym = matches @ (snapshot.pref_attract - snapshot.pref_avoid).T  # [p, n]
    # ScheduleAnyway spread: marginal skew (count − min over schedulable
    # domains) of each soft constraint's selector on this node.
    # spread_dmin: optional precomputed [S] minimum — a node-sharded
    # caller passes the GLOBAL (pmin'd) minimum so the term cannot
    # diverge from the dense path when domains span shards
    s = snapshot.domain_counts.shape[1]
    ssel = pods.soft_spread_sel                                   # [p, K]
    ok = (ssel >= 0) & (ssel < s)
    idx = jnp.clip(ssel, 0, max(s - 1, 0))
    dmin = local_spread_dmin(snapshot) if spread_dmin is None else spread_dmin
    skew = snapshot.domain_counts[:, idx] - dmin[idx][None, :, :]  # [n, p, K]
    soft_spread = (jnp.where(ok[None, :, :], skew, 0.0)).sum(-1).T  # [p, n]
    return na + pa + sym - taint_penalty_weight * pen - soft_spread


def local_spread_dmin(snapshot: SnapshotArrays) -> jnp.ndarray:
    """[S] per-selector minimum domain count over schedulable nodes —
    the spread families' reference point. ONE definition: the sharded
    path pmins this local value to the global minimum, so the two
    paths cannot drift on sentinel/masking details."""
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    return jnp.where(
        snapshot.node_mask[:, None], snapshot.domain_counts, big
    ).min(0)


def check_fused_contract(
    policy: str, normalizer: str, *, min_max_ok: bool = False
) -> None:
    """The fused Pallas path's (policy, normalizer) domain — shared by
    schedule_batch and the sharded factories so the two surfaces cannot
    enforce different contracts.

    min_max_ok=True (the DENSE surfaces) additionally admits
    normalizer="min_max": the kernel's epilogue applies the plain
    min-max rescale in the same tiled pass, with row bounds from the
    fused row-stats companion kernel, bitwise equal to the unfused
    normalize-then-mask composition at every feasible cell. The sharded
    factories keep the strict contract — their min-max bounds are
    pmax/pmin-reduced GLOBAL values the shard-local kernel epilogue
    cannot see."""
    if policy != "balanced_cpu_diskio":
        raise ValueError(
            f"fused kernel only implements balanced_cpu_diskio, not {policy!r}"
        )
    allowed = ("none", "min_max") if min_max_ok else ("none",)
    if normalizer not in allowed:
        raise ValueError(
            f"fused=True requires normalizer in {allowed}, not "
            f"{normalizer!r} (masked NEG sentinels would skew the "
            "statistics of any normalizer the kernel epilogue does not "
            "implement)"
        )


def compute_free_capacity(snapshot: SnapshotArrays) -> jnp.ndarray:
    """[n, r] free capacity for assignment; padded nodes get 0."""
    return jnp.where(
        snapshot.node_mask[:, None],
        snapshot.allocatable - snapshot.requested,
        0.0,
    )


def _fused_affinity_operands(
    snapshot: SnapshotArrays, pods: PodBatch
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(aff_pod [4S, p], aff_node [3S, n], valid [p]) — the count-based
    constraint families (pod_affinity_fit, anti_reverse_bad,
    topology_spread_fit) re-expressed as per-selector one-hot rows the
    fused kernel folds in one tiled pass. Boolean-equivalent to the op
    composition (duplicate/-1-padded selector ids collapse in the
    one-hots exactly like the gathered all()/any() forms; a stale id
    >= S surfaces in `valid`, making the pod infeasible everywhere —
    pod_affinity_fit's documented stance)."""
    s = snapshot.domain_counts.shape[1]
    p = pods.request.shape[0]
    a_hot = pod_has_anti_onehot(pods.affinity_sel, s).astype(jnp.float32)
    t_hot = pod_has_anti_onehot(pods.anti_affinity_sel, s).astype(jnp.float32)
    matches = match_matrix(pods, s).astype(jnp.float32)
    # per-(pod, selector) spread threshold: the TIGHTEST maxSkew of the
    # pod's constraints on that selector (+big when unconstrained) —
    # all-k(skew_s <= max_k) == skew_s <= min-k(max_k)
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    sel = jnp.clip(pods.spread_sel, 0, max(s - 1, 0))
    rows = jnp.arange(p)[:, None]
    thresh = jnp.full((p, s), big, jnp.float32).at[rows, sel].min(
        jnp.where(pods.spread_sel >= 0, pods.spread_max.astype(jnp.float32), big)
    )
    aff_pod = jnp.concatenate([a_hot.T, t_hot.T, matches.T, thresh.T], axis=0)
    present = (snapshot.domain_counts > 0).astype(jnp.float32).T
    avoid_present = (snapshot.avoid_counts > 0).astype(jnp.float32).T
    dmin = local_spread_dmin(snapshot)
    # skew of a prospective placement: counts + 1 - dmin, per selector —
    # the same expression (and op order) topology_spread_fit evaluates
    cnt_plus = (snapshot.domain_counts + 1.0 - dmin[None, :]).T
    aff_node = jnp.concatenate([present, avoid_present, cnt_plus], axis=0)
    valid = ~(
        (pods.affinity_sel >= s).any(-1)
        | (pods.anti_affinity_sel >= s).any(-1)
        | (pods.spread_sel >= s).any(-1)
    )
    return aff_pod, aff_node, valid


def _fused_masked_scores(
    snapshot: SnapshotArrays,
    pods: PodBatch,
    *,
    include_pod_affinity: bool,
    normalizer: str = "none",
    layout: "FusedLayout | None" = None,
) -> jnp.ndarray:
    """[p, n] score-where-feasible-else-NEG via the fused Pallas
    megakernel (ops/pallas_fused.py): score, resource fit, spec.nodeName
    pinning, the count-based (anti)affinity/avoider/spread families
    (when the selector axis fits MAX_FUSED_SELECTORS), and the remaining
    constraint mask (cards/taints/node-affinity, computed here and fed
    to the kernel as ONE operand) in a single tiled VMEM pass — plus the
    min-max normalize epilogue when normalizer="min_max". Only the
    balanced_cpu_diskio policy has a fused kernel.

    layout: optional engine.FusedLayout of device-resident kernel-layout
    node buffers — resident cycles skip the per-call transpose/pad/stack
    prep entirely (deltas land straight in kernel layout)."""
    from kubernetes_scheduler_tpu.ops.pallas_fused import (
        MAX_FUSED_SELECTORS,
        fused_masked_score,
    )

    stats = utilization_stats(snapshot.disk_io, snapshot.cpu_pct, snapshot.node_mask)
    s = snapshot.domain_counts.shape[1]
    fold_affinity = include_pod_affinity and s <= MAX_FUSED_SELECTORS
    aff_pod = aff_node = None
    pod_ok = pods.pod_mask
    if fold_affinity:
        aff_pod, aff_node, valid = _fused_affinity_operands(snapshot, pods)
        pod_ok = pod_ok & valid
    gpu_fits, _ = card_fit(
        snapshot.cards, snapshot.card_mask, snapshot.card_healthy,
        pods.want_number, pods.want_memory, pods.want_clock,
    )
    other = gpu_fits & taint_toleration_fit(
        snapshot.taints, snapshot.taint_mask, pods.tolerations, pods.tol_mask
    ) & node_affinity_fit(
        snapshot.node_labels, snapshot.node_label_mask,
        pods.na_key, pods.na_op, pods.na_vals, pods.na_val_mask, pods.na_mask,
        pods.na_term,
    )
    if include_pod_affinity and not fold_affinity:
        # selector axis too wide for the kernel unroll: keep the
        # outside composition for the count-based families
        other = other & pod_affinity_fit(
            snapshot.domain_counts, pods.affinity_sel, pods.anti_affinity_sel
        )
        matches = match_matrix(pods, snapshot.avoid_counts.shape[1])
        other = other & ~anti_reverse_bad(matches, snapshot.avoid_counts)
        other = other & topology_spread_fit(
            snapshot.domain_counts, snapshot.node_mask,
            pods.spread_sel, pods.spread_max,
        )
    return fused_masked_score(
        stats.u, stats.v, snapshot.node_mask,
        snapshot.allocatable, snapshot.requested,
        pods.request[:, 0], pods.r_io, pods.request, pod_ok,
        target_node=pods.target_node,
        other=other.astype(jnp.float32),
        aff_pod=aff_pod, aff_node=aff_node,
        node_prepped=None if layout is None else tuple(layout),
        normalizer=normalizer,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "policy", "assigner", "normalizer", "fused", "affinity_aware",
        "soft", "score_plugins",
    ),
)
def schedule_batch(
    snapshot: SnapshotArrays,
    pods: PodBatch,
    *,
    policy: str = "balanced_cpu_diskio",
    assigner: str = "greedy",
    normalizer: str = "min_max",
    fused: bool = False,
    affinity_aware: bool = True,
    soft: bool = False,
    auction_rounds: int = 1024,
    auction_price_frac: float = 1.0,
    score_plugins: tuple | None = None,
    layout: FusedLayout | None = None,
) -> ScheduleResult:
    """One scheduling cycle for the whole pending window, on device.

    With affinity_aware=True (default), inter-pod (anti)affinity within
    the window is exact on BOTH assigner paths: greedy threads live
    domain counts through its scan, and the auction recomputes its bid
    mask per round against running counts and evicts same-round conflicts
    before placements become permanent (ops/assign.py). Placement order
    under the auction differs from strict greedy; hard-constraint
    satisfaction does not.

    affinity_aware=False drops the per-round dynamic machinery and
    evaluates (anti)affinity statically against PRE-window counts only —
    exact whenever no pending pod in the window matches a selector some
    pod in the window uses (host.scheduler checks exactly that before
    passing False; it saves ~2x on selector-free windows).

    fused=True routes the whole masked-score pipeline — score, resource
    fit, spec.nodeName pinning, the count-based (anti)affinity/avoider/
    spread families, the remaining constraint mask, and (for
    normalizer="min_max") the normalize epilogue — through the fused
    Pallas megakernel (ops/pallas_fused.py): one [p, n] HBM write
    instead of up to seven round-trips. Requires
    policy="balanced_cpu_diskio" and normalizer in ("none", "min_max");
    softmax stays unfused (its exp/sum statistics would fold the NEG
    sentinels). Decisions match the unfused path: the kernel evaluates
    the same expressions on the same operands (mask families are
    boolean-EXACT; score values agree up to XLA's per-graph FMA
    contraction of `alpha*v - beta*u`, so near-ulp ties are pinned
    empirically by tests/test_pallas.py rather than guaranteed
    algebraically), and both assigners read infeasible entries as NEG
    anyway. Contract deviation:
    in fused replies `scores`/`raw_scores` ARE the masked matrix (NEG in
    infeasible cells) — the unmasked policy score is never materialized,
    that being the point of the fusion. Consumers that need scores
    across infeasible cells (e.g. models/learned.py teacher matrices)
    must use fused=False.

    layout: optional FusedLayout of device-resident kernel-layout node
    buffers (resident cycles — see LocalEngine.schedule_resident); only
    consulted on the fused path.

    score_plugins=((name, weight), ...) replaces the single `policy` with
    the upstream framework's weighted multi-plugin combination
    (combine_scores); `policy` and `normalizer` are then ignored —
    per-plugin normalization happens inside the combination and the
    weighted sum is final, as the framework runtime computes it.
    """
    if score_plugins:
        if fused:
            raise ValueError(
                "score_plugins is incompatible with fused=True (the fused "
                "kernel hardwires the single yoda formula)"
            )
        raw = combine_scores(snapshot, pods, score_plugins)
        feasible = compute_feasibility(
            snapshot, pods, include_pod_affinity=not affinity_aware
        )
        return finish_cycle(
            snapshot, pods, raw, raw, feasible,
            assigner=assigner, affinity_aware=affinity_aware, soft=soft,
            auction_rounds=auction_rounds,
            auction_price_frac=auction_price_frac,
        )
    if fused:
        check_fused_contract(policy, normalizer, min_max_ok=True)
        raw = _fused_masked_scores(
            snapshot, pods, include_pod_affinity=not affinity_aware,
            normalizer=normalizer, layout=layout,
        )
        feasible = raw > NEG * 0.5
        norm = raw
    else:
        raw = compute_scores(snapshot, pods, policy)
        feasible = compute_feasibility(
            snapshot, pods, include_pod_affinity=not affinity_aware
        )
        norm = normalize_scores(raw, snapshot.node_mask, normalizer)

    return finish_cycle(
        snapshot, pods, raw, norm, feasible,
        assigner=assigner, affinity_aware=affinity_aware, soft=soft,
        auction_rounds=auction_rounds, auction_price_frac=auction_price_frac,
    )


def schedule_batch_fleet(
    snapshot: SnapshotArrays,
    requests: tuple,
    *,
    policy: str = "balanced_cpu_diskio",
    assigner: str = "greedy",
    normalizer: str = "min_max",
    fused: bool = False,
    affinity_aware: bool = True,
    soft: bool = False,
    auction_rounds: int = 1024,
    auction_price_frac: float = 1.0,
    score_plugins: tuple | None = None,
) -> tuple:
    """N independent scheduling cycles in ONE device invocation — the
    coalesced super-batch behind host/engine_pool.SharedEnginePool.

    `requests` is a tuple of (delta | None, pods) pairs, one per origin
    replica: each window is scored against `snapshot` with its own
    optional SnapshotDelta applied FUNCTIONALLY first (row sets by
    value, never donated — the shared base is untouched), so every
    replica sees exactly the cluster state its private engine would
    have scored, bit for bit, while the fleet ships the common base
    once and only the per-replica divergence rows ride per element.

    Deliberately NOT wrapped in an outer jit: a fleet-wide program
    would key its signature on every element's delta bucket, and a
    growing cluster walking the power-of-two buckets upward recompiles
    the whole program at every crossing — seconds per coalesced
    dispatch, paid exactly when the fleet is busiest. Instead each
    element's delta folds in through fixed-shape chunked scatters
    (`_apply_delta_rows_chunked` — one compiled scatter per leaf
    family, forever) and the element schedules through the SAME cached
    jitted `schedule_batch` a private engine would run; the group still
    costs one pool dispatch/one RPC, and only the shared base crosses
    the host boundary once. The elements are mutually independent — no
    capacity or affinity coupling crosses them — which is what keeps
    first-bind-wins union parity unchanged (the BindTable, not the
    device, resolves races).
    `layout` is deliberately not threaded through: a per-element delta
    invalidates retained kernel-layout buffers, and the fused kernel's
    in-kernel prep is binding-parity-pinned against the injected-layout
    path (PARITY.md round 15)."""
    out = []
    for delta, pods in requests:
        snap = (
            snapshot
            if delta is None
            else _apply_delta_rows_chunked(snapshot, delta)
        )
        out.append(
            schedule_batch(
                snap, pods,
                policy=policy, assigner=assigner, normalizer=normalizer,
                fused=fused, affinity_aware=affinity_aware, soft=soft,
                auction_rounds=auction_rounds,
                auction_price_frac=auction_price_frac,
                score_plugins=score_plugins,
            )
        )
    return tuple(out)


def normalize_scores(
    raw: jnp.ndarray, node_mask: jnp.ndarray, normalizer: str
) -> jnp.ndarray:
    """Dispatch over NORMALIZERS; shared by schedule_batch and the learned
    engine so normalizer semantics cannot diverge."""
    if normalizer == "min_max":
        return min_max_normalize(raw, node_mask)
    if normalizer == "softmax":
        return softmax_normalize(raw, node_mask)
    if normalizer == "none":
        return raw
    raise ValueError(f"unknown normalizer {normalizer!r}")


def finish_cycle(
    snapshot: SnapshotArrays,
    pods: PodBatch,
    raw: jnp.ndarray,
    norm: jnp.ndarray,
    feasible: jnp.ndarray,
    *,
    assigner: str = "greedy",
    affinity_aware: bool = True,
    soft: bool = False,
    auction_rounds: int = 1024,
    auction_price_frac: float = 1.0,
) -> ScheduleResult:
    """Shared cycle tail: soft score terms → assignment → result. Any
    scorer composes with the full constraint/assignment machinery through
    this — schedule_batch's policies and the learned two-tower scorer
    (models/learned.LearnedEngine) both land here."""
    if soft:
        # preferred constraints are score terms layered on the normalized
        # policy score (upstream: weighted sum of scoring plugins). On the
        # fused path NEG-masked cells stay ~NEG (weights << 1e30)
        norm = norm + compute_soft_scores(snapshot, pods)
    free = compute_free_capacity(snapshot)
    affinity = make_affinity_state(snapshot, pods) if affinity_aware else None
    if assigner == "greedy":
        res: AssignResult = greedy_assign(
            norm, feasible, pods.request, free, pods.priority, pods.pod_mask,
            affinity=affinity,
        )
    else:
        res = auction_assign(
            norm, feasible, pods.request, free, pods.priority, pods.pod_mask,
            rounds=auction_rounds, price_frac=auction_price_frac,
            affinity=affinity,
        )
    # gang co-scheduling (ops/gang.py): rescind every placement of a
    # gang that did not fully fit, BEFORE the result leaves the engine —
    # the windows scan's capacity/affinity carries must never see a
    # phantom partial gang. Bitwise identity on gang-free windows.
    from kubernetes_scheduler_tpu.ops.gang import gang_mask_assign

    node_idx, free_after, n_assigned = gang_mask_assign(
        pods.gang_id, pods.gang_size, pods.pod_mask,
        res.node_idx, pods.request, res.free_after, res.n_assigned,
    )
    return ScheduleResult(
        node_idx=node_idx,
        scores=norm,
        raw_scores=raw,
        feasible=feasible,
        free_after=free_after,
        n_assigned=n_assigned,
    )


class WindowsResult(NamedTuple):
    node_idx: jnp.ndarray    # [w, p] int32 per-window assignments, -1 = none
    free_after: jnp.ndarray  # [n, r] free capacity after the last window
    n_assigned: jnp.ndarray  # [] int32 total across windows


def stack_windows(pods: PodBatch, window: int) -> PodBatch:
    """Reshape a [P, ...] PodBatch into [P//window, window, ...] for
    schedule_windows. P must be a multiple of `window` (pad the batch with
    pod_mask=False entries first — utils/padding.py).

    Host numpy inputs stay numpy (zero-copy views): an eager jnp.asarray
    here was ONE DEVICE TRANSFER PER LEAF on the spot — ~40 transfers x
    ~1 ms tunnel latency before the engine even dispatched. Deferring to
    the jit boundary (or LocalEngine's uniform-constant cache, which
    elides the transfer entirely for default-valued leaves) keeps the
    transfer count on the critical path minimal."""
    import numpy as np

    p = pods.request.shape[0]
    if p % window:
        raise ValueError(f"pod count {p} not a multiple of window {window}")

    def reshape(f):
        lib = jnp if isinstance(f, jnp.ndarray) else np
        f = lib.asarray(f)
        return lib.reshape(f, (p // window, window) + f.shape[1:])

    return PodBatch(*[reshape(f) for f in pods])


def fold_window_counts(snapshot, pods, node_idx, domain_counts, avoid_counts):
    """Fold one window's placements into the per-node replicated domain
    match AND avoider count tables so the NEXT window's (anti)affinity
    sees them (the sequential host loop gets this from re-snapshotting
    between cycles). Counts[n, s] are per-node replicated totals of node
    n's domain: increments scatter onto the representative row
    (domain_id) and gather back to every member node. Shared by the
    dense schedule_windows scan and LearnedEngine's windows scan."""
    found = node_idx >= 0
    s = domain_counts.shape[1]
    cols = jnp.arange(s)
    dom = snapshot.domain_id[
        jnp.clip(node_idx, 0, snapshot.domain_id.shape[0] - 1)
    ]  # [p, S]

    def fold(counts, per_pod):
        inc = jnp.where(found[:, None], per_pod.astype(counts.dtype), 0.0)
        added = jnp.zeros_like(counts).at[dom, cols[None, :]].add(inc)
        return counts + added[snapshot.domain_id, cols[None, :]]

    return (
        fold(domain_counts, match_matrix(pods, s)),
        fold(avoid_counts, pod_has_anti_onehot(pods.anti_affinity_sel, s)),
    )


def run_windows_scan(snapshot, pods_windows, cycle_fn) -> "WindowsResult":
    """The capacity- and (anti)affinity-carrying scan over stacked
    windows, parameterized by the per-window cycle (cycle_fn(snap, w) ->
    ScheduleResult). schedule_windows passes schedule_batch; the learned
    engine passes its two-tower cycle — ONE scan, so the carried state
    cannot drift between engines."""

    def step(carry, w):
        requested, domain_counts, avoid_counts = carry
        snap = snapshot._replace(
            requested=requested, domain_counts=domain_counts,
            avoid_counts=avoid_counts,
        )
        res = cycle_fn(snap, w)
        new_counts, new_avoid = fold_window_counts(
            snapshot, w, res.node_idx, domain_counts, avoid_counts
        )
        return (
            (snapshot.allocatable - res.free_after, new_counts, new_avoid),
            (res.node_idx, res.n_assigned),
        )

    (req_final, _, _), (node_idx, counts) = jax.lax.scan(
        step,
        (snapshot.requested, snapshot.domain_counts, snapshot.avoid_counts),
        pods_windows,
    )
    return WindowsResult(
        node_idx=node_idx,
        free_after=snapshot.allocatable - req_final,
        n_assigned=counts.sum().astype(jnp.int32),
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "policy", "assigner", "normalizer", "fused", "affinity_aware",
        "soft", "score_plugins",
    ),
)
def schedule_windows(
    snapshot: SnapshotArrays,
    pods_windows: PodBatch,
    *,
    policy: str = "balanced_cpu_diskio",
    assigner: str = "auction",
    normalizer: str = "none",
    fused: bool = False,
    affinity_aware: bool = True,
    soft: bool = False,
    auction_rounds: int = 1024,
    auction_price_frac: float = 1.0,
    score_plugins: tuple | None = None,
    layout: FusedLayout | None = None,
) -> WindowsResult:
    """Schedule many windows in ONE device program: lax.scan over the
    window axis, carrying node capacity AND (anti)affinity domain counts
    between windows, so a whole pending backlog costs one dispatch + one
    host sync instead of one per window. (On a tunneled/remote device the
    per-call round-trip is ~3 orders of magnitude above per-window compute
    — this is where the batch engine's throughput comes from.)

    pods_windows: a PodBatch whose arrays carry a leading [w, p, ...]
    window axis (see stack_windows). Scores/feasibility matrices are
    internal per-window temporaries here — XLA dead-code-eliminates the
    ScheduleResult fields the scan does not carry out.

    normalizer defaults to "none" (unlike schedule_batch): greedy picks
    per-row argmaxes, unchanged under any monotone row normalization, and
    the auction min-maxes rows internally, making it invariant under
    per-row affine rescaling (min_max gives identical decisions; softmax
    is monotone-but-nonaffine, so auction decisions may differ between
    near-ties). Skipping normalization saves a [p, n] pass per window;
    pass "min_max"/"softmax" to reproduce schedule_batch's configuration
    exactly.

    layout: optional FusedLayout (fused=True only) carried THROUGH the
    scan: node_ft and alloc_t are static across a backlog (utilization
    series and allocatable never change mid-dispatch), so every window
    reuses the retained buffers and only reqd_t — the one leaf the
    capacity carry moves — is rebuilt per window (prep_requested, the
    same expression prep_node_operands applies). Resident multi-window
    cycles thus skip the full per-window prep_node_operands the PR-8
    scan still paid; bindings are bitwise the re-prep path's
    (tests/test_pallas.py pins it).
    """
    if layout is not None and not fused:
        raise ValueError("layout requires fused=True (kernel-layout buffers)")

    def cycle(snap, w):
        lay = None
        if layout is not None:
            from kubernetes_scheduler_tpu.ops.pallas_fused import (
                prep_requested,
            )

            lay = layout._replace(reqd_t=prep_requested(snap.requested))
        return schedule_batch(
            snap, w, policy=policy, assigner=assigner, normalizer=normalizer,
            fused=fused, affinity_aware=affinity_aware, soft=soft,
            auction_rounds=auction_rounds,
            auction_price_frac=auction_price_frac,
            score_plugins=score_plugins,
            layout=lay,
        )

    return run_windows_scan(snapshot, pods_windows, cycle)


@functools.partial(jax.jit, static_argnames=("k_cap",))
def preempt_batch(
    snapshot: SnapshotArrays,
    pods: PodBatch,
    victims,
    *,
    k_cap: int,
):
    """The preemption pass (upstream PostFilter parity) as ONE device
    program: static feasibility against FULL allocatable (could this pod
    ever fit here after evictions) → per-node victim prefix tables →
    candidate selection with upstream's pickOneNodeForPreemption ordering
    (ops/preempt.py). `victims` is an ops.preempt.VictimArrays; the host
    pre-filters non-evictable pods (PDB-exhausted, terminating,
    nomination reservations) to node=-1.

    This is the engine surface the sidecar serves as the Preempt RPC —
    the phase the reference runs inside its compute process (upstream
    PostFilter via /root/reference/go.mod:13) now runs on the device
    side of the bridge, keeping the "host thin, device computes" split
    intact; host/scheduler._run_preemption falls back to in-host
    evaluation when the sidecar predates the RPC.
    """
    from kubernetes_scheduler_tpu.ops.preempt import (
        PreemptAffinity,
        build_victim_tables,
        preempt_candidates,
    )

    # node-local families only: the count-based (anti)affinity/spread
    # families are evaluated per (pod, node, k) against the counts AS
    # ADJUSTED by the candidate evictions (upstream RemovePod parity) —
    # ops/preempt.affinity_after_evictions
    static_ok = compute_feasibility(
        snapshot._replace(requested=jnp.zeros_like(snapshot.requested)),
        pods,
        include_pod_affinity=False,
    )
    s = snapshot.domain_counts.shape[1]
    m = victims.req.shape[0]
    matches = (
        victims.matches
        if victims.matches is not None
        else jnp.zeros((m, s), bool)
    )
    anti = (
        victims.anti if victims.anti is not None else jnp.zeros((m, s), bool)
    )
    tables = build_victim_tables(
        victims.node,
        victims.prio,
        victims.req,
        victims.mask,
        n_nodes=snapshot.allocatable.shape[0],
        k_cap=k_cap,
        victim_start=victims.start,
        victim_matches=matches,
        victim_anti=anti,
    )
    affinity = PreemptAffinity(
        domain_counts=snapshot.domain_counts,
        avoid_counts=snapshot.avoid_counts,
        domain_id=snapshot.domain_id,
        node_mask=snapshot.node_mask,
        affinity_sel=pods.affinity_sel,
        anti_affinity_sel=pods.anti_affinity_sel,
        pod_matches=pods.pod_matches,
        spread_sel=pods.spread_sel,
        spread_max=pods.spread_max,
    )
    return preempt_candidates(
        pods.request,
        pods.priority,
        pods.pod_mask,
        static_ok,
        compute_free_capacity(snapshot),
        tables,
        affinity=affinity,
    )
