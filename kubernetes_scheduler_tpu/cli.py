"""Process entry: the cmd/scheduler/main.go analog.

Subcommands:

    scheduler  run the scheduling loop (simulated cluster or injectable
               sources), the reference's single binary role
    sidecar    run the gRPC engine server (the TPU half of the pod pair)
    bench      the BASELINE.md throughput benchmark (one JSON line)
    config     print the effective SchedulerConfig as JSON
    policies   list registered score policies and plugins

The reference's main() seeds the RNG, builds the cobra command through the
register shim and executes it (cmd/scheduler/main.go:12-21); here the
register shim is kubernetes_scheduler_tpu.register and the "embedded
upstream framework" is host.Scheduler.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import sys
import time

import numpy as np

from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

log = logging.getLogger("yoda_tpu.cli")


def _load_config(args) -> SchedulerConfig:
    cfg = (
        SchedulerConfig.from_json(args.config)
        if getattr(args, "config", None)
        else SchedulerConfig()
    )
    for key in (
        "policy", "assigner", "normalizer", "batch_window",
        "learned_checkpoint", "trace_path", "span_path",
    ):
        v = getattr(args, key, None)
        if v is not None:
            cfg = dataclasses.replace(cfg, **{key: v})
    if getattr(args, "no_tpu", False):
        cfg.feature_gates.tpu_batch_score = False
    return cfg


def _add_config_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", help="SchedulerConfig JSON file")
    p.add_argument("--policy", choices=None, help="score policy override")
    p.add_argument("--assigner", choices=("greedy", "auction"))
    p.add_argument("--normalizer", choices=("min_max", "softmax", "none"))
    p.add_argument("--batch-window", type=int, dest="batch_window")
    p.add_argument(
        "--learned-checkpoint",
        dest="learned_checkpoint",
        help="orbax checkpoint for policy=learned (models/learned.py)",
    )
    p.add_argument(
        "--no-tpu",
        action="store_true",
        help="feature-gate TPUBatchScore=false: scalar fallback path only",
    )
    p.add_argument(
        "--trace",
        dest="trace_path",
        help="cycle flight recorder: journal every cycle under this "
        "directory (trace/; replay with `yoda-tpu trace replay`)",
    )
    p.add_argument(
        "--spans",
        dest="span_path",
        help="per-cycle span telemetry: Chrome-trace-event JSON under "
        "this directory (join with the sidecar's via "
        "`yoda-tpu spans merge`; open in Perfetto)",
    )


def _kube_config(args):
    """Resolve API-server connection: explicit flags > kubeconfig file >
    in-cluster service account > default kubeconfig (the GetConfigOrDie
    resolution order, pkg/yoda/scheduler.go:58)."""
    from kubernetes_scheduler_tpu.kube import KubeConfig

    if args.kube_server:
        # token_path (not a one-shot read): survives kubelet rotation of
        # projected service-account tokens
        return KubeConfig(
            base_url=args.kube_server,
            token_path=args.kube_token_file,
            ca_path=args.kube_ca,
            insecure=args.kube_insecure,
            namespace=args.kube_namespace or "default",
        )
    if args.kubeconfig:
        return KubeConfig.from_kubeconfig(args.kubeconfig)
    try:
        return KubeConfig.in_cluster()
    except (RuntimeError, FileNotFoundError):
        return KubeConfig.from_kubeconfig()


def cmd_scheduler_kube(args, cfg) -> int:
    """Live-cluster mode: list/watch via the API server, bind via the
    Binding subresource, leader-elect on the cluster Lease."""
    from kubernetes_scheduler_tpu.host.advisor import (
        BackgroundAdvisor,
        PrometheusAdvisor,
    )
    from kubernetes_scheduler_tpu.host.leader import LeaderElector
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.kube import (
        KubeBinder,
        KubeClient,
        KubeClusterSource,
        KubeEvictor,
        KubeLease,
    )
    from kubernetes_scheduler_tpu.kube.source import InformerCache, run_kube_loop

    client = KubeClient(_kube_config(args))
    # informer-style cache: nodes + assigned pods maintained by watch
    # threads, so cycles read local state instead of re-listing the
    # cluster each time (the upstream snapshot-from-informers pattern)
    cache = InformerCache(client, watch_timeout=args.watch_timeout).start()
    if not cache.wait_synced(timeout=60.0):
        log.error("informer cache failed to sync within 60s")
        return 1
    source = KubeClusterSource(
        client,
        scheduler_name=cfg.scheduler_name,
        namespace=args.kube_namespace,
        cache=cache,
    )
    engine = None
    if args.engine and args.engine != "local":
        from kubernetes_scheduler_tpu.bridge.client import RemoteEngine

        engine = RemoteEngine(args.engine)
    # background refresh keeps the five Prometheus round-trips OFF the
    # scheduling cycle's latency path (the reference pays them inside
    # PreScore); refresh_interval_seconds=0 restores direct fetching
    advisor = PrometheusAdvisor(cfg.advisor.prometheus_host)
    if cfg.advisor.refresh_interval_seconds > 0:
        advisor = BackgroundAdvisor(
            advisor,
            interval=cfg.advisor.refresh_interval_seconds,
            max_staleness=cfg.advisor.max_staleness_seconds,
        )
    sched = Scheduler(
        cfg,
        advisor=advisor,
        binder=KubeBinder(client, cache=cache, volumes=source.volumes),
        evictor=KubeEvictor(client),
        list_nodes=source.list_nodes,
        list_running_pods=source.list_running_pods,
        list_pdbs=source.list_pdbs,
        controller_replicas=source.controller_replicas,
        engine=engine,
    )
    if sched.mirror is not None:
        # streaming ingestion (config.snapshot_mirror): the informer's
        # node/pod watch events feed the mirror directly; relists reseed
        from kubernetes_scheduler_tpu.kube.source import attach_mirror

        attach_mirror(cache, sched)
    # exporter FIRST: a standby replica blocks in acquire_blocking below,
    # and it must serve /healthz + /metrics for its whole standby life
    # (the deploy manifest's readinessProbe) — upstream kube-scheduler
    # serves healthz while passive too
    exporter = None
    if args.metrics_port:
        from kubernetes_scheduler_tpu.host.observe import MetricsExporter

        exporter = MetricsExporter(sched)
        exporter.serve(args.metrics_port, host=cfg.metrics_bind_host)
    elector = None
    if args.lease_kube or args.lease:
        if args.lease_kube:
            lease = KubeLease(client, name=f"{cfg.scheduler_name}-scheduler")
        else:
            # --lease (file) stays honored under --source=kube: silently
            # ignoring it would run an HA pair with NO leader election
            from kubernetes_scheduler_tpu.host.leader import FileLease

            lease = FileLease(args.lease)
        elector = LeaderElector(lease, identity=args.lease_identity)
        log.info("waiting for leadership")
    try:
        if elector is not None:
            # inside the try: a SIGTERM landing right after the claim
            # succeeds must still release through the finally below
            elector.acquire_blocking()
        cycles = run_kube_loop(
            sched,
            source,
            max_cycles=None if args.serve_forever else args.max_cycles,
            elector=elector,
            exit_when_idle=not args.serve_forever,
            watch_timeout=args.watch_timeout,
        )
    except (KeyboardInterrupt, SystemExit):
        cycles = sched.totals["cycles"]
    finally:
        cache.stop()
        if sched.recorder is not None:
            sched.recorder.close()
        if sched.spans is not None:
            sched.spans.close()
        if hasattr(advisor, "close"):
            advisor.close()  # stop the background refresh thread
        if elector is not None:
            elector.release()
        if exporter is not None:
            exporter.close()
    # totals, not the (bounded) metrics window: run-lifetime counts
    print(
        json.dumps(
            {
                "cycles": cycles,
                "pods_bound": sched.totals["pods_bound"],
                "pods_unschedulable": sched.totals["pods_unschedulable"],
                "pods_dropped": sched.totals["pods_dropped"],
            }
        )
    )
    return 0


def cmd_scheduler(args) -> int:
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster, gen_host_pods

    cfg = _load_config(args)
    if args.source == "kube":
        if args.replicas > 1:
            log.error(
                "--replicas is the sim-source fleet runner; a kube "
                "deployment scales by running one process per "
                "membership slot (see README: Replicated schedulers)"
            )
            return 2
        return cmd_scheduler_kube(args, cfg)
    nodes, advisor = gen_host_cluster(
        args.nodes, seed=args.seed, gpu=args.gpu, constraints=args.constraints
    )
    pods = gen_host_pods(
        args.pods, seed=args.seed + 1, gpu=args.gpu, constraints=args.constraints
    )

    if args.replicas > 1:
        if getattr(args, "shared_engine", False) and not cfg.shared_engine:
            import dataclasses

            cfg = dataclasses.replace(
                cfg, shared_engine=True,
                # the coalescing seam is the async-dispatch path
                pipeline_depth=max(1, cfg.pipeline_depth),
            )
        return _cmd_scheduler_replicated(args, cfg, nodes, advisor, pods)

    engine = None
    if args.engine and args.engine != "local":
        from kubernetes_scheduler_tpu.bridge.client import RemoteEngine

        engine = RemoteEngine(args.engine)

    running: list = []
    sched = Scheduler(
        cfg,
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        engine=engine,
    )
    elector = None
    if args.lease:
        from kubernetes_scheduler_tpu.host.leader import FileLease, LeaderElector

        elector = LeaderElector(FileLease(args.lease), identity=args.lease_identity)
        log.info("waiting for leadership on %s", args.lease)

    exporter = None
    if args.metrics_port:
        from kubernetes_scheduler_tpu.host.observe import MetricsExporter

        exporter = MetricsExporter(sched)
        exporter.serve(args.metrics_port, host=cfg.metrics_bind_host)

    for pod in pods:
        sched.submit(pod)
    t0 = time.perf_counter()
    try:
        if elector is not None:
            elector.acquire_blocking()
        cycles = sched.run_until_empty(max_cycles=args.max_cycles)
    finally:
        # SIGTERM (SystemExit via _terminate) must still release the
        # lease — an unreleased lease stalls standby failover — close
        # the flight-recorder journal, and close the exporter; on the
        # normal path these are no-ops for the exporter in serve-forever
        # mode, handled below
        if elector is not None:
            elector.release()
        if sched.recorder is not None:
            sched.recorder.close()
        if sched.spans is not None:
            sched.spans.close()
    dt = time.perf_counter() - t0
    for binding in sched.binder.bindings:
        running.append(binding.pod)
    bound = sum(c.pods_bound for c in cycles)
    unsched = sum(c.pods_unschedulable for c in cycles)
    print(
        json.dumps(
            {
                "cycles": len(cycles),
                "pods_bound": bound,
                "pods_unschedulable": unsched,
                "seconds": round(dt, 3),
                "pods_per_sec": round(bound / dt, 1) if dt > 0 else None,
                "fallback_cycles": sum(c.used_fallback for c in cycles),
                # bind latency = full cycle wall time (queue pop -> binds),
                # the BASELINE.md north-star latency metric
                "cycle_p50_ms": round(
                    1e3 * float(np.percentile([c.cycle_seconds for c in cycles], 50)), 2
                ) if cycles else None,
                "cycle_p99_ms": round(
                    1e3 * float(np.percentile([c.cycle_seconds for c in cycles], 99)), 2
                ) if cycles else None,
            }
        )
    )
    if exporter is not None and not args.serve_forever:
        exporter.close()
    if args.serve_forever and exporter is not None:
        log.info("metrics on :%d; ctrl-c to exit", args.metrics_port)
        try:
            while True:
                time.sleep(3600)
        except (KeyboardInterrupt, SystemExit):
            exporter.close()
    return 0


def _cmd_scheduler_replicated(args, cfg, nodes, advisor, pods) -> int:
    """`yoda-tpu scheduler --replicas N`: the replicated fleet — N full
    scheduler loops over one partitioned queue and one first-bind-wins
    bind table (host/replica.py). With --lease, each replica loop first
    JOINS the elected membership (host/leader.ReplicaMembership: N slot
    leases at <lease>.slot<i>, slot index == partition index), so a
    second fleet process started against the same lease path finds all
    slots held and stands by — the single-lease active/passive story,
    generalized to N active."""
    from kubernetes_scheduler_tpu.host.queue import namespace_partition
    from kubernetes_scheduler_tpu.host.replica import ReplicaFleet

    n = args.replicas
    engine_factory = None
    if args.engine and args.engine != "local":
        from kubernetes_scheduler_tpu.bridge.client import RemoteEngine

        engine_factory = lambda i: RemoteEngine(args.engine)  # noqa: E731

    memberships = []
    if args.lease:
        from kubernetes_scheduler_tpu.host.leader import ReplicaMembership

        for i in range(n):
            # per-loop identity suffix: one shared identity would make
            # every loop's slot lease look like the same holder
            m = ReplicaMembership.on_files(
                args.lease, n,
                identity=(
                    f"{args.lease_identity}-r{i}"
                    if args.lease_identity else None
                ),
            )
            # blocks while every slot is held — the standby posture,
            # exactly like the single-lease acquire_blocking()
            slot = m.join()
            log.info("replica loop %d holds membership slot %s", i, slot)
            memberships.append(m)

    running: list = []
    fleet = ReplicaFleet(
        cfg,
        n_replicas=n,
        advisor_factory=lambda i: advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        engine_factory=engine_factory,
    )

    # the generated pods all live in "default"; spread them over one
    # tenant namespace per partition (round-robin, exactly balanced for
    # any N) so every replica owns real traffic
    ns_for = {}
    i = 0
    while len(ns_for) < n:
        ns = f"tenant-{i}"
        ns_for.setdefault(namespace_partition(ns, n), ns)
        i += 1
    for j, pod in enumerate(pods):
        pod.namespace = ns_for[j % n]
        fleet.submit(pod)

    exporters = []
    if args.metrics_port:
        from kubernetes_scheduler_tpu.host.observe import MetricsExporter

        class _ReplicaMetricsView:
            """Exporter facade for replica i: the scheduler's own
            surfaces plus the SHARED fleet counters (every replica's
            /metrics shows the whole fleet's conflict picture)."""

            def __init__(self, idx):
                self._sched = fleet.schedulers[idx]
                self._idx = idx

            def __getattr__(self, name):
                return getattr(self._sched, name)

            @property
            def prom_collectors(self):
                return fleet.prom_collectors(self._idx)

        for i in range(n):
            exporter = MetricsExporter(_ReplicaMetricsView(i))
            exporter.serve(args.metrics_port + i, host=cfg.metrics_bind_host)
            exporters.append(exporter)

    t0 = time.perf_counter()
    try:
        evidence = fleet.run_until_empty(max_cycles=args.max_cycles)
    finally:
        for sched in fleet.schedulers:
            if sched.recorder is not None:
                sched.recorder.close()
            if sched.spans is not None:
                sched.spans.close()
        for m in memberships:
            m.leave()
    dt = time.perf_counter() - t0
    cycles = [
        c for result in evidence.pop("replica_results") for c in result
    ]
    bound = sum(c.pods_bound for c in cycles)
    lat = [c.cycle_seconds for c in cycles]
    print(
        json.dumps(
            {
                "replicas": n,
                "cycles": len(cycles),
                "pods_bound": bound,
                "pods_unschedulable": sum(
                    c.pods_unschedulable for c in cycles
                ),
                "seconds": round(dt, 3),
                "pods_per_sec": round(bound / dt, 1) if dt > 0 else None,
                "fallback_cycles": sum(c.used_fallback for c in cycles),
                "cycle_p50_ms": round(
                    1e3 * float(np.percentile(lat, 50)), 2
                ) if cycles else None,
                "cycle_p99_ms": round(
                    1e3 * float(np.percentile(lat, 99)), 2
                ) if cycles else None,
                **evidence,
            }
        )
    )
    if args.serve_forever and exporters:
        log.info("metrics on :%d..%d; ctrl-c to exit",
                 args.metrics_port, args.metrics_port + n - 1)
        try:
            while True:
                time.sleep(3600)
        except (KeyboardInterrupt, SystemExit):
            pass
    for exporter in exporters:
        exporter.close()
    return 0


def cmd_sidecar(args) -> int:
    from kubernetes_scheduler_tpu.bridge import server

    argv = ["--port", str(args.port)]
    if args.metrics_port:
        argv += [
            "--metrics-port", str(args.metrics_port),
            "--metrics-host", args.metrics_host,
        ]
    if args.span_path:
        argv += ["--span-path", args.span_path]
    if args.profile_path:
        argv += ["--profile-path", args.profile_path]
    if args.step_slo_ms:
        argv += ["--step-slo-ms", str(args.step_slo_ms)]
    if args.mesh_devices:
        argv += ["--mesh-devices", str(args.mesh_devices)]
        argv += ["--assigner", args.assigner]
        argv += ["--normalizer", args.normalizer]
        if args.fused:
            argv += ["--fused"]
        if args.assigner == "auction":
            argv += [
                "--auction-rounds", str(args.auction_rounds),
                "--auction-price-frac", str(args.auction_price_frac),
            ]
    return server.main(argv)


def cmd_bench(args) -> int:
    import importlib

    bench = importlib.import_module("bench")
    bench.main()
    return 0


def cmd_trace(args) -> int:
    """Flight-recorder journal tooling: stats/dump read a journal
    without an engine; diff compares two journals on decision content;
    replay re-executes one and exits non-zero on any binding diff."""
    from kubernetes_scheduler_tpu.trace import inspect as tinspect

    if args.trace_cmd == "stats":
        print(json.dumps(tinspect.stats(args.journal)))
        return 0
    if args.trace_cmd == "dump":
        for line in tinspect.dump(args.journal, limit=args.limit):
            print(json.dumps(line))
        return 0
    if args.trace_cmd == "trend":
        from kubernetes_scheduler_tpu.trace.recorder import TraceError
        from kubernetes_scheduler_tpu.trace.trend import (
            TrendError,
            journal_trend,
        )

        try:
            report = journal_trend(
                args.journal,
                windows=args.windows,
                threshold_pct=args.threshold_pct,
                min_ms=args.min_ms,
            )
        except (TraceError, TrendError) as e:
            print(json.dumps({"error": str(e)}))
            return 2
        print(json.dumps(report))
        return 0 if report["clean"] else 1
    if args.trace_cmd == "diff":
        report = tinspect.diff(args.journal, args.other)
        print(json.dumps(report))
        clean = (
            report["differences"] == 0
            and report["extra_records_a"] == 0
            and report["extra_records_b"] == 0
            and not report.get("truncated")
        )
        return 0 if clean else 1
    # replay
    from kubernetes_scheduler_tpu.trace.replay import replay_journal

    engine = None
    if args.engine and args.engine != "local":
        from kubernetes_scheduler_tpu.bridge.client import RemoteEngine

        engine = RemoteEngine(args.engine)
    try:
        report = replay_journal(
            args.journal,
            engine=engine,
            mode=args.mode,
            resident=args.resident,
            record_path=args.out,
            span_path=args.span_path,
        )
    finally:
        if engine is not None:
            engine.close()
    print(json.dumps(report.to_dict()))
    return 1 if report.binding_diffs else 0


def cmd_scenario(args) -> int:
    """Scenario harness (sim/scenarios): seeded adversarial traffic
    programs over the host loop. `list` names them; `run` drives one and
    prints its summary JSON line — with --trace, the run emits a
    flight-recorder journal that `trace replay` must reproduce with zero
    binding diffs (every scenario is replay-pinned)."""
    from kubernetes_scheduler_tpu.sim import scenarios

    if args.scenario_cmd == "list":
        for name in sorted(scenarios.SCENARIOS):
            cls = scenarios.SCENARIOS[name]
            smoke = " [smoke]" if cls.smoke else ""
            print(f"{name:20s} {cls.description}{smoke}")
        return 0
    # run
    overrides: dict = {}
    if args.pipeline:
        overrides["pipeline_depth"] = 1
    if args.resident:
        overrides["resident_state"] = True
        overrides["pipeline_depth"] = 1
    if args.gang_off:
        overrides["gang_scheduling"] = False
    if args.mirror:
        overrides["snapshot_mirror"] = True
    if args.shared_engine:
        # fleet-shared device engine (host/engine_pool): replicated
        # scenarios multiplex every replica onto ONE engine and drain
        # through the split-phase seam so each round-robin round
        # coalesces into one device invocation
        overrides["shared_engine"] = True
        overrides["pipeline_depth"] = 1
    # a chaos program's own config knobs (sim/faults.py: mirror/
    # resident/stale-TTL/breaker settings its fault plan targets) are
    # the baseline; explicit flags win on conflict
    cls = scenarios.SCENARIOS.get(args.name)
    merged = dict(getattr(cls, "config_overrides", {}) or {}) if cls else {}
    merged.update(overrides)
    cfg = scenarios.scenario_config(merged)
    summary = scenarios.run(
        args.name,
        n_nodes=args.nodes,
        intensity=args.intensity,
        seed=args.seed,
        trace_path=args.trace_path,
        span_path=args.span_path,
        config=cfg,
        faults=not args.no_faults,
    )
    print(json.dumps(summary))
    if args.require_recovery and not summary.get("recovered", True):
        print(
            "scenario did not fully recover: "
            + json.dumps(
                {
                    "degradation_rungs": summary.get("degradation_rungs"),
                    "breaker_state": summary.get("breaker_state"),
                    "advisor_breaker_state": summary.get(
                        "advisor_breaker_state"
                    ),
                }
            ),
            file=sys.stderr,
        )
        return 1
    return 0


def cmd_shadow(args) -> int:
    """Shadow-mode serving (host/shadow.py): tail a live flight-recorder
    journal and re-score every cycle through a CANDIDATE config, zero
    writes to the bind path. Prints the decision/latency-diff summary as
    one JSON line; with --metrics-port the shadow's own exporter serves
    the divergence series for Prometheus (the continuous rollout gate);
    --max-divergence-ratio turns the summary into an exit code."""
    from kubernetes_scheduler_tpu.host.shadow import ShadowScheduler
    from kubernetes_scheduler_tpu.trace.recorder import last_journal_seq

    cfg = (
        SchedulerConfig.from_json(args.candidate_config)
        if args.candidate_config
        else SchedulerConfig()
    )
    resume = args.resume_seq
    if args.resume_end:
        resume = last_journal_seq(args.journal)
    shadow = ShadowScheduler(
        args.journal,
        cfg,
        mode=args.mode,
        resume_seq=resume,
        span_path=args.span_path,
    )
    if args.metrics_port is not None:
        port = shadow.serve(args.metrics_port, host=args.metrics_host)
        print(json.dumps({"shadow_metrics_port": port}), flush=True)
    try:
        summary = shadow.run(
            follow=args.follow,
            poll_interval_s=args.poll_interval_s,
            idle_timeout_s=args.idle_timeout_s,
            limit=args.limit,
        )
    finally:
        shadow.close()
    print(json.dumps(summary))
    if (
        args.max_divergence_ratio is not None
        and summary["divergence_ratio"] > args.max_divergence_ratio
    ):
        return 1
    return 0


def cmd_spans(args) -> int:
    """Span-timeline tooling: `merge` joins host + sidecar span
    directories on the shared trace ids into ONE Perfetto-loadable
    Chrome trace (non-zero exit when the two sides share no ids —
    broken metadata propagation); `report` turns a span source into
    per-stage percentiles + the cycle budget attribution table
    (trace/analyze.py); `diff` compares two sources with per-stage
    relative thresholds and exits non-zero on any regression — the
    CI-able perf gate."""
    from kubernetes_scheduler_tpu.trace import spans as tspans

    if args.spans_cmd == "report":
        from kubernetes_scheduler_tpu.trace.analyze import (
            AnalyzeError,
            build_report,
        )

        if args.trend:
            from kubernetes_scheduler_tpu.trace.trend import (
                TrendError,
                build_trend,
            )

            try:
                report = build_trend(
                    args.source,
                    windows=args.trend_windows,
                    warmup=args.trend_warmup,
                    threshold_pct=args.threshold_pct,
                    min_ms=args.min_ms,
                )
            except (AnalyzeError, TrendError) as e:
                print(json.dumps({"error": str(e)}))
                return 2
            print(json.dumps(report))
            return 0 if report["clean"] else 1
        try:
            report = build_report(args.source)
        except AnalyzeError as e:
            print(json.dumps({"error": str(e)}))
            return 1
        print(json.dumps(report))
        return 0
    if args.spans_cmd == "diff":
        from kubernetes_scheduler_tpu.trace.analyze import (
            AnalyzeError,
            diff_reports,
            load_report,
        )

        stage_thresholds = {}
        for spec in args.stage_threshold or ():
            stage, _, pct = spec.partition("=")
            try:
                stage_thresholds[stage] = float(pct)
            except ValueError:
                pct = None
            if not stage or pct is None:
                print(json.dumps(
                    {"error": f"--stage-threshold {spec!r}: want stage=pct"}
                ))
                return 2
        if args.trend:
            from kubernetes_scheduler_tpu.trace.trend import (
                TrendError,
                trend_over_reports,
            )

            sources = [args.baseline, args.candidate, *(args.more or ())]
            try:
                report = trend_over_reports(
                    [load_report(s) for s in sources],
                    threshold_pct=args.threshold_pct,
                    min_ms=args.min_ms,
                )
            except (AnalyzeError, TrendError) as e:
                print(json.dumps({"error": str(e)}))
                return 2
            report["sources"] = sources
            print(json.dumps(report))
            return 0 if report["clean"] else 1
        if args.more:
            print(json.dumps(
                {"error": "extra span sources need --trend (pairwise "
                 "diff takes exactly baseline + candidate)"}
            ))
            return 2
        try:
            report = diff_reports(
                load_report(args.baseline),
                load_report(args.candidate),
                threshold_pct=args.threshold_pct,
                min_ms=args.min_ms,
                stage_thresholds=stage_thresholds,
            )
        except AnalyzeError as e:
            print(json.dumps({"error": str(e)}))
            return 2
        print(json.dumps(report))
        return 0 if report["clean"] else 1
    # merge
    report = tspans.merge_spans(args.host, args.sidecar, args.out)
    print(json.dumps(report))
    if report["merged_events"] == 0:
        return 1
    # a side with NO files was never configured (e.g. a local-engine
    # run has no sidecar spans) — tolerated. A side whose writer ran
    # (files exist: SpanWriter opens its first file eagerly) but
    # contributed no joinable trace ids while the other side has them
    # is the broken-propagation signal this exit code exists for.
    if report["host_trace_ids"] and report["sidecar_files"]:
        if report["joined_trace_ids"] == 0:
            return 1
    if report["sidecar_trace_ids"] and report["host_files"]:
        if report["joined_trace_ids"] == 0:
            return 1
    return 0


def cmd_config(args) -> int:
    print(json.dumps(_load_config(args).to_dict(), indent=2))
    return 0


def cmd_policies(args) -> int:
    from kubernetes_scheduler_tpu import register
    from kubernetes_scheduler_tpu.models.policy import HEURISTIC_POLICIES

    for name, info in sorted(HEURISTIC_POLICIES.items()):
        live = "live" if info.live_in_reference else "alternate"
        print(f"policy   {name:22s} [{live}] {info.description}  ({info.reference})")
    for name in register.registered_plugins():
        print(f"plugin   {name}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="yoda-tpu")
    p.add_argument("-v", "--verbose", action="count", default=0)
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("scheduler", help="run the scheduling loop")
    _add_config_flags(ps)
    ps.add_argument("--nodes", type=int, default=100)
    ps.add_argument("--pods", type=int, default=200)
    ps.add_argument("--seed", type=int, default=0)
    ps.add_argument("--gpu", action="store_true")
    ps.add_argument("--constraints", action="store_true")
    ps.add_argument("--max-cycles", type=int, default=1000)
    ps.add_argument(
        "--engine",
        default="local",
        help='"local" (in-process) or a gRPC target like "localhost:50051"',
    )
    ps.add_argument(
        "--source",
        choices=("sim", "kube"),
        default="sim",
        help='"sim" (generated cluster) or "kube" (live API server)',
    )
    ps.add_argument("--kubeconfig", help="kubeconfig path for --source kube")
    ps.add_argument("--kube-server", help="API server URL (overrides kubeconfig)")
    ps.add_argument("--kube-token-file", help="bearer token file for --kube-server")
    ps.add_argument("--kube-ca", help="CA bundle for --kube-server")
    ps.add_argument("--kube-insecure", action="store_true")
    ps.add_argument(
        "--kube-namespace",
        help="schedule only this namespace (default: all)",
    )
    ps.add_argument(
        "--watch-timeout",
        type=float,
        default=30.0,
        help="seconds per bounded pending-pod watch stream",
    )
    ps.add_argument(
        "--replicas",
        type=int,
        default=1,
        help="run N scheduler replicas over a partitioned queue with "
        "first-bind-wins fencing (sim source; with --lease each "
        "replica joins a membership slot at <lease>.slot<i>)",
    )
    ps.add_argument(
        "--shared-engine", dest="shared_engine", action="store_true",
        help="with --replicas N: multiplex the fleet onto ONE "
        "Local/Remote engine (host/engine_pool) — one resident "
        "snapshot, one upload per churn event, concurrent windows "
        "coalesced into one device invocation; with --engine <addr> "
        "the fleet shares ONE bridge client/session",
    )
    ps.add_argument("--lease", help="leader-election lease file path")
    ps.add_argument(
        "--lease-kube",
        action="store_true",
        help="leader-elect on the cluster coordination.k8s.io Lease",
    )
    ps.add_argument("--lease-identity", default=None)
    ps.add_argument("--metrics-port", type=int, default=0)
    ps.add_argument("--serve-forever", action="store_true")
    ps.set_defaults(fn=cmd_scheduler)

    pc = sub.add_parser("sidecar", help="run the gRPC engine server")
    pc.add_argument("--port", type=int, default=50051)
    pc.add_argument(
        "--metrics-port", type=int, default=0,
        help="sidecar /metrics + /healthz + /debug/profile HTTP port "
        "(0 = disabled)",
    )
    pc.add_argument("--metrics-host", default="0.0.0.0")
    pc.add_argument(
        "--span-path", dest="span_path", default=None,
        help="server-side Chrome-trace spans under this directory",
    )
    pc.add_argument(
        "--profile-path", dest="profile_path", default=None,
        help="where /debug/profile jax.profiler dumps land",
    )
    pc.add_argument(
        "--step-slo-ms", dest="step_slo_ms", type=float, default=0.0,
        help="device-step SLO: steps slower than this bump "
        "slo_breaches_total{rpc} on the sidecar /metrics (0 = off)",
    )
    pc.add_argument("--mesh-devices", type=int, default=0)
    pc.add_argument(
        "--assigner", default="greedy", choices=["greedy", "auction"],
        help="assignment algorithm baked into the sharded engine "
        "(mesh mode only; the dense engine honors per-request assigners)",
    )
    pc.add_argument("--auction-rounds", type=int, default=1024)
    pc.add_argument("--auction-price-frac", type=float, default=1.0)
    pc.add_argument(
        "--normalizer", default="min_max",
        choices=["min_max", "softmax", "none"],
    )
    pc.add_argument(
        "--fused", action="store_true",
        help="fused Pallas score+fit on the sharded engine "
        "(requires --normalizer none)",
    )
    pc.set_defaults(fn=cmd_sidecar)

    pb = sub.add_parser("bench", help="run the throughput benchmark")
    pb.set_defaults(fn=cmd_bench)

    pt = sub.add_parser(
        "trace",
        help="flight-recorder journals: dump/stats/diff/replay/trend",
    )
    tsub = pt.add_subparsers(dest="trace_cmd", required=True)
    td = tsub.add_parser("dump", help="per-record summaries as JSON lines")
    td.add_argument("journal", help="journal directory")
    td.add_argument("--limit", type=int, default=None)
    ts = tsub.add_parser("stats", help="whole-journal aggregates")
    ts.add_argument("journal")
    tf = tsub.add_parser(
        "diff",
        help="record-by-record decision diff of two journals "
        "(exit 1 on any difference)",
    )
    tf.add_argument("journal")
    tf.add_argument("other")
    tr = tsub.add_parser(
        "replay",
        help="re-execute a journal and diff bindings bitwise "
        "(exit 1 on any diff)",
    )
    tr.add_argument("journal")
    tr.add_argument(
        "--engine",
        default="local",
        help='"local" or a gRPC sidecar target like "localhost:50051"',
    )
    tr.add_argument("--mode", choices=("serial", "pipelined"), default="serial")
    tr.add_argument(
        "--resident",
        action="store_true",
        help="drive the resident-state delta-upload surface",
    )
    tr.add_argument(
        "--out",
        default=None,
        help="re-record the replayed cycles as a new journal here",
    )
    tr.add_argument(
        "--spans",
        dest="span_path",
        default=None,
        help="re-emit every replayed cycle as Chrome-trace spans under "
        "this directory (post-hoc attribution for a telemetry-off "
        "journal; analyze with `spans report`/`spans diff`)",
    )
    tn = tsub.add_parser(
        "trend",
        help="soak-length leak & drift gate over one journal: windowed "
        "regression slopes for p99 creep, queue-depth runaway, "
        "resident-state growth and delta hit-rate decay (exit 1 on a "
        "regression, 2 on error)",
    )
    tn.add_argument("journal")
    tn.add_argument(
        "--windows", type=int, default=6,
        help="number of equal record slices the journal is cut into",
    )
    tn.add_argument(
        "--threshold-pct", type=float, default=25.0,
        help="relative first-to-last growth a series must show to fail",
    )
    tn.add_argument(
        "--min-ms", type=float, default=0.05,
        help="absolute cycle-latency growth floor (sub-tick jitter "
        "must not fail soaks)",
    )
    pt.set_defaults(fn=cmd_trace)

    pz = sub.add_parser(
        "scenario",
        help="scenario harness: seeded adversarial traffic programs "
        "(sim/scenarios), replay-pinned via the flight recorder",
    )
    zsub = pz.add_subparsers(dest="scenario_cmd", required=True)
    zl = zsub.add_parser("list", help="list registered scenarios")
    zl.set_defaults(fn=cmd_scenario)
    zr = zsub.add_parser(
        "run", help="run one scenario; prints a summary JSON line"
    )
    zr.add_argument("name", help="a registered scenario (see `list`)")
    zr.add_argument("--nodes", type=int, default=64)
    zr.add_argument(
        "--intensity", type=float, default=1.0,
        help="traffic scale factor relative to the node count",
    )
    zr.add_argument("--seed", type=int, default=0)
    zr.add_argument(
        "--trace", dest="trace_path", default=None,
        help="emit a flight-recorder journal under this directory "
        "(replay-pin with `yoda-tpu trace replay`)",
    )
    zr.add_argument(
        "--spans", dest="span_path", default=None,
        help="emit per-cycle span timelines under this directory "
        "(adversarial programs produce attribution data: analyze with "
        "`yoda-tpu spans report`)",
    )
    zr.add_argument(
        "--pipeline", action="store_true",
        help="drive the pipelined host loop (pipeline_depth=1)",
    )
    zr.add_argument(
        "--resident", action="store_true",
        help="device-resident cluster state (implies --pipeline)",
    )
    zr.add_argument(
        "--gang-off", action="store_true",
        help="disable gang co-scheduling (gang labels ignored)",
    )
    zr.add_argument(
        "--mirror", action="store_true",
        help="streaming state ingestion (snapshot_mirror): the world "
        "drives informer-style events through the event-sourced "
        "snapshot mirror instead of per-cycle rebuilds",
    )
    zr.add_argument(
        "--shared-engine", dest="shared_engine", action="store_true",
        help="fleet-shared device engine (replicated scenarios): ONE "
        "resident engine behind host/engine_pool, replicas' windows "
        "coalesced into one device invocation per round (implies "
        "--pipeline; no-op for replicas=1 scenarios)",
    )
    zr.add_argument(
        "--no-faults", action="store_true",
        help="run a chaos program's traffic WITHOUT its fault plan "
        "(the clean A/B twin of the same seeded run)",
    )
    zr.add_argument(
        "--require-recovery", action="store_true",
        help="exit 1 unless the run ends fully recovered (every "
        "degradation-ladder rung at top, breakers closed) — the "
        "chaos-smoke gate",
    )
    zr.set_defaults(fn=cmd_scenario)

    pw = sub.add_parser(
        "shadow",
        help="shadow-mode serving: tail a live flight-recorder journal "
        "and re-score every cycle through a CANDIDATE config — "
        "decision/latency diffs on a dedicated /metrics exporter, "
        "zero writes to the bind path (the rollout gate)",
    )
    pw.add_argument("journal", help="journal directory to tail")
    pw.add_argument(
        "--candidate-config", default=None,
        help="candidate SchedulerConfig JSON (default: built-in "
        "defaults) — policy/assigner/normalizer/plugins/auction knobs "
        "override the recorded engine options per cycle",
    )
    pw.add_argument(
        "--mode", choices=("serial", "pipelined"), default="serial",
        help="candidate dispatch mode (pipelined = async handle path)",
    )
    pw.add_argument(
        "--follow", action="store_true",
        help="keep tailing across rotations until idle-timeout or "
        "interrupt (without it: one catch-up pass over what exists)",
    )
    pw.add_argument(
        "--poll-interval-s", type=float, default=0.25,
        help="(--follow) sleep between empty polls",
    )
    pw.add_argument(
        "--idle-timeout-s", type=float, default=None,
        help="(--follow) stop after this long with no new records",
    )
    pw.add_argument(
        "--limit", type=int, default=None,
        help="stop after scoring this many records",
    )
    pw.add_argument(
        "--resume-seq", type=int, default=None,
        help="skip records with seq <= this (resume a prior shadow)",
    )
    pw.add_argument(
        "--resume-end", action="store_true",
        help="resume past everything already in the journal (score "
        "only records written after startup)",
    )
    pw.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve the shadow's own /metrics exporter on this port "
        "(0 = ephemeral; the bound port is printed as a JSON line)",
    )
    pw.add_argument("--metrics-host", default="127.0.0.1")
    pw.add_argument(
        "--spans", dest="span_path", default=None,
        help="emit shadow span timelines (reconstruct/candidate_step/"
        "decision_diff) under this directory",
    )
    pw.add_argument(
        "--max-divergence-ratio", type=float, default=None,
        help="exit 1 when the final bindings-changed / pods-compared "
        "ratio exceeds this (the CI-able rollout gate)",
    )
    pw.set_defaults(fn=cmd_shadow)

    pn = sub.add_parser(
        "spans",
        help="span timelines: merge host + sidecar files, per-stage "
        "budget reports, regression diffs",
    )
    nsub = pn.add_subparsers(dest="spans_cmd", required=True)
    nm = nsub.add_parser(
        "merge",
        help="join host and sidecar span directories on trace id into "
        "one Perfetto-loadable Chrome trace (exit 1 when non-empty "
        "sides share no trace ids)",
    )
    nm.add_argument("host", help="host span directory (--spans)")
    nm.add_argument("sidecar", help="sidecar span directory (--span-path)")
    nm.add_argument("--out", required=True, help="merged trace JSON path")
    nr = nsub.add_parser(
        "report",
        help="per-stage p50/p95/p99 + the cycle budget attribution "
        "table from a span directory, a merged trace, or one span file "
        "(exit 1 when there is nothing to report on)",
    )
    nr.add_argument(
        "source", help="span directory / merged trace JSON / span file"
    )
    nr.add_argument(
        "--trend", action="store_true",
        help="slice ONE soak-length span source into time windows and "
        "gate on monotone p50/p99 drift instead of printing the "
        "budget table (exit 1 on a regression, 2 on error)",
    )
    nr.add_argument(
        "--trend-windows", type=int, default=8,
        help="number of equal time slices for --trend",
    )
    nr.add_argument(
        "--trend-warmup", type=int, default=1,
        help="(--trend) leading non-empty windows to drop as warmup "
        "(JIT compile / cold caches) when enough points remain",
    )
    nr.add_argument(
        "--threshold-pct", type=float, default=25.0,
        help="(--trend) relative growth a series must show to fail",
    )
    nr.add_argument(
        "--min-ms", type=float, default=0.05,
        help="(--trend) absolute growth floor below which a series "
        "never regresses",
    )
    nd = nsub.add_parser(
        "diff",
        help="compare two span sources (or saved reports) per stage; "
        "exit 1 on any p50 regression over the thresholds — the "
        "CI-able perf gate",
    )
    nd.add_argument("baseline", help="span dir / merged trace / report JSON")
    nd.add_argument("candidate", help="span dir / merged trace / report JSON")
    nd.add_argument(
        "more", nargs="*",
        help="(--trend) additional span sources, oldest -> newest",
    )
    nd.add_argument(
        "--trend", action="store_true",
        help="treat baseline/candidate/MORE as a time-ordered series "
        "of soak snapshots and fail on a monotone p50/p99 regression "
        "slope across them (exit 1 on a regression, 2 on error)",
    )
    nd.add_argument(
        "--threshold-pct", type=float, default=25.0,
        help="default per-stage relative p50 regression threshold",
    )
    nd.add_argument(
        "--min-ms", type=float, default=0.05,
        help="absolute p50 growth floor below which a stage never "
        "regresses (sub-tick jitter must not fail builds)",
    )
    nd.add_argument(
        "--stage-threshold", action="append", metavar="STAGE=PCT",
        help="per-stage threshold override (repeatable), e.g. "
        "engine_step=10; use stage name `cycle` for the whole-cycle row",
    )
    pn.set_defaults(fn=cmd_spans)

    pf = sub.add_parser("config", help="print effective config")
    _add_config_flags(pf)
    pf.set_defaults(fn=cmd_config)

    pp = sub.add_parser("policies", help="list policies and plugins")
    pp.set_defaults(fn=cmd_policies)
    return p


def _terminate(signum, frame):
    """SIGTERM -> SystemExit so `finally` blocks run: Kubernetes stops
    pods with SIGTERM, and the serve loops must release the leader Lease
    on the way out (an unreleased lease stalls failover for the full
    lease duration) and close exporters/caches cleanly."""
    raise SystemExit(143)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose >= 2 else
        logging.INFO if args.verbose == 1 else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    try:
        import signal

        signal.signal(signal.SIGTERM, _terminate)
    except ValueError:
        pass  # not the main thread (embedded use): skip
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
