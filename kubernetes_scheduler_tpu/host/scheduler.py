"""The scheduling loop: queue -> snapshot -> engine -> bind.

This is the layer the reference gets for free from the embedded upstream
kube-scheduler (SURVEY.md §1: queue, node snapshot, binding cycle, leader
election) — rebuilt around batching: instead of one pod per cycle with a
per-node plugin fan-out, each cycle pops a priority-ordered window of
pending pods, builds one dense snapshot, runs one device program, and
emits all bindings.

Fallback: with feature gate tpu_batch_score=False (the design's
`--feature-gates=TPUBatchScore=false`) the loop runs the scalar per-pod
plugin path (host/plugins.py) — same scheduling decisions, no device —
which is also the recovery path if the device is unreachable: an engine
failure flips one cycle to scalar rather than stalling scheduling.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, NamedTuple

import numpy as np

from kubernetes_scheduler_tpu.engine import LocalEngine
from kubernetes_scheduler_tpu.host.advisor import NodeUtil
from kubernetes_scheduler_tpu.host.plugins import ScalarYodaPlugin, scalar_schedule_one
from kubernetes_scheduler_tpu.host.queue import (
    break_gang,
    make_queue,
    pod_gang,
    pod_priority,
)
from kubernetes_scheduler_tpu.ops.constraints import (
    PREFER_NO_SCHEDULE as _PREFER_NO_SCHEDULE,
)
from kubernetes_scheduler_tpu.host.snapshot import (
    FLAG_PLAIN as _FLAG_PLAIN,
    FLAG_SOFT as _FLAG_SOFT,
    _SCAL_DT,
    SnapshotBuilder,
    pod_batch_record,
    pod_flags as _pod_flags,
    pod_resource_request,
    suffix_record,
    suffix_start,
)
from kubernetes_scheduler_tpu.host.types import Node, Pod
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

log = logging.getLogger("yoda_tpu.scheduler")


def _pod_key(pod: Pod) -> str:
    """Identity that survives delete-and-recreate under the same name
    (kube.source.pod_key semantics)."""
    return pod.uid or f"{pod.namespace}/{pod.name}"


class Binding(NamedTuple):
    # NamedTuple (not dataclass): RecordingBinder.bind_many constructs
    # one per bind — tuple __new__ measured ~2x faster than dataclass
    # __init__ at 8k binds/cycle, and bindings are immutable records
    pod: Pod
    node_name: str


class RecordingBinder:
    """Binder for simulation/tests; a k8s binder would POST
    pods/<p>/binding here (the process boundary at SURVEY.md §3.2)."""

    def __init__(self):
        self.bindings: list[Binding] = []

    def bind(self, pod: Pod, node_name: str) -> None:
        pod.node_name = node_name
        self.bindings.append(Binding(pod, node_name))

    def bind_many(self, pods: list[Pod], node_names: list[str]) -> None:
        """Bulk surface the cycle's bind loop uses when available (must
        not raise — a binder with per-pod failure modes, like the live
        KubeBinder's 404/409 handling, should NOT define it and keep the
        per-pod path)."""
        for pod, nm in zip(pods, node_names):
            pod.node_name = nm
        self.bindings.extend(map(Binding, pods, node_names))


@dataclass
class Eviction:
    victim: Pod
    preemptor: Pod


class RecordingEvictor:
    """Evictor for simulation/tests; the live equivalent is
    kube.KubeEvictor (DELETE the victim pod with a UID precondition).
    Passing an evictor to Scheduler enables the preemption pass
    (upstream PostFilter parity, ops/preempt.py)."""

    def __init__(self):
        self.evictions: list[Eviction] = []

    def evict(self, victim: Pod, *, preemptor: Pod) -> None:
        self.evictions.append(Eviction(victim, preemptor))


@dataclass
class CycleMetrics:
    """Per-cycle observability (SURVEY.md §5: the reference exports
    nothing; we track the north-star numbers)."""

    pods_in: int = 0
    pods_bound: int = 0
    pods_unschedulable: int = 0
    # pods forgotten after a bind-time lifecycle race (deleted -> 404,
    # bound by a racer -> 409) — routine churn, NOT scheduling failures,
    # so they get their own counter and never pollute pods_unschedulable
    pods_dropped: int = 0
    # preemption pass (upstream PostFilter parity): preemptors that got a
    # candidate this cycle, and the victims evicted for them
    pods_preempted: int = 0
    victims_evicted: int = 0
    cycle_seconds: float = 0.0
    engine_seconds: float = 0.0
    used_fallback: bool = False
    # cluster-source/advisor fetch failed; window requeued, nothing ran.
    # Distinct from used_fallback so an advisor outage cannot masquerade
    # as scalar-fallback (TPU-path) degradation on dashboards
    fetch_failed: bool = False
    # the scalar fallback could not score config.policy (e.g. "learned")
    # and used the yoda formula instead — a POLICY change under
    # degradation, distinct from benign same-policy fallback
    policy_mismatch: bool = False
    # advisor stale-TTL grace (config.advisor_stale_ttl_s): this cycle
    # was served the LAST-GOOD cluster state because the advisor fetch
    # failed (or was held by the outage backoff) — scheduling flowed on
    # marked-stale utilization instead of stalling the window
    advisor_stale: bool = False
    # degradation ladder (host/resilience.DegradationLadder): the
    # subsystems sitting below their top rung when this cycle
    # completed — journaled with the cycle, so chaos runs are
    # replay-auditable ("which cycles ran degraded, and on what")
    degraded: tuple = ()
    # pipelined loop (config.pipeline_depth >= 1): host work done while
    # the engine call was in flight (the overlap win — next-cycle pop,
    # record warming, speculative pod-batch build), and speculative-state
    # discards (informer/layout churn, engine failure, non-device paths)
    host_overlap_seconds: float = 0.0
    pipeline_flushes: int = 0
    # resident cluster state (config.resident_state): how this cycle's
    # snapshot reached the engine — a SnapshotDelta applied to the
    # device-retained state (delta_uploads) or a full upload
    # (full_uploads; also counts resident cycles whose delta the engine
    # had to reject — epoch/shape mismatch degrades to full
    # transparently). delta_bytes_saved is the payload the delta avoided
    # shipping vs. the full snapshot.
    delta_uploads: int = 0
    full_uploads: int = 0
    delta_bytes_saved: int = 0
    # mesh-sharded engine (config.sharded_engine): device cycles served
    # by the sharded engine, and — for resident delta cycles — the
    # per-shard routed SnapshotDelta payload bytes (tuple indexed by
    # shard; empty when the cycle shipped no routed delta). The
    # {shard}-labeled byte counter and the flat-bytes bench gate read
    # these.
    sharded_cycles: int = 0
    shard_delta_bytes: tuple = ()
    # gang co-scheduling (config.gang_scheduling; ops/gang.py): gangs
    # whose every member bound this cycle, gangs deferred as a unit
    # (short of members in the window, partial device fit, or a scalar-
    # fallback cycle — gangs never bind through the scalar path), and
    # the tentative placements the all-or-nothing rule rescinded
    gangs_admitted: int = 0
    gangs_deferred: int = 0
    gang_pods_masked: int = 0


@dataclass
class _CycleStart:
    """State the cycle front-end (_begin_cycle: pop/fetch/eligibility)
    hands the path back-ends — one struct, so the serial and pipelined
    drivers cannot diverge on what a cycle knows."""

    window: list
    nodes: list
    running: list
    utils: dict
    eph_running: bool
    scalar_eligible: bool
    use_device: bool
    backlog: bool
    cells: int
    t_path: float


@dataclass
class _InFlight:
    """One dispatched-but-unforced engine call (the 1-deep pipeline)."""

    handle: object       # .result() -> ScheduleResult (engine.PendingSchedule)
    pods_batch: object   # the dispatched PodBatch (validation + deltas)
    t_eng: float         # dispatch timestamp (engine wall time)
    # resident-state accounting: was this a resident dispatch, did the
    # host send a delta, and how many bytes the delta saved vs. the full
    # snapshot (attributed in _complete_window once the engine reports
    # which path actually served the call)
    resident: bool = False
    delta_sent: bool = False
    delta_bytes_saved: int = 0
    # flight-recorder context for this dispatch (config.trace_path):
    # snapshot/pods/kw references plus, after the force, the node_idx —
    # host numpy only, so holding them costs nothing on the device path
    trace_ctx: dict | None = None


class _PendingCycle:
    """Handle from Scheduler.run_cycle_split(): the dispatch half has
    run; .complete() forces the in-flight engine call (with the full
    fallback chain) and finishes the cycle. Cycles that never reached
    the device (scalar, backlog, empty queue, failed dispatch) arrive
    already completed and .complete() just returns their metrics.
    Complete every handle exactly once, before the next run_cycle/
    run_cycle_split on the same scheduler."""

    __slots__ = ("_sched", "_m", "_flight")

    def __init__(self, sched, m, flight):
        self._sched = sched
        self._m = m
        self._flight = flight  # None => cycle already finished

    @property
    def dispatched(self) -> bool:
        """True while an engine call is in flight for this cycle."""
        return self._flight is not None

    def complete(self):
        if self._flight is None:
            return self._m
        start, infl, t0 = self._flight
        self._flight = None
        return self._sched._complete_cycle_split(self._m, start, infl, t0)


class Scheduler:
    def __init__(
        self,
        config: SchedulerConfig,
        *,
        advisor,
        binder=None,
        evictor=None,
        list_nodes: Callable[[], list[Node]],
        list_running_pods: Callable[[], list[Pod]],
        list_pdbs: Callable[[], list] | None = None,
        controller_replicas: Callable[[str, str, str], int | None] | None = None,
        engine=None,
        queue_clock: Callable[[], float] | None = None,
        queue=None,
    ):
        self.config = config
        self.advisor = advisor
        if config.sharded_engine and config.policy == "learned":
            # before the learned block: failing here must not pay a
            # checkpoint load it immediately discards
            raise ValueError(
                "sharded_engine has no learned-policy path yet; use a "
                "sharded sidecar with --learned-checkpoint instead"
            )
        if config.policy == "learned":
            from kubernetes_scheduler_tpu.models.learned import (
                LearnedEngine,
                init_train_state,
                load_learned_engine,
            )

            if not config.feature_gates.tpu_batch_score:
                raise ValueError(
                    "policy='learned' requires the engine path "
                    "(feature_gates.tpu_batch_score=True); the scalar "
                    "fallback only implements the yoda formula"
                )
            if engine is not None and not isinstance(engine, LearnedEngine):
                # a remote/in-process heuristic engine cannot evaluate the
                # learned policy (no parameters); failing loud beats every
                # cycle erroring into the scalar yoda fallback forever
                raise ValueError(
                    "policy='learned' requires a LearnedEngine; got "
                    f"{type(engine).__name__} (remote sidecars do not serve "
                    "the learned policy)"
                )
            if engine is None and config.learned_checkpoint:
                engine = load_learned_engine(config.learned_checkpoint)
            elif engine is None:
                import jax as _jax

                log.warning(
                    "policy='learned' with no learned_checkpoint: scheduling "
                    "with freshly initialized (UNTRAINED) scorer parameters"
                )
                state, model, _ = init_train_state(_jax.random.key(0))
                engine = LearnedEngine(state.params, model=model)
        if engine is None and config.sharded_engine:
            # the mesh-sharded in-process engine: node axis over every
            # visible device (parallel/engine.ShardedEngine picks the
            # largest divisor of 8, matching the builder's node-bucket
            # multiple); both drivers dispatch through the same
            # _dispatch_resident/_dispatch_windows surfaces unchanged
            from kubernetes_scheduler_tpu.parallel.engine import (
                ShardedEngine,
            )

            engine = ShardedEngine()
        self.engine = engine or LocalEngine()
        # auction knobs ride only engines whose call surface takes them
        # (LocalEngine's **kw and RemoteEngine's explicit params both do;
        # the knobs ride the ScheduleRequest wire fields) — gating on the
        # SIGNATURE so an engine predating the wire fields degrades to
        # defaults instead of TypeError-ing every cycle into the scalar
        # fallback
        import inspect

        try:
            params = inspect.signature(self.engine.schedule_batch).parameters
            self._engine_takes_auction_kw = "auction_price_frac" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):
            self._engine_takes_auction_kw = False
        # deep-queue batching needs the windows surface; flips False at
        # runtime if a version-skewed sidecar answers UNIMPLEMENTED
        self._engine_windows_ok = hasattr(self.engine, "schedule_windows")
        self.binder = binder or RecordingBinder()
        self.evictor = evictor
        self._cycle_unsched: list[Pod] = []
        self._cycle_bound: list[Pod] = []
        # victims whose DELETE was issued but that still appear in
        # list_running_pods (termination grace): never re-evicted, and
        # their nodes are off-limits to further preemption until the
        # capacity actually frees (poor-man's nominatedNodeName)
        self._pending_evictions: dict[str, str] = {}  # pod key -> node name
        # preemptor key -> (nominated node, preemptor pod, expiry):
        # a pod that already triggered evictions waits for that node's
        # capacity — reserved via a virtual running pod — instead of
        # evicting more victims elsewhere every retry cycle (upstream
        # nominatedNodeName semantics)
        self._nominations: dict[str, tuple[str, Pod, float]] = {}
        self.list_nodes = list_nodes
        self.list_running_pods = list_running_pods
        # PodDisruptionBudgets for the preemption pass (None = no budgets
        # consulted, e.g. simulated clusters without PDBs)
        self.list_pdbs = list_pdbs
        # (kind, namespace, name) -> spec.replicas resolver for the PDB
        # percentage math's expected count (upstream disruption-controller
        # semantics); None = current-count fallback
        self.controller_replicas = controller_replicas
        if config.feature_gates.native_host:
            from kubernetes_scheduler_tpu import native

            self._native_ok = native.available()
            if not self._native_ok:
                log.warning(
                    "native_host enabled but libyoda_host unavailable; "
                    "using pure-Python host paths"
                )
        else:
            self._native_ok = False
        # queue_clock: injectable retry-backoff clock (default wall
        # monotonic) — the scenario harness passes a virtual clock so
        # backoffs resolve in simulated ticks, deterministically.
        # queue: injectable pre-built queue (any SchedulingQueue-surface
        # object) — the replicated fleet (host/replica.py) hands each
        # replica its ReplicaCoordinator, a partition of the shared
        # queue fenced by the bind table, through this seam
        self.queue = queue if queue is not None else make_queue(
            initial_backoff=config.initial_backoff_seconds,
            max_backoff=config.max_backoff_seconds,
            prefer_native=self._native_ok,
            **({"clock": queue_clock} if queue_clock is not None else {}),
        )
        self.builder = SnapshotBuilder(
            extended_resources=list(config.extended_resources),
            gang_scheduling=config.gang_scheduling,
            # warm-restart pre-size (`trace stats` peak_selector_slots):
            # start the selector bucket at the prior run's peak so the
            # early power-of-two crossings never flush the mirror
            initial_selectors=config.mirror_initial_selectors,
        )
        # event-driven cycle triggering (config.cycle_trigger="event"):
        # queue pushes and mirror events notify the trigger the host
        # loops sleep on; "tick" (default) keeps the fixed-poll waits
        if config.cycle_trigger not in ("tick", "event"):
            raise ValueError(
                f"unknown cycle_trigger {config.cycle_trigger!r}; "
                "expected 'tick' or 'event'"
            )
        from kubernetes_scheduler_tpu.host.mirror import (
            CycleTrigger,
            SnapshotMirror,
        )

        self.trigger = (
            CycleTrigger() if config.cycle_trigger == "event" else None
        )
        # streaming state ingestion (config.snapshot_mirror): the
        # event-sourced mirror replaces the per-cycle build_snapshot/
        # snapshot_delta pair on the hot path; the advisor is wrapped
        # for changed-node fetches unless it already coalesces
        self.mirror = None
        if config.snapshot_mirror:
            self.mirror = SnapshotMirror(
                self.builder,
                verify_interval=config.mirror_verify_interval,
                on_dirty=(
                    self.trigger.notify if self.trigger is not None else None
                ),
            )
            if not hasattr(self.advisor, "fetch_changed"):
                from kubernetes_scheduler_tpu.host.advisor import (
                    CoalescingAdvisor,
                )

                self.advisor = CoalescingAdvisor(self.advisor)
        if config.adaptive_dispatch:
            from kubernetes_scheduler_tpu.utils.adaptive import AdaptiveDispatch

            self._dispatch = AdaptiveDispatch(config.min_device_work)
        else:
            self._dispatch = None
        self._scalar_cycler = None
        # gang co-scheduling (config.gang_scheduling): gang key ->
        # consecutive front-of-queue deferrals; cleared on admission,
        # resolved per config.gang_defer_policy when the budget runs out
        if config.gang_defer_policy not in ("split", "drop"):
            raise ValueError(
                f"unknown gang_defer_policy {config.gang_defer_policy!r}; "
                "expected 'split' or 'drop'"
            )
        self._gang_defers: dict[str, int] = {}
        # bounded: a long-lived process keeps the last window of cycle
        # metrics (latency quantiles), while monotonic run totals live in
        # self.totals — Prometheus counters must never decrease, and the
        # rolling window alone would make them sawtooth after eviction
        from collections import deque

        self.metrics: deque[CycleMetrics] = deque(maxlen=8192)
        self.totals = {
            "cycles": 0,
            "pods_bound": 0,
            "pods_unschedulable": 0,
            "pods_dropped": 0,
            "pods_preempted": 0,
            "victims_evicted": 0,
            "fallback_cycles": 0,
            "fetch_failures": 0,
            "fallback_policy_mismatch": 0,
            "pipeline_flushes": 0,
            "host_overlap_seconds": 0.0,
            "delta_uploads": 0,
            "full_uploads": 0,
            "delta_bytes_saved": 0,
            "sharded_cycles": 0,
            "shard_delta_bytes": 0,
            "gangs_admitted": 0,
            "gangs_deferred": 0,
            "gang_pods_masked": 0,
            "advisor_stale_cycles": 0,
            "degraded_cycles": 0,
        }
        # resident cluster state (config.resident_state): the last full
        # snapshot the engine confirmed retaining (the delta base), the
        # epoch the next upload will be tagged with, and whether the
        # engine-side state is trusted — flipped False on engine
        # failure, epoch desync, or preemption so the next dispatch
        # flushes to a full upload
        self._resident_prev = None
        self._resident_epoch = 0
        self._resident_ok = False
        # pipelined loop state (config.pipeline_depth >= 1): the window
        # prefetched while the previous cycle's engine call was in
        # flight, and the speculative pod batch prebuilt for it (kept at
        # dispatch time only if the layout fingerprint still matches)
        self._prefetched: list[Pod] | None = None
        self._spec_batch: tuple | None = None  # (window, fingerprint, batch)
        # appends/reads cross threads (scheduling loop vs /metrics scrape;
        # deque raises on mutation during iteration, unlike list)
        self._metrics_lock = threading.Lock()
        # cycle flight recorder (config.trace_path; trace/recorder.py):
        # one record per cycle appended from the completion stage —
        # never from the dispatch path
        self.recorder = None
        if config.trace_path:
            from kubernetes_scheduler_tpu.trace.recorder import CycleRecorder

            self.recorder = CycleRecorder(
                config.trace_path,
                file_bytes=config.trace_file_bytes,
                max_bytes=config.trace_max_bytes,
            )
        # per-cycle dispatch contexts the recorder reads in _finish_cycle
        self._trace_cycle: list[dict] = []
        # per-cycle span telemetry (config.span_path; observe.SpanRecorder
        # over trace/spans.py): collection appends perf_counter pairs on
        # the cycle path; Chrome-event encoding and the file write happen
        # in _finish_cycle AFTER the cycle's bookkeeping — the same
        # off-the-critical-path discipline as the flight recorder. The
        # cycle's trace id also rides gRPC metadata (engine.set_trace_id)
        # so sidecar-side spans join the host timeline.
        self.spans = None
        if config.span_path:
            from kubernetes_scheduler_tpu.host.observe import SpanRecorder

            self.spans = SpanRecorder(
                config.span_path,
                file_bytes=config.span_file_bytes,
                max_bytes=config.span_max_bytes,
                process="host",
            )
        self._cycle_span = None
        # labeled Prometheus collectors, rendered by MetricsExporter
        # beside the legacy quantile gauges: real histograms (bucketed,
        # labeled by driver path) instead of window quantiles, and the
        # upload counter the resident-state dashboards key on
        from kubernetes_scheduler_tpu.host.observe import Counter, Histogram

        self.hist_cycle = Histogram(
            "cycle_duration_seconds",
            "End-to-end cycle latency by driver path",
            labels=("path",),
        )
        self.hist_engine = Histogram(
            "engine_step_duration_seconds",
            "Device (engine) step time by driver path",
            labels=("path",),
        )
        self.ctr_uploads = Counter(
            "snapshot_uploads_total",
            "Snapshot uploads to the engine (resident delta vs full)",
            labels=("upload",),
        )
        self.ctr_shard_bytes = Counter(
            "shard_delta_bytes_total",
            "Routed SnapshotDelta payload bytes per owning node shard "
            "(mesh-sharded resident engine)",
            labels=("shard",),
        )
        self.ctr_slo = Counter(
            "slo_breaches_total",
            "Cycles that blew the configured cycle_slo_ms latency budget",
            labels=("path",),
        )
        self.prom_collectors = (
            self.hist_cycle, self.hist_engine, self.ctr_uploads,
            self.ctr_shard_bytes, self.ctr_slo,
        ) + (self.mirror.collectors if self.mirror is not None else ())
        # SLO watchdog state (config.cycle_slo_ms): run totals, the last
        # breach's identity (trace id + flight-recorder seq — the two
        # handles that find the cycle in the span timeline and journal),
        # and the self-arm window countdown (config.slo_profile_cycles):
        # a breach storm arms the profiler once per window, not once per
        # breach — re-arming every cycle would profile forever and keep
        # resetting the dump the operator wants to read
        self.slo_breaches = 0
        self.last_slo_breach: dict | None = None
        self._slo_profile_pending = 0
        # resilience layer (host/resilience.py): the degradation-ladder
        # state machine (single owner of every subsystem's rung), the
        # circuit breakers guarding the engine dispatch and advisor
        # fetch, and the shared deterministic-jitter backoff policy the
        # advisor outage path retries on. All of it observes and gates —
        # with no failures the breakers stay closed, every rung stays at
        # top, and the loop is bit-identical to the pre-resilience
        # scheduler (PARITY round 17).
        from kubernetes_scheduler_tpu.host.resilience import (
            BackoffPolicy,
            CircuitBreaker,
            DegradationLadder,
        )

        # the retry/backoff clock of record is the QUEUE's clock (the
        # injectable queue_clock; the scenario harness's virtual
        # SimClock) — the breakers and the advisor backoff hold read it
        # LIVE through the queue so virtual-clock runs are
        # tick-deterministic and test clock pokes stay coherent
        self._clock = lambda: self.queue._clock()
        self.ladder = DegradationLadder()
        self.ctr_breaker = Counter(
            "breaker_transitions_total",
            "Circuit-breaker state transitions (state entered), by "
            "breaker (engine dispatch vs advisor fetch)",
            labels=("breaker", "state"),
        )
        # ONE breaker governs the engine path. An engine that owns a
        # breaker (RemoteEngine: one per sidecar target, gating its own
        # RPCs) is adopted and retuned with the config knobs + queue
        # clock + transition hook — two stacked breakers would each
        # need their half-open windows to line up before a probe could
        # reach the wire. Engines without one (local/sharded) get a
        # scheduler-owned breaker, and the dispatch gate below is the
        # only enforcement point.
        eng_brk = getattr(self.engine, "breaker", None)
        self._engine_owns_breaker = isinstance(eng_brk, CircuitBreaker)
        if self._engine_owns_breaker:
            self.engine_breaker = eng_brk.configure(
                failure_threshold=config.breaker_failure_threshold,
                recovery_window_s=config.breaker_recovery_window_s,
                clock=self._clock,
                on_transition=self._on_breaker_transition,
            )
        else:
            self.engine_breaker = CircuitBreaker(
                "engine",
                failure_threshold=config.breaker_failure_threshold,
                recovery_window_s=config.breaker_recovery_window_s,
                clock=self._clock,
                on_transition=self._on_breaker_transition,
            )
        self.advisor_breaker = CircuitBreaker(
            "advisor",
            failure_threshold=config.breaker_failure_threshold,
            recovery_window_s=config.breaker_recovery_window_s,
            clock=self._clock,
            on_transition=self._on_breaker_transition,
        )
        self._backoff = BackoffPolicy()
        # advisor outage bookkeeping: consecutive failures, the
        # backoff-held next-attempt time, and the last-good UTILIZATION
        # snapshot the stale-TTL grace mode serves (utils only — the
        # node/running lists are re-read LIVE under grace, so the
        # scheduler's own binds stay visible and capacity is never
        # double-booked against a frozen running set)
        self._advisor_fails = 0
        self._advisor_retry_at = float("-inf")
        self._last_good_utils: tuple | None = None  # (utils, ts)
        # kernel-rung latch: has this config ever served a fused cycle?
        # (only then is coming back unfused a capability downgrade)
        self._kernel_fused_seen = False
        self.prom_collectors = (
            self.prom_collectors
            + (self.ctr_breaker,)
            + self.ladder.collectors
            # engines owning exported collectors (RemoteEngine's
            # engine_health_failures_total) ride the host exporter too
            + tuple(getattr(self.engine, "collectors", ()))
        )

    def _on_breaker_transition(self, name: str, state: str) -> None:
        """Breaker state change hook: count the transition and keep the
        ladder coupled — an OPEN engine breaker implies the engine
        subsystem sits below its top rung (the `degradation-ladder`
        protocol model's breaker-open-implies-degraded invariant).
        Everything but the advisor breaker IS the engine breaker (an
        adopted bridge-client breaker keeps its per-target name)."""
        self.ctr_breaker.inc(breaker=name, state=state)
        if name != "advisor" and state == "open":
            self.ladder.demote(
                "engine", reason="breaker-open",
                seq=self.totals["cycles"],
            )

    def _engine_failure(self, reason: str) -> None:
        """One engine-dispatch failure: feed the breaker and walk the
        ladder down — engine (remote->local), plus sharded->dense when
        the failed engine was the mesh-sharded one (its fallback is the
        dense scalar path). With a SHARED client-owned breaker the
        client already recorded the terminal outcome per call — a
        second record here would restart the open window every cycle
        and recovery would never come."""
        if not self._engine_owns_breaker:
            self.engine_breaker.record_failure()
        seq = self.totals["cycles"]
        self.ladder.demote("engine", reason=reason, seq=seq)
        if getattr(self.engine, "n_shards", 0):
            self.ladder.demote("sharding", reason=reason, seq=seq)

    def _ladder_cycle_end(self, m: CycleMetrics) -> None:
        """Completion-stage ladder bookkeeping: a clean device cycle IS
        the recovery probe for the engine-side rungs (the dispatch
        re-attempted the degraded path and it served), so probe+promote
        climb them back; the policy rung follows policy_mismatch."""
        seq = self.totals["cycles"]
        lad = self.ladder
        device_ok = m.engine_seconds > 0 and not m.used_fallback
        if device_ok:
            if not self._engine_owns_breaker:
                # a shared client breaker already recorded per call
                self.engine_breaker.record_success()
            for sub in ("engine", "sharding"):
                if lad.depth(sub) > 0:
                    lad.probe(sub, seq=seq)
                    lad.promote(sub, seq=seq)
        if m.policy_mismatch:
            lad.demote("policy", reason="no-scalar-mirror", seq=seq)
        elif device_ok and lad.depth("policy") > 0:
            lad.probe("policy", seq=seq)
            lad.promote("policy", seq=seq)

    def _cycle_path(self, m: CycleMetrics) -> str:
        """The histogram `path` label: which driver served the cycle."""
        if m.used_fallback or m.fetch_failed:
            return "fallback"
        return "pipelined" if self.config.pipeline_depth > 0 else "serial"

    def _span(self, name: str, t0: float, t1: float | None = None, **args):
        """Record one span on the current cycle's SpanSet (no-op with
        spans off — one attribute read on the hot path)."""
        sp = self._cycle_span
        if sp is not None:
            sp.add(name, t0, time.perf_counter() if t1 is None else t1, **args)

    def arm_profile(self, cycles: int) -> dict:
        """Arm jax.profiler capture of the next `cycles` engine calls
        (the /debug/profile?cycles=N endpoint). A local engine dumps
        under config.profile_path (default <span_path>/profiles, else a
        tempdir), one dump per call named after the trace id it covers;
        a RemoteEngine forwards the arm to the sidecar over metadata."""
        armer = getattr(self.engine, "arm_profile", None)
        if armer is None:
            return {"armed": 0, "error": "engine has no profile surface"}
        out_dir = self.config.profile_path
        if out_dir is None and self.config.span_path:
            import os

            out_dir = os.path.join(self.config.span_path, "profiles")
        return armer(int(cycles), out_dir)

    def _record(self, m: CycleMetrics) -> None:
        # mesh-sharded engine: a device cycle (engine_seconds only
        # accrues after a successful force) through a sharded engine is
        # a sharded cycle, whatever dispatch surface served it
        if m.engine_seconds > 0 and getattr(self.engine, "n_shards", 0):
            m.sharded_cycles = 1
        # degradation-ladder audit: the rungs below top as this cycle
        # lands (journaled with the cycle's metrics; the same-mutation
        # precedent as the sharded_cycles attribution above)
        m.degraded = self.ladder.degraded()
        path = self._cycle_path(m)
        self.hist_cycle.observe(m.cycle_seconds, path=path)
        if m.engine_seconds > 0:
            self.hist_engine.observe(m.engine_seconds, path=path)
        if m.delta_uploads:
            self.ctr_uploads.inc(m.delta_uploads, upload="delta")
        if m.full_uploads:
            self.ctr_uploads.inc(m.full_uploads, upload="full")
        for shard, nbytes in enumerate(m.shard_delta_bytes):
            if nbytes:
                self.ctr_shard_bytes.inc(nbytes, shard=str(shard))
        with self._metrics_lock:
            self.metrics.append(m)
            self.totals["cycles"] += 1
            self.totals["pods_bound"] += m.pods_bound
            self.totals["pods_unschedulable"] += m.pods_unschedulable
            self.totals["pods_dropped"] += m.pods_dropped
            self.totals["pods_preempted"] += m.pods_preempted
            self.totals["victims_evicted"] += m.victims_evicted
            self.totals["fallback_cycles"] += int(m.used_fallback)
            self.totals["fetch_failures"] += int(m.fetch_failed)
            self.totals["fallback_policy_mismatch"] += int(m.policy_mismatch)
            self.totals["pipeline_flushes"] += m.pipeline_flushes
            self.totals["host_overlap_seconds"] += m.host_overlap_seconds
            self.totals["delta_uploads"] += m.delta_uploads
            self.totals["full_uploads"] += m.full_uploads
            self.totals["delta_bytes_saved"] += m.delta_bytes_saved
            self.totals["sharded_cycles"] += m.sharded_cycles
            self.totals["shard_delta_bytes"] += sum(m.shard_delta_bytes)
            self.totals["gangs_admitted"] += m.gangs_admitted
            self.totals["gangs_deferred"] += m.gangs_deferred
            self.totals["gang_pods_masked"] += m.gang_pods_masked
            self.totals["advisor_stale_cycles"] += int(m.advisor_stale)
            self.totals["degraded_cycles"] += int(bool(m.degraded))

    def metrics_snapshot(self) -> tuple[list[CycleMetrics], dict]:
        """Point-in-time copy for exporters (safe against the scheduling
        thread appending mid-iteration)."""
        with self._metrics_lock:
            return list(self.metrics), dict(self.totals)

    def submit(self, pod: Pod) -> None:
        """Enqueue + admission-time precompute. Pod specs are immutable,
        so the per-pod derived values every cycle probes — dispatch flags,
        the request row, priority — are computed HERE, on the informer/
        submission path, not inside the scheduling loop. This mirrors
        upstream's scheduling queue doing its preprocessing at Add time:
        the cycle then sees only warm per-pod caches (a fresh 8k-pod
        backlog otherwise pays ~100ms of first-touch attribute walks
        inside its first cycle)."""
        try:
            pod_batch_record(pod, self.builder.resource_names_tuple())
        except Exception:
            # a malformed spec must surface in the cycle's error
            # handling (requeue/backoff), not kill the informer thread
            pass
        self.queue.push(pod)
        if self.trigger is not None:
            # event-driven loops wake on arrival instead of the next tick
            self.trigger.notify()

    # ---- one cycle -----------------------------------------------------

    def run_cycle(self) -> CycleMetrics:
        """One scheduling cycle. With config.pipeline_depth >= 1 the
        batched device path runs 1-deep pipelined — async engine
        dispatch with next-cycle host work overlapped against the
        in-flight call; depth 0 is the strictly alternating host/device
        loop. Bindings are bit-identical between the two for the same
        arrival order (PARITY.md)."""
        if self.config.pipeline_depth > 0:
            return self._run_cycle_pipelined()
        return self._run_cycle_serial()

    def _run_cycle_serial(self) -> CycleMetrics:
        m = CycleMetrics()
        t0 = time.perf_counter()
        start = self._begin_cycle(m, t0)
        if start is None:
            return m
        self._run_paths(start, m)
        self._finish_cycle(start, m, t0)
        return m

    def _window_cap(self) -> int:
        return self.config.batch_window * (
            max(1, self.config.max_windows_per_cycle)
            if self._engine_windows_ok
            else 1
        )

    def _mirror_state(self) -> tuple[list, list, dict]:
        """Cluster state off the event-sourced mirror (config.
        snapshot_mirror): the full list/fetch callables run ONCE to
        seed; afterwards the per-cycle state fetch reduces to draining
        the advisor's changed-node records and applying them as
        utilization events (span event_apply) — O(events), not
        O(nodes). Pod/node events arrive out of band (informer hooks,
        ScenarioWorld, the scheduler's own post-bind self-apply)."""
        mir = self.mirror
        if not mir.seeded:
            mir.seed(
                self.list_nodes(),
                self.list_running_pods(),
                self.advisor.fetch(),
            )
        else:
            fetch_changed = getattr(self.advisor, "fetch_changed", None)
            if fetch_changed is not None:
                t_e = time.perf_counter()
                changed = fetch_changed()
                if changed:
                    mir.apply_util_events(changed)
                self._span("event_apply", t_e, events=len(changed))
        return mir.state()

    def _advisor_ready(self) -> bool:
        """May this cycle attempt a state fetch? False while the
        deterministic backoff hold from the last failure is pending or
        the advisor breaker is open (its half-open probe is the ONE
        fetch attempt per recovery window)."""
        if self._clock() < self._advisor_retry_at:
            return False
        return self.advisor_breaker.allow()

    def _advisor_failed(self) -> None:
        """One failed fetch attempt: feed the breaker and arm the next
        attempt at the shared BackoffPolicy's deterministic-jitter
        exponential delay (never a fixed per-cycle hammer)."""
        self.advisor_breaker.record_failure()
        self._advisor_retry_at = self._clock() + self._backoff.delay(
            self._advisor_fails, key="advisor"
        )
        self._advisor_fails += 1

    def _advisor_recovered(self, state: tuple) -> None:
        """A successful fetch: clear the outage bookkeeping and adopt
        this cycle's utilization as the stale-grace fallback payload."""
        if self._advisor_fails or self.advisor_breaker.state() != "closed":
            self.advisor_breaker.record_success()
        self._advisor_fails = 0
        self._advisor_retry_at = float("-inf")
        self._last_good_utils = (state[2], self._clock())

    def _stale_state(self) -> tuple | None:
        """(nodes, running, utils) for a grace-mode cycle: LIVE cluster
        lists (the scheduler's own binds must stay visible — serving a
        frozen running set would double-book node capacity) joined with
        the last-good utilization while the stale TTL
        (config.advisor_stale_ttl_s) still covers it. None when the TTL
        is off/expired or the cluster source itself is down (then the
        requeue outage path owns the cycle)."""
        ttl = self.config.advisor_stale_ttl_s
        lg = self._last_good_utils
        if ttl <= 0 or lg is None or self._clock() - lg[1] > ttl:
            return None
        try:
            if self.mirror is not None:
                # the mirror's lists are event-sourced and live; its
                # utilization is simply frozen at the last applied
                # advisor events — exactly the grace semantics
                if not self.mirror.seeded:
                    return None
                return self.mirror.state()
            return self.list_nodes(), self.list_running_pods(), lg[0]
        except Exception:
            log.exception("stale-grace cluster-list fetch failed")
            return None

    def _cycle_snapshot(
        self, window, nodes, running, utils, *, ephemeral: bool,
    ):
        """(snapshot, mirror delta | None) for one dispatch — the ONE
        place the two state paths fork: mirror.emit serves the
        persistent arrays plus a ready-made delta in O(events) (span
        mirror_emit); the classic build_snapshot path (span
        snapshot_build) covers mirror-off and ephemeral builds (a
        reservation-concatenated running list is throwaway and must
        never touch the mirror's state)."""
        t_build = time.perf_counter()
        plain = self._window_flags(window)[0]
        if self.mirror is not None and not ephemeral:
            snapshot, delta, rebuilt = self.mirror.emit(
                window,
                pending_all_plain=plain,
                prev=self._resident_prev if self._resident_ok else None,
            )
            self._span(
                "mirror_emit", t_build,
                rebuilt=rebuilt, delta=delta is not None,
            )
            # ladder: a flush-to-full rebuild IS the mirror->rebuild
            # rung (verify resync, churn); a mirror-served emit while
            # degraded is the recovery probe that climbs back
            seq = self.totals["cycles"]
            if rebuilt:
                self.ladder.demote(
                    "mirror",
                    reason=getattr(
                        self.mirror, "last_rebuild_reason", "flush"
                    ),
                    seq=seq,
                )
            elif self.ladder.depth("mirror") > 0:
                self.ladder.probe("mirror", seq=seq)
                self.ladder.promote("mirror", seq=seq)
            return snapshot, delta
        snapshot = self.builder.build_snapshot(
            nodes, utils, running, pending_pods=window,
            ephemeral=ephemeral, pending_all_plain=plain,
        )
        self._span("snapshot_build", t_build)
        return snapshot, None

    def _begin_cycle(
        self, m: CycleMetrics, t0: float, window: list | None = None,
    ) -> _CycleStart | None:
        """Cycle front-end shared by the serial and pipelined drivers:
        pop (or adopt a prefetched) window, fetch cluster state, apply
        the ReadWriteOncePod filter and nomination reservations, and
        decide the path. Returns None after finishing the cycle itself
        on the terminal paths (empty window, fetch failure, everything
        filtered)."""
        self._cycle_unsched = []
        self._cycle_bound = []
        self._trace_cycle = []
        self._cycle_span = (
            self.spans.begin() if self.spans is not None else None
        )
        t_pop = time.perf_counter()
        if window is None:
            window = self.queue.pop_window(self._window_cap())
        m.pods_in = len(window)
        if not window:
            # empty cycles (backoff waits, idle polls) are not recorded:
            # a serve-forever loop would otherwise grow self.metrics
            # without bound on pure idle time — and not spanned (the
            # same unbounded-idle concern applies to span files)
            self._cycle_span = None
            m.cycle_seconds = time.perf_counter() - t0
            return None
        self._span("queue_pop", t_pop)

        # gang admission control BEFORE any state fetch: gangs short of
        # members (or too big to ever fit a window) defer as a unit —
        # scheduling a knowingly-partial gang would only burn a device
        # dispatch to mask it out again
        if self.config.gang_scheduling:
            window = self._gang_screen(window, m)
            if not window:
                m.cycle_seconds = time.perf_counter() - t0
                self._record(m)
                self._flush_spans(t0, m)
                return None

        t_fetch = time.perf_counter()
        state = None
        if self._advisor_ready():
            try:
                if self.mirror is not None:
                    state = self._mirror_state()
                else:
                    state = (
                        self.list_nodes(),
                        self.list_running_pods(),
                        self.advisor.fetch(),
                    )
            except Exception:
                # a cluster-source or advisor outage (API server blip,
                # Prometheus restart): feed the advisor breaker and arm
                # the deterministic-jitter backoff hold, so retry
                # attempts pace out instead of paying the fetch timeout
                # every cycle
                log.exception("cycle state fetch failed")
                self._advisor_failed()
        if state is not None:
            self._advisor_recovered(state)
            nodes, running, utils = state
        else:
            # outage (or a backoff hold between retry attempts): the
            # stale-TTL grace mode serves the last-good cluster state,
            # marked, so scheduling keeps flowing on slightly stale
            # utilization (config.advisor_stale_ttl_s)
            stale = self._stale_state()
            if stale is None:
                # past the TTL (or grace off): the outage must not LOSE
                # the popped window — requeue it with backoff and
                # surface a failed cycle (the reference's PreScore error
                # path makes pods retriable the same way,
                # scheduler.go:106-109)
                for pod in window:
                    self.queue.requeue_unschedulable(pod)
                m.pods_unschedulable = len(window)
                m.fetch_failed = True
                m.cycle_seconds = time.perf_counter() - t0
                self._record(m)
                self._flush_spans(t0, m)
                return None
            nodes, running, utils = stale
            m.advisor_stale = True
        self._span("state_fetch", t_fetch)

        # VolumeRestrictions (ReadWriteOncePod): at most one pod
        # cluster-wide may use an exclusive claim. Enforced HERE, against
        # this cycle's running set plus earlier window positions, because
        # any admission-time check races (two pods pending together both
        # look unconstrained before either binds).
        if any(pod.exclusive_claims for pod in window):
            held = {
                f"{pd.namespace}/{c}"
                for pd in running
                for c in pd.volume_claims
            }
            kept = []
            for pod in window:
                exc = set(pod.exclusive_claims)
                if exc & held:
                    log.info(
                        "pod %s/%s waits: exclusive claim in use",
                        pod.namespace, pod.name,
                    )
                    self._requeue_unschedulable(pod, m)
                else:
                    held |= exc
                    kept.append(pod)
            window = kept
            if not window:
                m.cycle_seconds = time.perf_counter() - t0
                self._record(m)
                self._flush_spans(t0, m)
                return None

        # nominated-capacity reservations (upstream nominatedNodeName):
        # a preemptor whose victims were evicted holds its nominated
        # node's capacity as a virtual running pod, so the freed space
        # cannot be consumed by lower-priority arrivals during the
        # preemptor's retry backoff — which would otherwise re-trigger
        # eviction loops under a steady low-priority trickle. The
        # reservation is skipped while the preemptor itself is in the
        # window (it is about to consume the capacity for real).
        reservations = self._nomination_reservations(window)
        if reservations:
            # NB: only copy when there ARE reservations — the copy would
            # otherwise defeat every downstream prefix-identity cache
            # (running-features, snapshot accumulation) every cycle
            running = running + reservations

        # adaptive dispatch: tiny cycles are device-latency-bound; the
        # scalar host path (C++ when native) wins below the crossover.
        # Only when the scalar path's decisions match — it implements the
        # live yoda formula + resource fit, so any other policy or any
        # taint/affinity/GPU constraint family stays on the engine. The
        # crossover itself is learned from observed per-path latencies
        # when adaptive_dispatch is on (utils/adaptive.py); cells below
        # min_device_work route scalar until both models are fitted.
        cells = len(window) * len(nodes)
        # with reservations, `running` is a per-cycle throwaway
        # concatenation: probes must not record prefix caches on it
        eph_running = bool(reservations)
        scalar_eligible = (
            self.config.policy in ("balanced_cpu_diskio", "free_capacity")
            and self._scalar_sufficient(
                window, nodes, running, record=not eph_running
            )
        )
        if not scalar_eligible:
            use_device = True
        elif self._dispatch is not None:
            use_device = self._dispatch.decide(cells)
        else:
            use_device = cells >= self.config.min_device_work
        if use_device and self.config.feature_gates.tpu_batch_score:
            # breaker open: the engine is not dispatched at all — the
            # scalar path serves this window, so the outage costs one
            # probe per recovery window instead of a timeout per call.
            # Scheduler-owned breakers enforce HERE via allow() (one
            # half-open probe per window takes the device path below);
            # a breaker SHARED with the bridge client is only peek()ed
            # — the client's allow() at send time is the consuming
            # gate, and eating its probe here would fail every probe
            # cycle spuriously.
            if self._engine_owns_breaker:
                use_device = self.engine_breaker.peek()
            else:
                use_device = self.engine_breaker.allow()
        t_path = time.perf_counter()
        backlog = (
            len(window) > self.config.batch_window and self._engine_windows_ok
        )
        return _CycleStart(
            window=window, nodes=nodes, running=running, utils=utils,
            eph_running=eph_running, scalar_eligible=scalar_eligible,
            use_device=use_device, backlog=backlog, cells=cells,
            t_path=t_path,
        )

    def _run_paths(self, start: _CycleStart, m: CycleMetrics) -> None:
        """Serial path dispatch: device (single-window or backlog) with
        scalar fallback, or the scalar path outright — plus the adaptive
        crossover observations."""
        window, nodes, running, utils = (
            start.window, start.nodes, start.running, start.utils,
        )
        eph_running = start.eph_running
        scalar_eligible = start.scalar_eligible
        use_device = start.use_device
        backlog = start.backlog
        cells = start.cells
        t_path = start.t_path
        if self.config.feature_gates.tpu_batch_score and nodes and use_device:
            try:
                # deep backlog: schedule all popped windows in ONE engine
                # dispatch when the engine serves the windows surface
                if backlog:
                    try:
                        self._run_backlog(
                            window, nodes, running, utils, m,
                            ephemeral=eph_running,
                        )
                    except NotImplementedError:
                        # version-skewed sidecar without the windows RPC:
                        # degrade to per-window dispatches (same
                        # decisions, one RPC each), never to the scalar
                        # fallback, and stop popping deep windows
                        log.warning(
                            "engine lacks the windows surface; falling "
                            "back to per-window dispatch"
                        )
                        self._engine_windows_ok = False
                        bw = self.config.batch_window
                        for i in range(0, len(window), bw):
                            chunk = window[i : i + bw]
                            # each chunk must see the capacity consumed
                            # by earlier chunks' binds (the one-dispatch
                            # path carries it on device; the one-window-
                            # per-cycle shape re-lists between cycles)
                            run_now = (
                                running + self._cycle_bound
                                if self._cycle_bound
                                else running
                            )
                            try:
                                self._run_batched(
                                    chunk, nodes, run_now, utils, m,
                                    ephemeral=eph_running
                                    or run_now is not running,
                                )
                            except Exception:
                                # chunk-local fallback: earlier chunks'
                                # binds are final and must NOT be
                                # re-scheduled by a whole-window fallback
                                log.exception(
                                    "chunk failed; scalar fallback for "
                                    "this chunk only"
                                )
                                m.used_fallback = True
                                self._engine_failure("chunk-failed")
                                self._run_scalar(
                                    chunk, nodes, run_now, utils, m
                                )
                else:
                    self._run_batched(
                        window, nodes, running, utils, m,
                        ephemeral=eph_running,
                    )
                # backlog cycles amortize dispatch over many windows — a
                # different cost curve than the single-dispatch cycles
                # the scalar/device crossover model is about, so only
                # single-window cycles feed it
                if self._dispatch is not None and scalar_eligible and not backlog:
                    self._dispatch.observe(
                        True, cells, time.perf_counter() - t_path
                    )
            except Exception:
                log.exception(
                    "engine cycle failed; falling back to scalar path "
                    "(policy=%r; unsupported policies degrade to the "
                    "yoda formula and bump fallback_policy_mismatch)",
                    self.config.policy,
                )
                m.used_fallback = True
                self._engine_failure("engine-cycle-failed")
                self._invalidate_resident()
                self._run_scalar(window, nodes, running, utils, m)
                # a failed device cycle is a device observation priced at
                # its FULL cost: the failed attempt (timeout or fast
                # connect error) plus the scalar fallback that had to
                # run. Pricing only the time-to-exception would teach the
                # model that a fast-failing path is cheap and keep
                # routing to it; pricing nothing would never re-model a
                # degraded path at all.
                if self._dispatch is not None and scalar_eligible and not backlog:
                    self._dispatch.observe(
                        True, cells, time.perf_counter() - t_path
                    )
        else:
            m.used_fallback = True
            self._run_scalar(window, nodes, running, utils, m)
            if self._dispatch is not None and scalar_eligible and not backlog:
                self._dispatch.observe(
                    False, cells, time.perf_counter() - t_path
                )

    def _finish_cycle(
        self, start: _CycleStart, m: CycleMetrics, t0: float
    ) -> None:
        # successful binds clear their retry counters in ONE batch (the
        # native path pays one foreign call instead of one per bind);
        # the 404/409 drop path inside _bind still marks immediately
        if self._cycle_bound:
            self.queue.mark_scheduled_many(self._cycle_bound)
            if self.mirror is not None:
                # the assume-cache equivalent: this cycle's binds enter
                # the mirror as pod events NOW (every driver path —
                # device, backlog, scalar), so the next emit's delta
                # carries their rows; a later informer echo of the SAME
                # Pod object coalesces by identity in the mirror
                for pod in self._cycle_bound:
                    self.mirror.apply_pod_event("BOUND", pod)

        # PostFilter parity: unschedulable pods may preempt strictly-
        # lower-priority running pods (ops/preempt.py). A failure here
        # must never lose the cycle's bindings — preemptors are already
        # requeued and simply retry without preemption next cycle. On
        # the pipelined driver this runs in the COMPLETION stage, after
        # the engine result was forced and this cycle's binds applied —
        # preemption always sees real, never speculative, capacity.
        if (
            self._cycle_unsched
            and self.evictor is not None
            and self.config.preemption
        ):
            try:
                self._run_preemption(
                    self._cycle_unsched, start.nodes, start.running,
                    start.utils, m, ephemeral=start.eph_running,
                )
            except Exception:
                log.exception("preemption pass failed; retrying next cycle")
            if m.victims_evicted and self.config.resident_state:
                # evictions change the running set out-of-band of the
                # binding flow; flush the resident contract so the next
                # dispatch re-uploads in full rather than trusting a
                # delta base that predates the kills
                self._invalidate_resident()

        # resilience completion stage: breaker outcome + ladder
        # probe/promote climbs, BEFORE _record so the cycle journals
        # the rungs it actually ended on
        self._ladder_cycle_end(m)
        m.cycle_seconds = time.perf_counter() - t0
        self._record(m)
        seq = None
        if self.recorder is not None:
            # AFTER the cycle's own bookkeeping: journal serialization
            # time never inflates cycle_seconds, and the record carries
            # the final metrics. The seq is read BEFORE the append — the
            # value this cycle's record is journaled under, and the same
            # value the dispatch propagated to the sidecar.
            seq = self.recorder._seq
            dropped_before = self.recorder.records_dropped
            t_rec = time.perf_counter()
            self._record_trace(start, m)
            self._span("recorder_write", t_rec)
            if self.recorder.records_dropped != dropped_before:
                # the record was NOT journaled under the predicted seq —
                # the next cycle's record will own it. Omit the
                # cross-link rather than point at the wrong record (the
                # sidecar's copy of the prediction cannot be retracted).
                seq = None
        # watchdog AFTER the recorder (it logs the seq the cycle was
        # journaled under) and BEFORE the span flush (it reads the
        # cycle's trace id off the still-open span set) — all of it on
        # the completion stage, never the device-dispatch path
        self._check_slo(m, seq)
        self._flush_spans(t0, m, seq=seq)

    def _check_slo(self, m: CycleMetrics, seq: int | None) -> None:
        """Live SLO watchdog (config.cycle_slo_ms): a cycle over budget
        logs the handles that FIND it again — trace id (span timeline),
        flight-recorder seq (journal record) — increments
        slo_breaches_total{path}, and, with config.slo_profile_cycles
        set, self-arms the jax.profiler hook for the next N engine calls
        so the follow-up slow cycles leave a device-level profile dump
        beside the spans. Pure observation: never touches a decision,
        so watchdog-on/off bindings are bit-identical (PARITY.md)."""
        slo = self.config.cycle_slo_ms
        if slo <= 0 or m.pods_in == 0:
            return
        # the self-arm window drains one per watched cycle (~one engine
        # call each), approximating "the armed dumps were taken"
        if self._slo_profile_pending > 0:
            self._slo_profile_pending -= 1
        cycle_ms = m.cycle_seconds * 1e3
        if cycle_ms <= slo:
            return
        path = self._cycle_path(m)
        sp = self._cycle_span
        trace_id = sp.trace_id if sp is not None else None
        self.slo_breaches += 1
        self.ctr_slo.inc(path=path)
        armed = 0
        if self.config.slo_profile_cycles > 0 and self._slo_profile_pending <= 0:
            try:
                report = self.arm_profile(self.config.slo_profile_cycles)
                armed = int(report.get("armed", 0))
            except Exception:
                # the profiler is a bonus artifact; failing to arm it
                # must not cost the breach record (or the cycle)
                log.debug("slo: profile self-arm failed", exc_info=True)
            if armed > 0:
                self._slo_profile_pending = armed
        self.last_slo_breach = {
            "cycle_ms": round(cycle_ms, 3),
            "slo_ms": slo,
            "path": path,
            "trace_id": trace_id,
            "seq": seq,
            "pods_in": m.pods_in,
            "profile_armed": armed,
        }
        log.warning(
            "SLO breach: cycle took %.1f ms (budget %.1f ms, path=%s, "
            "pods_in=%d) trace_id=%s journal_seq=%s%s",
            cycle_ms, slo, path, m.pods_in,
            trace_id if trace_id is not None else "-",
            seq if seq is not None else "-",
            f"; armed profiler for next {armed} engine calls" if armed
            else "",
        )

    def _flush_spans(
        self, t0: float, m: CycleMetrics, seq: int | None = None
    ) -> None:
        """Close out the cycle's span set: add the whole-cycle span and
        hand it to the recorder for encoding + write (completion stage —
        the device dispatch never pays for serialization). `seq`
        cross-links every span to the cycle's flight-recorder record so
        a replayed cycle can be found in the timeline."""
        sp = self._cycle_span
        if sp is None:
            return
        self._cycle_span = None
        sp.add(
            "cycle",
            t0,
            time.perf_counter(),
            path=self._cycle_path(m),
            pods_in=m.pods_in,
            pods_bound=m.pods_bound,
        )
        self.spans.flush(sp, seq=seq)

    def _trace_fingerprint(self, start: _CycleStart) -> dict:
        """Config + layout identity summary riding every full record —
        enough for `trace stats`/`diff` to flag a replay against the
        wrong build or cluster shape, cheap enough to never matter."""
        c = self.config
        return {
            "policy": c.policy,
            "assigner": c.assigner,
            "normalizer": c.normalizer,
            "batch_window": c.batch_window,
            "resident_state": c.resident_state,
            "pipeline_depth": c.pipeline_depth,
            "nodes": len(start.nodes),
            "resource_columns": len(self.builder.resource_names),
            "selectors": len(self.builder.selectors),
        }

    def _record_trace(self, start: _CycleStart, m: CycleMetrics) -> None:
        """Append this cycle's journal record (config.trace_path). One
        clean device/backlog dispatch records in full (replayable);
        scalar cycles, failed dispatches, and the rare multi-dispatch
        degraded paths record decision/metrics only."""
        ctxs, self._trace_cycle = self._trace_cycle, []
        bindings = [
            (p.namespace, p.name, p.node_name) for p in self._cycle_bound
        ]
        node_names = [nd.name for nd in start.nodes]
        try:
            if len(ctxs) == 1 and ctxs[0].get("node_idx") is not None:
                ctx = ctxs[0]
                self.recorder.record_cycle(
                    path=ctx["path"],
                    metrics=m,
                    node_names=node_names,
                    pod_keys=[
                        (p.namespace, p.name) for p in ctx["window"]
                    ],
                    bindings=bindings,
                    snapshot=ctx["snapshot"],
                    delta=ctx.get("delta"),
                    delta_base=ctx.get("delta_base"),
                    pods=ctx["pods"],
                    engine_kw=ctx["kw"],
                    node_idx=ctx["node_idx"],
                    resident_epoch=ctx.get("epoch", 0),
                    delta_sent=bool(ctx.get("delta_sent")),
                    batch_window=ctx.get("batch_window", 0),
                    fingerprint=self._trace_fingerprint(start),
                )
            else:
                self.recorder.record_cycle(
                    path="mixed" if len(ctxs) > 1 else "scalar",
                    metrics=m,
                    node_names=node_names,
                    pod_keys=[(p.namespace, p.name) for p in start.window],
                    bindings=bindings,
                )
        except Exception:
            # the recorder is an observer: it must never cost a cycle —
            # but a cycle missing from the journal must still COUNT
            # (trace_records_dropped_total is the "journal is not the
            # whole story" signal `trace diff` readers check first)
            log.exception("trace: cycle record failed")
            self.recorder.records_dropped += 1

    # ---- pipelined loop (config.pipeline_depth >= 1) -------------------

    def _run_cycle_pipelined(self) -> CycleMetrics:
        """One cycle of the 1-deep pipeline: dispatch this cycle's
        engine call asynchronously, do next-cycle host work (window pop,
        record warming, speculative pod-batch build) while it is in
        flight, then force, validate, and bind. Non-device paths
        (scalar, deep backlog, fetch failure) run the serial back-end
        unchanged and flush any speculative state; an engine failure
        mid-flight drains the pipeline and falls back to scalar for this
        window exactly once; the preemption pass runs in the completion
        stage against real — never speculative — capacity."""
        return self.run_cycle_split().complete()

    def run_cycle_split(self) -> "_PendingCycle":
        """The dispatch half of a pipelined cycle as a first-class seam:
        begin the cycle, launch the engine call asynchronously, overlap
        the prefetch, and return a handle whose .complete() forces the
        result and finishes the cycle. run_cycle_split().complete() is
        exactly _run_cycle_pipelined().

        This is the fleet-shared-engine dispatch seam
        (host/engine_pool.SharedEnginePool): a round-robin fleet drain
        calls run_cycle_split() on EVERY replica before completing any,
        so all N windows sit in the pool's queue when the first force
        arrives and the round coalesces into one device invocation —
        deterministically, without relying on thread timing. Non-device
        paths (scalar, deep backlog, empty queue, dispatch failure)
        finish inside this call and return an already-completed handle.

        Between dispatch and complete() the scheduler must not start
        another cycle: builder/mirror state snapshotted at dispatch is
        what the in-flight call scores."""
        m = CycleMetrics()
        t0 = time.perf_counter()
        start = self._begin_cycle(m, t0, window=self._take_prefetched())
        if start is None:
            return _PendingCycle(self, m, None)
        if not (
            self.config.feature_gates.tpu_batch_score
            and start.nodes
            and start.use_device
            and not start.backlog
        ):
            # scalar and multi-window backlog cycles keep their serial
            # semantics; speculative state never survives into them
            self._discard_speculative(m)
            self._run_paths(start, m)
            self._finish_cycle(start, m, t0)
            return _PendingCycle(self, m, None)
        try:
            infl = self._dispatch_window(
                start.window, start.nodes, start.running, start.utils, m,
                ephemeral=start.eph_running, use_async=True,
            )
        except Exception:
            log.exception(
                "engine dispatch failed; falling back to scalar path "
                "(policy=%r; unsupported policies degrade to the yoda "
                "formula and bump fallback_policy_mismatch)",
                self.config.policy,
            )
            m.used_fallback = True
            self._engine_failure("engine-dispatch-failed")
            self._invalidate_resident()
            self._discard_speculative(m)
            self._run_scalar(
                start.window, start.nodes, start.running, start.utils, m
            )
            self._observe_dispatch(start, m)
            self._finish_cycle(start, m, t0)
            return _PendingCycle(self, m, None)
        # overlap: next-cycle host work while the engine runs — this is
        # the serialized host time the strictly alternating loop paid
        # on the critical path (BENCH_r05: ~65 ms of a 168 ms cycle)
        t_prep = time.perf_counter()
        self._prefetch_next()
        m.host_overlap_seconds = time.perf_counter() - t_prep
        self._span("host_overlap", t_prep, t_prep + m.host_overlap_seconds)
        return _PendingCycle(self, m, (start, infl, t0))

    def _complete_cycle_split(self, m, start, infl, t0) -> CycleMetrics:
        """The force half of run_cycle_split (shared with the inline
        pipelined loop through _PendingCycle.complete)."""
        try:
            self._complete_window(
                infl, start.window, start.nodes, m,
                ephemeral=start.eph_running,
            )
            self._observe_dispatch(start, m)
        except Exception:
            log.exception(
                "engine cycle failed; draining pipeline and falling back "
                "to scalar path (policy=%r; unsupported policies degrade "
                "to the yoda formula and bump fallback_policy_mismatch)",
                self.config.policy,
            )
            m.used_fallback = True
            self._engine_failure("engine-force-failed")
            self._invalidate_resident()
            self._discard_speculative(m)
            self._run_scalar(
                start.window, start.nodes, start.running, start.utils, m
            )
            # failed device cycle priced at FULL cost — same rationale
            # as the serial fallback's observation
            self._observe_dispatch(start, m)
        self._finish_cycle(start, m, t0)
        return m

    def _observe_dispatch(self, start: _CycleStart, m: CycleMetrics) -> None:
        """Adaptive-crossover observation for a pipelined device cycle
        (single-window by construction; the serial back-end keeps its
        own inline observations)."""
        if self._dispatch is not None and start.scalar_eligible:
            self._dispatch.observe(
                True, start.cells, time.perf_counter() - start.t_path
            )

    def _layout_fingerprint(self) -> tuple:
        """Everything a prebuilt PodBatch depends on besides the window
        itself: column layout, selector-table size, node set (target_node
        indices), port mapping, image vocabulary. The speculative batch
        built while the engine is in flight is used at dispatch time only
        if this fingerprint still matches — an informer event in between
        (node add/remove, selector-minting churn) discards it, forcing a
        serial rebuild so a stale snapshot is never scored."""
        b = self.builder
        sc = b.__dict__.get("_node_static")
        return (
            b.resource_names_tuple(),
            len(b.selectors),
            sc["ids"] if sc is not None else None,
            tuple(sorted(b._port_index.items())),
            len(b.images),
        )

    def _take_prefetched(self) -> list[Pod] | None:
        w = self._prefetched
        self._prefetched = None
        return w

    def _discard_speculative(self, m: CycleMetrics) -> None:
        """Flush the speculative pod batch (never the prefetched WINDOW
        — those are real popped pods and dispatch next cycle on whatever
        path then applies)."""
        if self._spec_batch is not None:
            self._spec_batch = None
            m.pipeline_flushes += 1

    def drain_pipeline(self) -> None:
        """Hand a prefetched-but-undispatched window back to the queue
        (front, exact order on the Python queue) and drop speculative
        state. Call when abandoning the scheduler mid-backlog so
        len(queue) reflects reality and a restart reschedules the pods;
        run_cycle/run_until_empty drain naturally otherwise."""
        self._spec_batch = None
        w = self._prefetched
        self._prefetched = None
        if w:
            self.queue.restore_window(w)

    def _prefetch_next(self) -> None:
        """Host work overlapped with the in-flight engine call: pop the
        next window, warm its per-pod records/flags, and pre-build its
        pod batch. The batch is speculative — kept at dispatch time only
        if the layout fingerprint still matches.

        Skipped entirely at zero backoff: a requeue from THIS cycle
        could then legally re-enter the very next window, and a
        prefetched pop would misorder it against serial mode (with the
        default >= 1 s backoff, a requeued pod is never ready within one
        cycle's flight time)."""
        if self._prefetched is not None:
            return
        if self.config.initial_backoff_seconds <= 0:
            return
        window = self.queue.pop_window(self._window_cap())
        if not window:
            return
        self._prefetched = window
        if len(window) > self.config.batch_window:
            return  # backlog windows take the serial multi-window path
        try:
            self._window_flags(window)  # warms records + the flag cache
            batch = self.builder.build_pod_batch(
                window, recs=self._window_recs(window)
            )
            fp = self._layout_fingerprint()
        except Exception:
            # e.g. a hostPort outside the table (build_snapshot has not
            # seen this window yet): the serial build at dispatch time
            # surfaces it inside the cycle's normal error handling
            log.debug("speculative pod-batch build failed; will rebuild")
            return
        self._spec_batch = (window, fp, batch)

    def _dispatch_window(
        self, window, nodes, running, utils, m: CycleMetrics,
        *, ephemeral: bool, use_async: bool,
    ) -> _InFlight:
        """Build the snapshot, adopt or rebuild the pod batch, dispatch
        the engine — ONE implementation for the serial path (use_async=
        False: synchronous call, forced in _complete_window right after)
        and the pipelined path (use_async=True: the call goes out
        unforced so host work can overlap it).

        Snapshot FIRST: build_snapshot registers every selector the
        cycle needs — the window's terms AND running pods' anti terms
        (reverse anti-affinity) — so build_pod_batch computes
        pod_matches against the complete table. Reversed, a selector
        first introduced by a running avoider would be missing from
        pod_matches and the reverse check would silently pass. (The
        speculative prebuild respects this through the layout
        fingerprint: a selector minted between prebuild and here
        discards the prebuilt batch.)"""
        snapshot, mirror_delta = self._cycle_snapshot(
            window, nodes, running, utils, ephemeral=ephemeral
        )
        pods_batch = None
        spec = self._spec_batch
        if spec is not None and spec[0] is window:
            self._spec_batch = None
            if spec[1] == self._layout_fingerprint():
                pods_batch = spec[2]
            else:
                # informer/selector churn since the prebuild: the batch
                # could carry stale selector ids, node indices, or port
                # columns — never score it
                m.pipeline_flushes += 1
        if pods_batch is None:
            pods_batch = self.builder.build_pod_batch(
                window, recs=self._window_recs(window)
            )
        kw = self._engine_options(
            window, nodes, running, pods_batch, snapshot,
            record=not ephemeral,
        )
        self._set_engine_trace_id()
        tctx = None
        if self.recorder is not None:
            # references only — serialization happens in _finish_cycle,
            # after the force, off the dispatch path
            tctx = {
                "path": "device", "window": window, "snapshot": snapshot,
                "pods": pods_batch, "kw": kw,
            }
            self._trace_cycle.append(tctx)
        infl = self._dispatch_resident(
            snapshot, pods_batch, kw, ephemeral=ephemeral, use_async=use_async,
            tctx=tctx, mirror_delta=mirror_delta,
        )
        if infl is not None:
            infl.trace_ctx = tctx
            return infl
        t_eng = time.perf_counter()
        submit = (
            getattr(self.engine, "schedule_batch_async", None)
            if use_async
            else None
        )
        if submit is not None:
            handle = submit(snapshot, pods_batch, **kw)
        else:
            # serial mode, and engines without the async surface:
            # synchronous dispatch (the pipeline still interleaves
            # correctly around it, with no overlap)
            from kubernetes_scheduler_tpu.engine import PendingSchedule

            handle = PendingSchedule(
                self.engine.schedule_batch(snapshot, pods_batch, **kw)
            )
        return _InFlight(
            handle=handle, pods_batch=pods_batch, t_eng=t_eng, trace_ctx=tctx,
        )

    def _set_engine_trace_id(self) -> None:
        """Hand the cycle's trace id + predicted flight-recorder seq to
        the engine before dispatch: RemoteEngine ships them as gRPC
        metadata (sidecar spans join the host timeline on the id), a
        local engine names on-demand profile dumps with them. One
        getattr when spans are off."""
        sp = self._cycle_span
        if sp is None:
            return
        setter = getattr(self.engine, "set_trace_id", None)
        if setter is not None:
            setter(
                sp.trace_id,
                self.recorder._seq if self.recorder is not None else -1,
            )

    def _dispatch_resident(
        self, snapshot, pods_batch, kw, *, ephemeral: bool, use_async: bool,
        tctx: dict | None = None, mirror_delta=None,
    ) -> "_InFlight | None":
        """Resident-state dispatch (config.resident_state): ship a
        SnapshotDelta against the engine-retained snapshot when the
        cycle-over-cycle change is delta-expressible, a tagged full
        upload otherwise. Returns None when the resident path does not
        apply (knob off, engine without the surface, ephemeral builds —
        a throwaway reservation-concatenated snapshot must never become
        the delta base) and the caller runs the ordinary dispatch.

        The full snapshot always accompanies a delta down the engine
        surface, so an epoch/shape mismatch degrades to a full upload
        INSIDE the call (local: transparently; remote: INVALID_ARGUMENT
        resend) and never costs the cycle."""
        if not self.config.resident_state or ephemeral:
            return None
        supports = getattr(self.engine, "supports_resident", None)
        if supports is None or not supports():
            return None
        delta, epoch, saved = self._derive_resident_delta(
            snapshot, tctx, mirror_delta=mirror_delta
        )
        t_eng = time.perf_counter()
        submit = (
            getattr(self.engine, "schedule_resident_async", None)
            if use_async
            else None
        )
        if submit is not None:
            handle = submit(snapshot, pods_batch, delta=delta, epoch=epoch, **kw)
        else:
            from kubernetes_scheduler_tpu.engine import PendingSchedule

            handle = PendingSchedule(
                self.engine.schedule_resident(
                    snapshot, pods_batch, delta=delta, epoch=epoch, **kw
                )
            )
        # optimistic commit: the dispatched snapshot is the next delta
        # base. A failure before the result forces flips _resident_ok
        # False (the completion/fallback paths call
        # _invalidate_resident), flushing the next cycle to full.
        self._commit_resident(snapshot, epoch)
        return _InFlight(
            handle=handle, pods_batch=pods_batch, t_eng=t_eng,
            resident=True, delta_sent=delta is not None,
            delta_bytes_saved=saved, trace_ctx=tctx,
        )

    def _invalidate_resident(self) -> None:
        """Flush the resident-state contract: the next resident dispatch
        uploads in full (engine failure, preemption, epoch desync)."""
        if self.config.resident_state:
            # ladder: resident -> full until a delta applies again
            self.ladder.demote(
                "resident", reason="resident-flush",
                seq=self.totals["cycles"],
            )
        self._resident_ok = False
        self._resident_prev = None
        inval = getattr(self.engine, "invalidate_resident", None)
        if inval is not None:
            try:
                inval()
            except Exception:
                log.debug("engine invalidate_resident failed", exc_info=True)

    def _complete_window(
        self, infl: _InFlight, window, nodes, m: CycleMetrics,
        *, ephemeral: bool,
    ) -> None:
        """Force the (possibly in-flight) result, validate (BEFORE any
        bind, so the scalar fallback re-schedules the window exactly
        once), apply assignments, and fold the binds into the snapshot
        accumulator. Shared by the serial and pipelined paths — the
        validation and bind semantics cannot drift between them."""
        res = infl.handle.result()
        idx = np.asarray(res.node_idx)
        t_done = time.perf_counter()
        m.engine_seconds += t_done - infl.t_eng
        self._span(
            "engine_step", infl.t_eng, t_done,
            resident=infl.resident, delta=infl.delta_sent,
        )
        if infl.resident:
            # attribute AFTER the force: the engine reports whether the
            # delta actually applied or it degraded to a full upload
            # (epoch/shape mismatch) inside the call
            self._account_resident(m, infl.delta_sent, infl.delta_bytes_saved)
        p_padded = int(np.asarray(infl.pods_batch.request).shape[0])
        if (
            idx.shape != (p_padded,)
            or p_padded < len(window)
            or (idx[: len(window)] >= len(nodes)).any()
        ):
            raise RuntimeError(
                f"engine returned node_idx shape {idx.shape} (max "
                f"{idx.max() if idx.size else 'n/a'}) for a {len(window)}-pod "
                f"window padded to {p_padded} over {len(nodes)} nodes"
            )
        if infl.trace_ctx is not None:
            # the replay comparison target: engine decisions over the
            # real window rows (copy — idx may view an engine buffer)
            infl.trace_ctx["node_idx"] = self._trace_node_idx(
                infl.pods_batch, idx, len(window)
            )
        pre = len(self._cycle_bound)
        t_bind = time.perf_counter()
        self._apply_assignments(window, nodes, idx, m)
        self._span("bind", t_bind)
        bound = self._cycle_bound[pre:]
        if bound and not ephemeral:
            # incremental snapshot carry: fold this cycle's binds into
            # the builder's accumulated `requested` matrix now (one
            # vectorized scatter-add), so the next dispatch's build
            # skips re-walking them when the informer appends these pods
            try:
                if (
                    len(bound) == len(window)
                    and bound[0] is window[0]
                    and bound[-1] is window[-1]
                ):
                    # every pod bound in window order (the steady-state
                    # drain shape): rows are the identity — skip the
                    # 8k-entry id map
                    rows = np.arange(len(window))
                else:
                    pos = {id(pod): i for i, pod in enumerate(window)}
                    rows = [pos[id(pod)] for pod in bound]
                self.builder.apply_assignment_deltas(
                    bound, idx[rows], np.asarray(infl.pods_batch.request)[rows]
                )
            except Exception:
                # the delta is an optimization: on any surprise the next
                # build's suffix scan recomputes from scratch
                log.exception("assignment-delta fold failed; next build rescans")

    def _trace_node_idx(self, pods_batch, idx, n: int) -> np.ndarray:
        """The journaled node_idx over the real window rows, with the
        gang mask applied: against a gang-capable engine this is the
        identity (sentinels already present), but a gang-blind engine
        (capability-downgraded sidecar, mesh-sharded path) replies with
        RAW placements — recording those would make the journal
        unreplayable (local replay re-masks and diffs). The np mirror
        is test-pinned bitwise-equal to the device op, so the recorded
        vector is exactly what any gang-capable replay produces."""
        out = np.array(np.asarray(idx).reshape(-1)[:n], np.int32)
        if self.config.gang_scheduling:
            from kubernetes_scheduler_tpu.ops.gang import (
                mask_partial_gangs_np,
            )

            gid = np.asarray(pods_batch.gang_id).reshape(-1)[:n]
            if (gid >= 0).any():
                out, _ = mask_partial_gangs_np(
                    gid,
                    np.asarray(pods_batch.gang_size).reshape(-1)[:n],
                    out,
                )
        return out

    def _pdb_expected_count(self, matching: list[Pod]) -> int | None:
        """The upstream disruption controller's expected count for
        percentage budgets: the summed spec.replicas of the DISTINCT
        controllers owning the matching pods (via ownerReferences).
        None — the documented current-count fallback — when there is no
        resolver, any pod is controller-less, or a controller is
        unknown to the informer."""
        if self.controller_replicas is None or not matching:
            return None
        owners: set[tuple] = set()
        for pd in matching:
            if pd.owner is None:
                return None
            owners.add((pd.owner[0], pd.namespace, pd.owner[1]))
        total = 0
        for kind, ns, name in owners:
            replicas = self.controller_replicas(kind, ns, name)
            if replicas is None:
                return None
            total += replicas
        return total

    def _run_preemption(
        self, pods, nodes, running, utils, m: CycleMetrics,
        *, ephemeral: bool = False,
    ):
        """Select and evict victims for this cycle's unschedulable pods.

        Device pass (ops/preempt.py) proposes (node, victims) per
        preemptor; the host applies proposals in priority order, one
        preemptor per node per cycle (two proposals for one node were
        each computed assuming the other's victims still hold capacity).
        Victims are evicted through self.evictor; the preemptor is
        already requeued and binds on a later cycle once the victims'
        capacity is actually released — upstream's nominated-node flow
        has the same asynchrony (preemption never binds in-cycle).
        """
        import jax.numpy as jnp

        from kubernetes_scheduler_tpu.ops.preempt import VictimArrays

        k_cap = self.config.preemption_max_victims
        if k_cap <= 0 or not nodes:
            return
        cap = self.config.preemption_max_candidates
        if cap > 0 and len(pods) > cap:
            # highest-priority preemptors first; the rest retry next
            # cycle (the device pass's candidate tensors scale with the
            # preemptor count, and only one proposal lands per node per
            # cycle anyway)
            pods = sorted(pods, key=pod_priority, reverse=True)[:cap]
        # THIS cycle's bindings must be part of the capacity model: the
        # `running` list was read before they happened, and a preemption
        # computed against pre-bind free capacity can kill victims for a
        # preemptor that still won't fit (upstream simulates PostFilter
        # against the assume-cache for the same reason)
        if self._cycle_bound:
            running = running + self._cycle_bound
        if not running:
            return
        # drop eviction records whose victim has actually terminated;
        # a still-terminating victim keeps occupying snapshot capacity
        # (it is in `running`) and is excluded from the victim tables
        # below, so its node is naturally unattractive — no explicit
        # node blocking needed
        live_keys = {_pod_key(pd) for pd in running}
        self._pending_evictions = {
            k: v for k, v in self._pending_evictions.items() if k in live_keys
        }
        # snapshot with requests zeroed: compute_feasibility's resource
        # term then checks against FULL allocatable — "could this pod
        # ever fit here after evictions" — while every other constraint
        # family applies unchanged (see ops/preempt.py for the
        # documented affinity-recheck deviation)
        # ephemeral: when this cycle bound pods (or held nomination
        # reservations), `running` here is a
        # throwaway concatenation — recording it would clobber the
        # steady-state prefix caches the main cycle build relies on,
        # silently re-enabling full O(running) rescans every cycle in
        # exactly the saturated regime preemption runs in
        snapshot = self.builder.build_snapshot(
            nodes, utils, running, pending_pods=pods,
            ephemeral=bool(self._cycle_bound) or ephemeral,
        )
        pend = self.builder.build_pod_batch(pods)
        vics = self.builder.build_pod_batch(running)
        # PodDisruptionBudgets: preemption NEVER violates one (stricter
        # than upstream's last-resort violation ordering — documented in
        # ops/preempt.py). Victims under an exhausted budget are excluded
        # from the tables; remaining budgets cap the apply loop below.
        pdbs = list(self.list_pdbs()) if self.list_pdbs is not None else []
        budgets: list[int] = []
        victim_budgets: dict[int, list[int]] = {}
        if pdbs:
            real = [
                pd for pd in running
                # neither nomination reservations (not real pods) nor
                # terminating victims (already being disrupted) count as
                # healthy — otherwise consecutive cycles each see the
                # full count and re-spend the same disruption budget
                if _pod_key(pd) not in self._nominations
                and _pod_key(pd) not in self._pending_evictions
            ]
            for pdb in pdbs:
                matching = [pd for pd in real if pdb.selects(pd)]
                allowed = pdb.allowed(
                    len(matching),
                    expected_count=self._pdb_expected_count(matching),
                )
                if pdb.disruptions_allowed is not None:
                    # the server-computed status predates our in-flight
                    # evictions (informer/TTL lag): a victim still
                    # terminating must be charged against it, or two
                    # consecutive cycles spend the same budget (ADVICE
                    # r3). The spec-math path needs no correction — its
                    # healthy count (`real`) already excludes
                    # pending-eviction victims.
                    pending_matching = sum(
                        1
                        for pd in running
                        if _pod_key(pd) in self._pending_evictions
                        and pdb.selects(pd)
                    )
                    allowed = max(0, allowed - pending_matching)
                budgets.append(allowed)
            for i, pd in enumerate(running):
                sel = [b for b, pdb in enumerate(pdbs) if pdb.selects(pd)]
                if sel:
                    victim_budgets[i] = sel
        node_index = {nd.name: j for j, nd in enumerate(nodes)}
        m_slots = np.asarray(vics.request).shape[0]
        vnode = np.full(m_slots, -1, np.int32)
        # relative start seconds (int32-safe): later = less important =
        # evicted first among equal priority; a pod without
        # status.startTime counts as just-started (upstream
        # GetPodStartTime's nil-means-now)
        starts = [pd.start_time for pd in running if pd.start_time is not None]
        base = min(starts) if starts else 0.0
        vstart = np.full(m_slots, 2**30, np.int32)
        for i, pd in enumerate(running):
            if pd.start_time is not None:
                vstart[i] = int(min(pd.start_time - base, 2**30 - 1))
        for i, pd in enumerate(running):
            key = _pod_key(pd)
            # terminating victims and nomination reservations occupy
            # capacity but are not evictable (a reservation is not a
            # real pod; a terminating victim is already dying)
            if key in self._pending_evictions or key in self._nominations:
                continue
            if any(budgets[b] <= 0 for b in victim_budgets.get(i, ())):
                continue  # an exhausted budget protects this victim
            vnode[i] = node_index.get(pd.node_name, -1)
        # victim selector data for the RemovePod re-simulation
        # (ops/preempt.affinity_after_evictions): matches = the victims'
        # pod_matches rows; anti = one-hot union of their REQUIRED anti
        # terms. Column count pinned to the SNAPSHOT's selector axis —
        # building the victim batch can mint selector ids the snapshot
        # tables never saw (running pods' required attract terms), and
        # no pending pod references those.
        s_cols = int(np.asarray(snapshot.domain_counts).shape[1])
        vmatches = np.zeros((m_slots, s_cols), bool)
        vanti = np.zeros((m_slots, s_cols), bool)
        pm = np.asarray(vics.pod_matches)
        take = min(s_cols, pm.shape[1])
        vmatches[:, :take] = pm[: m_slots, :take]
        asel = np.asarray(vics.anti_affinity_sel)
        rows, cols = np.nonzero((asel >= 0) & (asel < s_cols))
        vanti[rows, asel[rows, cols]] = True
        victims = VictimArrays(
            node=jnp.asarray(vnode),
            prio=vics.priority,
            req=vics.request,
            mask=vics.pod_mask,
            start=jnp.asarray(vstart),
            matches=jnp.asarray(vmatches),
            anti=jnp.asarray(vanti),
        )
        # the pass runs on the engine — on a bridged deployment that is
        # the sidecar's Preempt RPC, keeping PostFilter on the compute
        # side of the bridge like every other phase; a version-skewed or
        # unreachable sidecar degrades to the in-host evaluation (same
        # tensors, CPU jax), never to no-preemption
        res = None
        # breaker state() (never allow()): preemption must not consume
        # the half-open recovery probe the next cycle's schedule
        # dispatch is entitled to — while the breaker is anything but
        # closed, the pass runs in-host outright
        if hasattr(self.engine, "preempt") and (
            self.engine_breaker.state() == "closed"
        ):
            try:
                res = self.engine.preempt(snapshot, pend, victims, k_cap=k_cap)
            except NotImplementedError:
                log.warning(
                    "engine lacks the Preempt surface; running the "
                    "preemption pass in-host"
                )
            except Exception:
                log.exception(
                    "engine preemption pass failed; running in-host"
                )
                if not self._engine_owns_breaker:
                    # a shared client breaker already recorded the
                    # terminal outcome inside the call (same guard as
                    # _engine_failure — double-feeding would count one
                    # outage twice toward the threshold)
                    self.engine_breaker.record_failure()
        if res is None:
            from kubernetes_scheduler_tpu.engine import preempt_batch

            res = preempt_batch(snapshot, pend, victims, k_cap=k_cap)
        # graftlint: disable=host-transfer -- the preemption pass's TWO bulk boundary syncs (node + victim matrices, whole result at once); the per-victim reads below stay on host numpy
        chosen_node = np.asarray(res.node)
        # graftlint: disable=host-transfer -- second leaf of the same bulk boundary sync
        victim_ids = np.asarray(res.victims)
        prio = np.asarray(pend.priority)
        order = sorted(range(len(pods)), key=lambda i: (-int(prio[i]), i))
        claimed_nodes: set[int] = set()
        ttl = self.config.preemption_nomination_ttl_seconds
        for i in order:
            j = int(chosen_node[i])
            if (
                j < 0
                or j >= len(nodes)
                or j in claimed_nodes
                or _pod_key(pods[i]) in self._nominations
            ):
                continue
            claimed_nodes.add(j)
            vset = [int(v) for v in victim_ids[i] if 0 <= int(v) < len(running)]
            # a proposal that would overdraw any disruption budget is
            # skipped whole (never partially violate): the preemptor
            # retries next cycle against recomputed budgets
            if victim_budgets:
                need: dict[int, int] = {}
                for v in vset:
                    for b in victim_budgets.get(v, ()):
                        need[b] = need.get(b, 0) + 1
                if any(budgets[b] < k for b, k in need.items()):
                    continue
            n_evicted = 0
            for v in vset:
                try:
                    self.evictor.evict(running[v], preemptor=pods[i])
                except Exception:
                    # partial proposal: victims already deleted are
                    # tracked below either way; stop killing more for a
                    # proposal that may no longer complete
                    log.exception(
                        "evicting %s for %s failed; abandoning the rest "
                        "of this proposal",
                        running[v].name, pods[i].name,
                    )
                    break
                self._pending_evictions[_pod_key(running[v])] = nodes[j].name
                for b in victim_budgets.get(v, ()):
                    budgets[b] -= 1
                n_evicted += 1
            if n_evicted:
                # the nomination must be recorded even for a PARTIAL
                # eviction round: capacity was destroyed on this node
                # for this preemptor, and an un-nominated preemptor
                # would evict again elsewhere next cycle
                self._nominations[_pod_key(pods[i])] = (
                    nodes[j].name, pods[i], time.monotonic() + ttl,
                )
                m.pods_preempted += 1
                m.victims_evicted += n_evicted
                log.info(
                    "preempting %d pod(s) on %s for %s",
                    n_evicted, nodes[j].name, pods[i].name,
                )

    # ---- gang co-scheduling (config.gang_scheduling; ops/gang.py) ------

    def _window_gang_groups(self, window) -> dict:
        """gang key -> [declared size, member row indices] over a
        window. Empty for gang-free traffic (one memoized label probe
        per pod — the cost profile of the existing flag scans).
        Members declaring inconsistent sizes (malformed labels) take
        the MAX: the conservative all-or-nothing reading."""
        groups: dict[str, list] = {}
        for i, pod in enumerate(window):
            g = pod_gang(pod)
            if g is not None:
                ent = groups.get(g[0])
                if ent is None:
                    groups[g[0]] = ent = [g[1], []]
                elif g[1] > ent[0]:
                    ent[0] = g[1]
                ent[1].append(i)
        return groups

    def _gang_screen(self, window: list, m: CycleMetrics) -> list:
        """Pre-dispatch gang admission control: defer gangs that cannot
        possibly bind this cycle (members missing from the window, or a
        declared size no window can hold), and keep gangs from
        STRADDLING a stacked-window stride (each scan step checks
        completeness against its own window, so a boundary-crossing
        gang would always read as partial) — stride-aligned gangs ride
        the deep multi-window dispatch untouched. Returns the window to
        dispatch."""
        groups = self._window_gang_groups(window)
        if not groups:
            return window
        drop: set[int] = set()
        for key, (size, rows) in groups.items():
            if len(rows) >= size and size <= self.config.batch_window:
                continue
            drop.update(rows)
            self._defer_gang(key, size, [window[i] for i in rows], m)
        if drop:
            window = [pd for i, pd in enumerate(window) if i not in drop]
        bw = self.config.batch_window
        if len(window) > bw:
            # deep pop: a gang fully inside ONE stacked-window stride is
            # fine (each scan step applies its own all-or-nothing mask),
            # but a gang STRADDLING a stride boundary would always read
            # as partial in both strides. Cut the pop at the first
            # straddling gang's first member (pulling in any gang a
            # naive cut would itself split) and hand the suffix back —
            # gang-free deep backlogs and stride-aligned gangs keep the
            # full multi-window dispatch.
            groups = self._window_gang_groups(window)
            straddle = [
                rows[0]
                for _, rows in groups.values()
                if rows[0] // bw != rows[-1] // bw
            ]
            if straddle:
                cut = min(straddle)
                while True:
                    new_cut = min(
                        (
                            rows[0]
                            for _, rows in groups.values()
                            if rows[-1] >= cut
                        ),
                        default=cut,
                    )
                    if new_cut == cut:
                        break
                    cut = new_cut
                if cut > 0:
                    self.queue.restore_window(window[cut:])
                    window = window[:cut]
                else:
                    # the straddling gang starts at row 0: a prefix cut
                    # cannot make progress. Trim to one stride instead,
                    # moving any stride-crossing gang out whole — the
                    # head gangs then schedule in a single window and
                    # the tail leads the next pop.
                    move = {
                        key
                        for key, (_, rows) in groups.items()
                        if rows[-1] >= bw
                    }
                    kept, restored = [], []
                    for i, pd in enumerate(window):
                        g = pod_gang(pd)
                        if i >= bw or (g is not None and g[0] in move):
                            restored.append(pd)
                        else:
                            kept.append(pd)
                    self.queue.restore_window(restored)
                    window = kept
        return window

    def _defer_gang(
        self, key: str, size: int, members: list, m: CycleMetrics,
        *, masked: int = 0,
    ) -> None:
        """All-or-nothing deferral: the whole gang returns to the queue
        as a unit. Within the defer budget it goes back to the FRONT
        (queue.restore_window — order preserved, re-pops next cycle,
        picking up members that arrive in between). A gang that exhausts
        config.gang_max_defers — or could never fit a window — resolves
        per config.gang_defer_policy: "split" drops the gang identity
        (members schedule as individuals), "drop" keeps it and retries
        all-or-nothing at ordinary backoff cadence."""
        m.gangs_deferred += 1
        m.gang_pods_masked += masked
        n = self._gang_defers.get(key, 0) + 1
        oversize = size > self.config.batch_window
        if oversize or n > self.config.gang_max_defers:
            self._gang_defers.pop(key, None)
            split = oversize or self.config.gang_defer_policy == "split"
            if split:
                for pod in members:
                    break_gang(pod)
            log.warning(
                "gang %s (%d/%d members) %s after %d deferral(s)%s",
                key, len(members), size,
                "split into individuals" if split else "dropped to backoff",
                n,
                " (gang larger than any window)" if oversize else "",
            )
            for pod in members:
                self.queue.requeue_unschedulable(pod)
            m.pods_unschedulable += len(members)
            return
        self._gang_defers[key] = n
        # atomic requeue, matched to the queue's restore semantics so
        # serial and pipelined pop orders stay identical per queue type:
        # - front-restoring queue (pure Python): hand the prefetched
        #   window back FIRST, then the gang — the next pop yields
        #   gang + prefetched pods exactly as serial would have popped
        #   them (newest restore wins the front);
        # - back-restoring queue (native heap): KEEP the prefetch — the
        #   gang goes behind the waiting pods on both drivers, and
        #   flushing the prefetch would re-push it behind pods the
        #   serial driver pops later.
        if getattr(self.queue, "RESTORES_TO_FRONT", False):
            pf = self._take_prefetched()
            if pf is not None:
                self._discard_speculative(m)
                self.queue.restore_window(pf)
        self.queue.restore_window(members)

    def _resolve_gangs(self, window, idx, m: CycleMetrics):
        """Post-result gang resolution: bind fully-placed gangs, defer
        the rest as units. The host-side all-or-nothing BACKSTOP is
        ops.gang.mask_partial_gangs_np — the numpy mirror test-pinned
        bitwise-equal to the device op — applied to EVERY reply:
        against a gang-capable engine it is the identity (the device
        already rescinded partial placements, sentinels <= -2); against
        a gang-blind one (old sidecar after a capability downgrade, the
        mesh-sharded fast path) it produces the same masked vector, so
        no partial gang can ever reach mark_scheduled on ANY path.
        Admission mirrors the device rule exactly: assigned-member
        count >= declared size (an over-submitted gang's surplus
        members fall through to the ordinary requeue loop).
        Returns the (window, idx) remainder for the ordinary bind loop."""
        from kubernetes_scheduler_tpu.ops.gang import (
            GANG_MASKED_BASE,
            mask_partial_gangs_np,
        )

        groups = self._window_gang_groups(window)
        if not groups:
            return window, idx
        n_win = len(window)
        gang_id = np.full(n_win, -1, np.int32)
        gang_size = np.zeros(n_win, np.int32)
        for slot, (size, rows) in enumerate(groups.values()):
            gang_id[rows] = slot
            gang_size[rows] = size
        idx, _ = mask_partial_gangs_np(
            gang_id, gang_size, np.asarray(idx)[:n_win]
        )
        drop: set[int] = set()
        for key, (size, rows) in groups.items():
            got = idx[rows]
            if int((got >= 0).sum()) >= size > 0:
                m.gangs_admitted += 1
                self._gang_defers.pop(key, None)
                continue
            drop.update(rows)
            self._defer_gang(
                key, size, [window[i] for i in rows], m,
                masked=int((got <= GANG_MASKED_BASE).sum()),
            )
        if drop:
            keep = [i for i in range(n_win) if i not in drop]
            window = [window[i] for i in keep]
            idx = idx[keep]
        return window, idx

    def _nomination_reservations(self, window) -> list[Pod]:
        """Virtual running pods holding nominated capacity (see
        run_cycle). Prunes expired nominations; a nomination is also
        dropped when its preemptor binds (Scheduler._bind)."""
        import dataclasses

        now = time.monotonic()
        self._nominations = {
            k: v for k, v in self._nominations.items() if v[2] > now
        }
        if not self._nominations:
            return []
        in_window = {_pod_key(pd) for pd in window}
        return [
            dataclasses.replace(pod, node_name=node)
            for key, (node, pod, _) in self._nominations.items()
            if key not in in_window
        ]

    def _running_features(self, running, *, record: bool = True) -> tuple[bool, bool]:
        """(any pod with (anti)affinity terms, any PREFERRED term) over
        the running set, with a prefix-identity cache: the cluster source
        passes the SAME append-only list cycle after cycle, so only pods
        added since the last probe are walked (two O(running) scans per
        cycle otherwise — a visible cost at 20k+ running pods). A rebuilt
        or shrunk list falls back to a full scan.

        record=False probes without storing the prefix record — for
        throwaway concatenations (nomination reservations, per-chunk
        running + cycle_bound): recording those would evict the
        steady-state record and force a full rescan next cycle (the same
        rule as the snapshot builder's ephemeral=True)."""
        rf = self.__dict__.get("_run_feat")
        start = suffix_start(rf[0] if rf else None, running)
        any_aff, any_pref = (rf[1], rf[2]) if start else (False, False)
        if start < len(running):
            for pd in running[start:]:
                fl = pd.__dict__.get("_flags_cache")
                if fl is not None and fl & _FLAG_PLAIN:
                    continue  # plain pods carry no pod_affinity terms
                pa = pd.pod_affinity
                if pa:
                    any_aff = True
                    if not any_pref and any(t.preferred for t in pa):
                        any_pref = True
            if record:
                self.__dict__["_run_feat"] = (
                    suffix_record(running), any_aff, any_pref,
                )
        return any_aff, any_pref

    def _window_flags(self, window) -> tuple[bool, bool]:
        """(every pod FLAG_PLAIN, any pod FLAG_SOFT) over the window,
        computed in ONE pass and identity-cached on the window list:
        _scalar_sufficient and _engine_options otherwise each ran their
        own full-window flag scan per cycle (~13ms each at 8k pods).

        The pass assembles the window's batch records (warmed at submit)
        and reduces their packed flag column vectorized; the records are
        kept for build_pod_batch so the window is only walked once."""
        wf = self.__dict__.get("_wflags")
        if wf is not None and wf[0] is window:
            return wf[1], wf[2]
        if not window:
            res = (window, True, False)
        else:
            names_t = self.builder.resource_names_tuple()
            recs = [
                rc
                if (rc := pd.__dict__.get("_batch_rec_cache")) is not None
                and rc[0] is names_t
                else pod_batch_record(pd, names_t)
                for pd in window
            ]
            flags = np.frombuffer(
                b"".join([rc[7] for rc in recs]), _SCAL_DT
            )["fl"]
            res = (
                window,
                bool(((flags & _FLAG_PLAIN) != 0).all()),
                bool((flags & _FLAG_SOFT).any()),
            )
            self.__dict__["_wrecs"] = (window, recs)
        self.__dict__["_wflags"] = res
        return res[1], res[2]

    def _window_recs(self, window):
        """The batch records _window_flags assembled for this window, or
        None when a different window was flagged last."""
        wr = self.__dict__.get("_wrecs")
        return wr[1] if wr is not None and wr[0] is window else None

    def _scalar_sufficient(
        self, window, nodes, running, *, record: bool = True
    ) -> bool:
        """True when this cycle uses no constraint family beyond the scalar
        path's surface (live score + resource fit).

        Running pods matter too: a running pod's REQUIRED anti-affinity
        forbids matching pending pods from its domain (the reverse
        direction upstream InterPodAffinity enforces), and its PREFERRED
        terms contribute score — both engine-only capabilities, so any
        running pod with pod_affinity terms forces the engine path."""
        if any(nd.taints or nd.cards for nd in nodes):
            return False
        if not self._window_flags(window)[0]:
            return False
        any_aff, _ = self._running_features(running, record=record)
        return not any_aff

    def _bind(self, pod, node_name: str, m: CycleMetrics) -> None:
        """Bind with upstream error semantics: a 404/409 from the API
        server means the pod is gone or already bound (routine lifecycle
        races) — forget it; any other bind failure requeues with backoff.
        A binder error must never escape the cycle (it would kill the
        serve-forever loop on one racing pod)."""
        try:
            self.binder.bind(pod, node_name)
        except Exception as e:
            status = getattr(e, "status", None)
            if status in (404, 409):
                log.warning(
                    "bind %s -> %s rejected (HTTP %s); dropping pod",
                    pod.name, node_name, status,
                )
                self.queue.mark_scheduled(pod)
                m.pods_dropped += 1
            else:
                log.warning(
                    "bind %s -> %s failed (%s); requeueing", pod.name, node_name, e
                )
                self.queue.requeue_unschedulable(pod)
                m.pods_unschedulable += 1
            return
        # retry-counter clearing is deferred to the cycle-end batch
        # (queue.mark_scheduled_many over _cycle_bound)
        m.pods_bound += 1
        self._cycle_bound.append(pod)
        if self._nominations:  # skip the key build on the common path
            self._nominations.pop(_pod_key(pod), None)

    def _requeue_unschedulable(self, pod: Pod, m: CycleMetrics) -> None:
        """Nothing fit this pod this cycle: requeue with backoff and
        remember it as a preemption candidate for this cycle's PostFilter
        pass (upstream: unschedulable pods enter PostFilter)."""
        self.queue.requeue_unschedulable(pod)
        m.pods_unschedulable += 1
        self._cycle_unsched.append(pod)

    def _engine_options(
        self, window, nodes, running, pods_batch, snapshot=None,
        *, record: bool = True,
    ) -> dict:
        """Per-cycle engine options, shared by the single-window and
        backlog device paths so their semantics cannot diverge.

        Both assigners enforce window-internal (anti)affinity exactly
        (greedy: live counts in the scan; auction: per-round dynamic
        masks + same-round conflict eviction — ops/assign.py). The
        dynamic machinery is only needed when placements inside this
        cycle can interact: some pod matches a selector AND some pod
        constrains on one; otherwise static pre-window counts are exact
        and ~2x cheaper. Preferred (soft) constraints become score terms
        only when present (window preferences, running pods' preferred
        terms, soft taints). The fused Pallas path is an optimization
        with identical decisions; silently unavailable outside its
        (policy, normalizer) domain."""
        if snapshot is not None:
            # vectorized soft-taint probe over the already-built arrays
            # (taints[..., 2] is the encoded effect column); the nested
            # generator scan over 4k nodes measured ~1ms/cycle
            tmask = np.asarray(snapshot.taint_mask)
            soft_taints = bool(tmask.any()) and bool(
                (
                    (np.asarray(snapshot.taints)[..., 2] == _PREFER_NO_SCHEDULE)
                    & tmask
                ).any()
            )
        else:
            soft_taints = any(
                t.effect == "PreferNoSchedule" for nd in nodes for t in nd.taints
            )
        soft = (
            self._window_flags(window)[1]
            or self._running_features(running, record=record)[1]
            or soft_taints
        )
        affinity_aware = bool(
            np.asarray(pods_batch.pod_matches).any()
            and (
                (np.asarray(pods_batch.affinity_sel) >= 0).any()
                or (np.asarray(pods_batch.anti_affinity_sel) >= 0).any()
                or (np.asarray(pods_batch.spread_sel) >= 0).any()
            )
        )
        score_plugins = self.config.score_plugins_tuple()
        # the fused megakernel's domain (engine.check_fused_contract with
        # min_max_ok): "none" masked-raw, or "min_max" via the kernel's
        # normalize epilogue — which puts the DEPLOYED DEFAULT
        # (normalizer="min_max") on the fused path on TPU-backed engines
        # (_fused_min_max_ok); softmax stays unfused
        fused = (
            self.config.feature_gates.fused_kernel
            and score_plugins is None
            and self.config.policy == "balanced_cpu_diskio"
            and (
                self.config.normalizer == "none"
                or (
                    self.config.normalizer == "min_max"
                    and self._fused_min_max_ok()
                )
            )
        )
        self._ladder_kernel(fused)
        kw = dict(
            policy=self.config.policy,
            assigner=self.config.assigner,
            normalizer=self.config.normalizer,
            fused=fused,
            affinity_aware=affinity_aware,
            soft=soft,
        )
        if score_plugins is not None:
            # multi-plugin weighted scoring (upstream RunScorePlugins);
            # gated on the engine accepting the kw so a version-skewed
            # remote degrades loud (TypeError -> scalar fallback) rather
            # than silently scoring single-policy
            kw["score_plugins"] = score_plugins
        if self._engine_takes_auction_kw:
            kw.update(
                auction_rounds=self.config.auction_rounds,
                auction_price_frac=self.config.auction_price_frac,
            )
        return kw

    def _ladder_kernel(self, fused: bool) -> None:
        """fused->unfused rung tracking: only a CAPABILITY downgrade —
        a config that HAS served fused cycles coming back unfused
        (mid-stream sidecar downgrade dropping the fused_min_max latch)
        — demotes; configurations that never fuse (softmax, CPU-local
        min_max, plugin scoring) are not degraded, they are simply not
        on the fused path."""
        lad = self.ladder
        seq = self.totals["cycles"]
        if fused:
            self._kernel_fused_seen = True
            if lad.depth("kernel") > 0:
                lad.probe("kernel", seq=seq)
                lad.promote("kernel", seq=seq)
        elif self._kernel_fused_seen and lad.depth("kernel") == 0:
            lad.demote("kernel", reason="capability-downgrade", seq=seq)

    def _run_backlog(
        self, window, nodes, running, utils, m: CycleMetrics,
        *, ephemeral: bool = False,
    ):
        """Deep-queue cycle: schedule the whole backlog as stacked
        windows in ONE engine dispatch (engine.schedule_windows /
        the ScheduleWindows RPC), capacity and (anti)affinity carried
        between windows on device instead of one dispatch per window."""
        from kubernetes_scheduler_tpu.engine import stack_windows
        from kubernetes_scheduler_tpu.utils.padding import pad_pod_batch

        bw = self.config.batch_window
        snapshot, mirror_delta = self._cycle_snapshot(
            window, nodes, running, utils, ephemeral=ephemeral
        )
        pods_batch = self.builder.build_pod_batch(
            window, recs=self._window_recs(window)
        )
        n_padded = -(-len(window) // bw) * bw
        p_have = int(np.asarray(pods_batch.request).shape[0])
        if p_have < n_padded:
            pods_batch = pad_pod_batch(pods_batch, n_padded)
        elif p_have > n_padded:
            # bucket padding overshot the window multiple: drop only
            # pod_mask=False padding rows
            pods_batch = type(pods_batch)(
                # graftlint: disable=host-sync -- builder leaves are host numpy; trimming pad rows, no device sync
                *[np.asarray(a)[:n_padded] for a in pods_batch]
            )
        windows = stack_windows(pods_batch, bw)
        kw = self._engine_options(
            window, nodes, running, pods_batch, snapshot,
            record=not ephemeral,
        )
        self._set_engine_trace_id()
        tctx = None
        if self.recorder is not None:
            tctx = {
                "path": "backlog", "window": window, "snapshot": snapshot,
                "pods": pods_batch, "kw": kw, "batch_window": bw,
            }
            self._trace_cycle.append(tctx)
        res, t_eng = self._dispatch_windows(
            snapshot, windows, kw, m, ephemeral=ephemeral, tctx=tctx,
            mirror_delta=mirror_delta,
        )
        idx = np.asarray(res.node_idx).reshape(-1)
        t_done = time.perf_counter()
        m.engine_seconds += t_done - t_eng
        self._span("engine_step", t_eng, t_done, backlog=True)
        if (
            idx.shape[0] < len(window)
            or (idx[: len(window)] >= len(nodes)).any()
        ):
            raise RuntimeError(
                f"engine returned node_idx shape {np.asarray(res.node_idx).shape} "
                f"for a {len(window)}-pod backlog over {len(nodes)} nodes"
            )
        if tctx is not None:
            tctx["node_idx"] = self._trace_node_idx(
                pods_batch, idx, len(window)
            )
        t_bind = time.perf_counter()
        self._apply_assignments(window, nodes, idx, m)
        self._span("bind", t_bind)

    def _dispatch_windows(
        self, snapshot, windows, kw, m: CycleMetrics,
        *, ephemeral: bool, tctx: dict | None, mirror_delta=None,
    ):
        """Backlog engine dispatch, resident-aware: with
        config.resident_state and an engine serving the windows-resident
        surface, the multi-window backlog path ships SnapshotDeltas too
        (the ROADMAP follow-up — previously full-upload only). Flushes
        to full exactly like the single-window path: snapshot_delta
        returns None on any cross-window layout churn (node/column/
        selector drift), and an ephemeral build is never a delta base.

        Returns (result, engine dispatch timestamp): the host-side
        delta derivation happens BEFORE the timestamp, so the caller's
        engine_seconds measures the engine call + force only — the same
        attribution the single-window _dispatch_resident uses."""
        resident = (
            self.config.resident_state
            and not ephemeral
            and bool(
                getattr(self.engine, "supports_windows_resident", None)
                and self.engine.supports_windows_resident()
            )
        )
        if not resident:
            t_eng = time.perf_counter()
            return self.engine.schedule_windows(snapshot, windows, **kw), t_eng
        delta, epoch, saved = self._derive_resident_delta(
            snapshot, tctx, mirror_delta=mirror_delta
        )
        t_eng = time.perf_counter()
        res = self.engine.schedule_windows_resident(
            snapshot, windows, delta=delta, epoch=epoch, **kw
        )
        # commit AFTER success (the call is synchronous — a failure
        # falls to the caller's scalar fallback, which invalidates)
        self._commit_resident(snapshot, epoch)
        self._account_resident(m, delta is not None, saved)
        return res, t_eng

    def _derive_resident_delta(
        self, snapshot, tctx: dict | None, mirror_delta=None,
    ) -> tuple:
        """(delta, epoch, bytes_saved) for a resident dispatch, with the
        trace context filled — ONE derivation shared by the single-
        window and backlog dispatchers so the two resident surfaces
        cannot drift on delta-base, epoch, or recorder-chain semantics.

        With the snapshot mirror on, the delta was emitted WITH the
        snapshot (already validated against the engine-retained base by
        identity, flush rules applied) — the O(nodes) row diff never
        runs; the delta_derive span survives at ~0 as the before/after
        evidence in `spans report`."""
        from kubernetes_scheduler_tpu.engine import snapshot_nbytes
        from kubernetes_scheduler_tpu.host.snapshot import snapshot_delta

        t_d = time.perf_counter()
        if self.mirror is not None:
            delta = mirror_delta
        else:
            delta = None
            if self._resident_ok and self._resident_prev is not None:
                delta = snapshot_delta(self._resident_prev, snapshot)
        self._span("delta_derive", t_d, sent=delta is not None)
        epoch = self._resident_epoch + 1
        saved = 0
        if delta is not None:
            saved = max(0, snapshot_nbytes(snapshot) - snapshot_nbytes(delta))
        if tctx is not None:
            tctx["delta"] = delta
            # the delta's base identity — the recorder's chain rule
            # (trace/recorder.py) only records a delta whose base IS the
            # previous device record's snapshot
            tctx["delta_base"] = (
                self._resident_prev if delta is not None else None
            )
            tctx["epoch"] = epoch
            tctx["delta_sent"] = delta is not None
        return delta, epoch, saved

    def _commit_resident(self, snapshot, epoch: int) -> None:
        """The dispatched snapshot becomes the next delta base."""
        self._resident_prev = snapshot
        self._resident_epoch = epoch
        self._resident_ok = True

    def _account_resident(
        self, m: CycleMetrics, delta_sent: bool, saved: int
    ) -> None:
        """Attribute a resident dispatch AFTER the engine reports which
        path actually served it (delta applied vs degraded to full) —
        the ONE implementation both resident surfaces and the pipelined
        completion stage use."""
        used_delta = delta_sent and bool(
            getattr(self.engine, "resident_used_delta", False)
        )
        if used_delta:
            m.delta_uploads += 1
            m.delta_bytes_saved += saved
            if self.ladder.depth("resident") > 0:
                # the delta attempt was the recovery probe, and the
                # engine confirmed applying it: climb back to the top
                seq = self.totals["cycles"]
                self.ladder.probe("resident", seq=seq)
                self.ladder.promote("resident", seq=seq)
        else:
            m.full_uploads += 1
        # mesh-sharded engine (config.sharded_engine): which shards this
        # cycle's delta actually reached, read AFTER the force like
        # resident_used_delta (the 1-deep pipeline completes a cycle
        # before the next dispatch overwrites the engine's attributes)
        if used_delta and getattr(self.engine, "n_shards", 0):
            per_shard = getattr(self.engine, "shard_delta_bytes", ())
            if per_shard:
                m.shard_delta_bytes = tuple(int(b) for b in per_shard)

    def _apply_assignments(self, window, nodes, idx, m: CycleMetrics) -> None:
        """Apply engine results: bind assigned pods, requeue the rest.

        Bulk path: when the binder exposes bind_many (RecordingBinder;
        the live KubeBinder keeps per-pod POSTs with their 404/409
        semantics), all assigned pods go through ONE call — the per-pod
        _bind dispatch (try/except + counters) measured ~4.5us x 8k pods
        per cycle, a visible slice of the host loop."""
        if self.config.gang_scheduling:
            window, idx = self._resolve_gangs(window, idx, m)
            if not window:
                return
        p_real = len(window)
        bind_many = getattr(self.binder, "bind_many", None)
        if bind_many is None or p_real < 256:
            for i, pod in enumerate(window):
                j = int(idx[i])
                if j >= 0:
                    self._bind(pod, nodes[j].name, m)
                else:
                    self._requeue_unschedulable(pod, m)
            return
        idxw = np.asarray(idx)[:p_real]
        assigned_at = np.nonzero(idxw >= 0)[0]
        if assigned_at.size == p_real:
            assigned = list(window)
        else:
            assigned = [window[i] for i in assigned_at.tolist()]
            for i in np.nonzero(idxw < 0)[0].tolist():
                self._requeue_unschedulable(window[i], m)
        names = [nodes[j].name for j in idxw[assigned_at].tolist()]
        bind_many(assigned, names)
        m.pods_bound += len(assigned)
        self._cycle_bound.extend(assigned)
        if self._nominations:
            for pod in assigned:
                self._nominations.pop(_pod_key(pod), None)

    def _fused_min_max_ok(self) -> bool:
        """Whether the min_max→fused widening applies for THIS engine.
        LOCAL engines: only on a TPU backend — a CPU backend would
        trade the XLA normalize pass for the interpret-mode Pallas
        megakernel (~2x slower, exactly the per-stage regression `make
        perf-gate` exists to catch); cached, one backend probe.
        REMOTE engines: the HealthReply.fused_min_max capability bit —
        the sidecar advertises the epilogue contract only when its own
        backend profits (TPU), the client latches it with the other
        capability bits, and the answer is deliberately NOT cached
        here: a mid-stream downgrade invalidates the latch and the
        next cycle must come back unfused instead of rejecting the
        fused contract forever. Engines without the probe (version
        skew, learned overrides) keep the pre-widening unfused min_max
        path. normalizer="none" configurations keep their
        long-standing always-fused behavior either way."""
        probe = getattr(self.engine, "supports_fused_min_max", None)
        if probe is not None:
            return bool(probe())
        v = self.__dict__.get("_fused_minmax_ok")
        if v is None:
            if isinstance(self.engine, LocalEngine):
                import jax

                v = jax.default_backend() == "tpu"
            else:
                v = False
            self.__dict__["_fused_minmax_ok"] = v
        return v

    def _run_batched(
        self, window, nodes, running, utils, m: CycleMetrics,
        *, ephemeral: bool = False,
    ):
        """Serial single-window device cycle: the same dispatch/complete
        pair the pipelined driver uses, back to back — one
        implementation of snapshot ordering, engine-result validation,
        and bind application, so the two modes cannot drift."""
        infl = self._dispatch_window(
            window, nodes, running, utils, m,
            ephemeral=ephemeral, use_async=False,
        )
        self._complete_window(infl, window, nodes, m, ephemeral=ephemeral)

    def _run_scalar(self, window, nodes, running, utils, m: CycleMetrics):
        if self.config.gang_scheduling:
            groups = self._window_gang_groups(window)
            if groups:
                # gangs never bind through the scalar path: all-or-
                # nothing needs the batched view (the per-pod loop binds
                # as it goes). Defer each gang as a unit; the rest of
                # the window scalar-schedules normally.
                drop: set[int] = set()
                for key, (size, rows) in groups.items():
                    drop.update(rows)
                    self._defer_gang(
                        key, size, [window[i] for i in rows], m
                    )
                window = [
                    pd for i, pd in enumerate(window) if i not in drop
                ]
                if not window:
                    return
        t_s = time.perf_counter()
        try:
            self._run_scalar_inner(window, nodes, running, utils, m)
        finally:
            self._span("scalar_cycle", t_s)

    def _run_scalar_inner(
        self, window, nodes, running, utils, m: CycleMetrics
    ):
        from kubernetes_scheduler_tpu.host.plugins import SCALAR_POLICIES

        policy = self.config.policy
        score_plugins = self.config.score_plugins_tuple()
        if score_plugins is not None:
            # weighted multi-plugin mode: every heuristic plugin has a
            # scalar mirror; truncate=False matches the engine's
            # combination (its yoda term never truncates)
            bad = [n for n, _ in score_plugins if n not in SCALAR_POLICIES]
            if bad:
                log.warning(
                    "scalar fallback cannot score plugins %r; scoring "
                    "with balanced_cpu_diskio (fallback_policy_mismatch)",
                    bad,
                )
                m.policy_mismatch = True
                score_plugins = None
            else:
                plugin = ScalarYodaPlugin(
                    utils, score_plugins=score_plugins, truncate=False
                )
                self._scalar_window(plugin, window, nodes, running, m)
                return
        if policy == "balanced_cpu_diskio" and nodes and self._native_ok:
            self._run_scalar_native(window, nodes, running, utils, m)
            return
        if policy not in SCALAR_POLICIES:
            # e.g. "learned": the scalar path has no faithful mirror —
            # degrade to the yoda formula and SAY SO, both in the log and
            # in a dedicated counter (a policy change under degradation
            # must be distinguishable from benign same-policy fallback)
            log.warning(
                "scalar fallback cannot score policy %r; scoring with "
                "balanced_cpu_diskio (fallback_policy_mismatch)",
                policy,
            )
            m.policy_mismatch = True
            policy = "balanced_cpu_diskio"
        plugin = ScalarYodaPlugin(utils, policy=policy)
        self._scalar_window(plugin, window, nodes, running, m)

    def _scalar_window(self, plugin, window, nodes, running, m: CycleMetrics):
        free = {
            n.name: {
                res: n.allocatable.get(res, 0.0) for res in self.builder.resource_names
            }
            for n in nodes
        }
        for pod in running:
            if pod.node_name in free:
                for res in free[pod.node_name]:
                    free[pod.node_name][res] -= pod_resource_request(pod, res)
        # scores read the PRE-window capacity state (the engine computes
        # a window's score matrices before any in-window bind; only
        # feasibility is dynamic) — freeze a copy for the scorers while
        # `free` keeps live bookkeeping
        score_free = {name: dict(res) for name, res in free.items()}
        for pod in window:
            plugin.cache.flush()
            best = (
                scalar_schedule_one(
                    plugin, pod, nodes, free, score_free=score_free
                )
                if nodes
                else None
            )
            if best is not None:
                self._bind(pod, best, m)
            else:
                self._requeue_unschedulable(pod, m)

    def _run_scalar_native(self, window, nodes, running, utils, m: CycleMetrics):
        """The scalar fallback in C++ (native/scalar.cc): same decisions
        as the Python plugin path, one library call per window."""
        from kubernetes_scheduler_tpu import native
        from kubernetes_scheduler_tpu.host.snapshot import parse_float_or_zero

        names = self.builder.resource_names
        req = np.array(
            [[pod_resource_request(p, r) for r in names] for p in window],
            np.float32,
        )
        r_io = np.array(
            [parse_float_or_zero(p.annotations.get("diskIO")) for p in window],
            np.float32,
        )
        free = np.array(
            [[n.allocatable.get(r, 0.0) for r in names] for n in nodes],
            np.float32,
        )
        node_index = {n.name: j for j, n in enumerate(nodes)}
        for pod in running:
            j = node_index.get(pod.node_name)
            if j is not None:
                free[j] -= [pod_resource_request(pod, r) for r in names]
        util = [utils.get(n.name, NodeUtil()) for n in nodes]
        disk_io = np.array([u.disk_io for u in util], np.float32)
        cpu_pct = np.array([u.cpu_pct for u in util], np.float32)

        # prebound cycler, reused while the cycle shape is stable (steady
        # state for a fixed window size on a fixed cluster): one foreign
        # call per cycle instead of per-call pointer marshaling
        cyc = self._scalar_cycler
        if cyc is None or cyc.shape != (len(window), len(nodes), len(names)):
            cyc = native.ScalarCycler(req, r_io, free, disk_io, cpu_pct)
            self._scalar_cycler = cyc
        else:
            cyc.update(
                pod_req=req, r_io=r_io, free=free, disk_io=disk_io,
                cpu_pct=cpu_pct,
            )
        cyc.run()
        idx = cyc.node_idx
        for i, pod in enumerate(window):
            j = int(idx[i])
            if j >= 0:
                self._bind(pod, nodes[j].name, m)
            else:
                self._requeue_unschedulable(pod, m)

    # ---- loop ----------------------------------------------------------

    def run_until_empty(self, *, max_cycles: int = 1000) -> list[CycleMetrics]:
        out = []
        for _ in range(max_cycles):
            # a prefetched window lives outside the queue (popped while
            # the previous engine call was in flight) — the drain is not
            # done until it has been dispatched too
            if len(self.queue) == 0 and self._prefetched is None:
                break
            out.append(self.run_cycle())
        return out
