"""Host layer: everything between the cluster and the device engine.

The reference splits this across the upstream kube-scheduler framework
(queue, snapshot, binding cycle) and its plugin (pkg/yoda). Here the host
layer owns:

- typed cluster objects (types.py) standing in for the k8s API objects,
- string-interning snapshot builders producing the dense arrays the engine
  consumes (snapshot.py),
- the metrics advisor scraping Prometheus (advisor.py),
- the per-cycle cache that replaces Redis (cache.py),
- the priority scheduling queue with retry backoff (queue.py),
- the extension-point plugin surface and the scalar fallback path
  (plugins.py),
- the scheduling loop that ties it together (scheduler.py).
"""

from kubernetes_scheduler_tpu.host.types import Card, Container, Node, Pod, Taint, Toleration
from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder
from kubernetes_scheduler_tpu.host.advisor import NodeUtil, PrometheusAdvisor, StaticAdvisor
from kubernetes_scheduler_tpu.host.cache import CycleCache
from kubernetes_scheduler_tpu.host.queue import SchedulingQueue
from kubernetes_scheduler_tpu.host.scheduler import (
    RecordingBinder,
    RecordingEvictor,
    Scheduler,
)
