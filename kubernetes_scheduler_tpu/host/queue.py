"""Scheduling queue: priority ordering + retry backoff.

Reproduces the two queue behaviors the reference relies on:
- priority ordering by the `scv/priority` label, higher first (the
  QueueSort comparator the reference defines but never registers,
  pkg/yoda/sort/sort.go:8-18) with FIFO order among equals;
- unschedulable pods retry with exponential backoff between
  podInitialBackoffSeconds=1 and podMaxBackoffSeconds=10
  (deploy/yoda-scheduler.yaml:19-20).
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field

from kubernetes_scheduler_tpu.host.types import Pod


def pod_priority(pod: Pod) -> int:
    """sort.go:12-18: integer `scv/priority` label, 0 when absent/garbage."""
    try:
        return int(pod.labels.get("scv/priority", 0))
    except (TypeError, ValueError):
        return 0


@dataclass(order=True)
class _Entry:
    sort_key: tuple
    pod: Pod = field(compare=False)


class SchedulingQueue:
    def __init__(
        self,
        *,
        initial_backoff: float = 1.0,
        max_backoff: float = 10.0,
        clock=time.monotonic,
    ):
        self._active: list[_Entry] = []
        self._backoff: list[tuple[float, int, Pod]] = []  # (ready_at, seq, pod)
        self._attempts: dict[str, int] = {}
        self._seq = itertools.count()
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self._clock = clock

    def _key(self, pod: Pod) -> tuple:
        return (-pod_priority(pod), next(self._seq))

    def push(self, pod: Pod) -> None:
        heapq.heappush(self._active, _Entry(self._key(pod), pod))

    def requeue_unschedulable(self, pod: Pod) -> None:
        """Failed cycle -> backoff queue with exponential delay."""
        uid = f"{pod.namespace}/{pod.name}"
        attempt = self._attempts.get(uid, 0) + 1
        self._attempts[uid] = attempt
        delay = min(self.initial_backoff * 2 ** (attempt - 1), self.max_backoff)
        heapq.heappush(
            self._backoff, (self._clock() + delay, next(self._seq), pod)
        )

    def mark_scheduled(self, pod: Pod) -> None:
        self._attempts.pop(f"{pod.namespace}/{pod.name}", None)

    def _drain_backoff(self) -> None:
        now = self._clock()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, pod = heapq.heappop(self._backoff)
            self.push(pod)

    def pop_window(self, max_pods: int) -> list[Pod]:
        """Highest-priority window of pending pods for one engine cycle."""
        self._drain_backoff()
        out = []
        while self._active and len(out) < max_pods:
            out.append(heapq.heappop(self._active).pod)
        return out

    def __len__(self) -> int:
        return len(self._active) + len(self._backoff)
