"""Scheduling queue: priority ordering + retry backoff.

Reproduces the two queue behaviors the reference relies on:
- priority ordering, higher first, FIFO among equals: the API-server-
  resolved `spec.priority` (upstream PriorityClass) when present, else
  the `scv/priority` label (the QueueSort comparator the reference
  defines but never registers, pkg/yoda/sort/sort.go:8-18);
- unschedulable pods retry with exponential backoff between
  podInitialBackoffSeconds=1 and podMaxBackoffSeconds=10
  (deploy/yoda-scheduler.yaml:19-20).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import zlib
from dataclasses import dataclass, field

from kubernetes_scheduler_tpu.host.types import Pod


def pod_priority(pod: Pod) -> int:
    """spec.priority when the API server resolved one (upstream
    PriorityClass semantics), else the reference's integer
    `scv/priority` label (sort.go:12-18), 0 when absent/garbage.
    Memoized on the pod object (immutable spec): probed per pod by the
    queue key, the batch builder, and preemption ordering every cycle."""
    v = pod.__dict__.get("_prio_cache")
    if v is None:
        if pod.priority is not None:
            v = int(pod.priority)
        else:
            try:
                v = int(pod.labels.get("scv/priority", 0))
            except (TypeError, ValueError):
                v = 0
        pod.__dict__["_prio_cache"] = v
    return v


_GANG_UNSET = object()


def pod_gang(pod: Pod) -> tuple[str, int] | None:
    """Gang identity (all-or-nothing co-scheduling, ops/gang.py) from
    the `scv/gang` + `scv/gang-size` labels, memoized on the pod object
    like pod_priority: ("<namespace>/<gang name>", declared size), or
    None for ordinary pods (absent/garbage labels, or size < 2 — a
    one-pod "gang" is just a pod). The scheduler clears the memo to None
    when a gang exhausts its defer budget under the "split" policy
    (break_gang) — its members then schedule as individuals."""
    v = pod.__dict__.get("_gang_cache", _GANG_UNSET)
    if v is _GANG_UNSET:
        v = None
        name = pod.labels.get("scv/gang")
        if name:
            try:
                size = int(pod.labels.get("scv/gang-size", 0))
            except (TypeError, ValueError):
                size = 0
            if size >= 2:
                v = (f"{pod.namespace}/{name}", size)
        pod.__dict__["_gang_cache"] = v
    return v


def break_gang(pod: Pod) -> None:
    """Drop a pod's gang identity (the "split" defer policy): it
    schedules as an individual from the next cycle on."""
    pod.__dict__["_gang_cache"] = None


@dataclass(order=True)
class _Entry:
    sort_key: tuple
    pod: Pod = field(compare=False)


class SchedulingQueue:
    """Thread-safe: the live-cluster loop (kube/source.run_kube_loop)
    feeds submissions from a watch thread while the scheduling thread
    pops windows — the same producer/consumer split as the upstream
    scheduling queue."""

    # restore_window returns pods to the FRONT of their priority class
    # (exact re-pop position). Gang deferral branches on this: a
    # front-restoring queue needs the pipelined driver's prefetched
    # window handed back BEHIND the deferred gang to match serial pop
    # order; a back-restoring queue (the native heap) must instead KEEP
    # the prefetch — see Scheduler._defer_gang.
    RESTORES_TO_FRONT = True

    def __init__(
        self,
        *,
        initial_backoff: float = 1.0,
        max_backoff: float = 10.0,
        clock=time.monotonic,
    ):
        self._active: list[_Entry] = []
        self._backoff: list[tuple[float, int, Pod]] = []  # (ready_at, seq, pod)
        self._attempts: dict[str, int] = {}
        self._seq = itertools.count()
        # restore_window keys: strictly below every normal seq, so a
        # returned window pops ahead of equal-priority pods queued since
        self._front_floor = 0
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self._clock = clock
        self._lock = threading.RLock()

    def _key(self, pod: Pod) -> tuple:
        return (-pod_priority(pod), next(self._seq))

    def push(self, pod: Pod) -> None:
        with self._lock:
            heapq.heappush(self._active, _Entry(self._key(pod), pod))

    def requeue_unschedulable(self, pod: Pod) -> None:
        """Failed cycle -> backoff queue with exponential delay."""
        with self._lock:
            uid = f"{pod.namespace}/{pod.name}"
            attempt = self._attempts.get(uid, 0) + 1
            self._attempts[uid] = attempt
            delay = min(
                self.initial_backoff * 2 ** (attempt - 1), self.max_backoff
            )
            heapq.heappush(
                self._backoff, (self._clock() + delay, next(self._seq), pod)
            )

    def mark_scheduled(self, pod: Pod) -> None:
        with self._lock:
            self._attempts.pop(f"{pod.namespace}/{pod.name}", None)

    def mark_scheduled_many(self, pods: list[Pod]) -> None:
        """Batch form: one lock round for a whole cycle's binds."""
        with self._lock:
            for pod in pods:
                self._attempts.pop(f"{pod.namespace}/{pod.name}", None)

    def _drain_backoff(self) -> None:
        now = self._clock()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, pod = heapq.heappop(self._backoff)
            heapq.heappush(self._active, _Entry(self._key(pod), pod))

    def pop_window(self, max_pods: int) -> list[Pod]:
        """Highest-priority window of pending pods for one engine cycle."""
        with self._lock:
            self._drain_backoff()
            if self._active and len(self._active) <= max_pods:
                # whole-queue pop (the deep-backlog drain shape —
                # queue_pop was a named stage in the 4k-node cycle
                # budget): ONE sort instead of a heappop per pod, and
                # the SAME order — sort keys are unique (seq counter),
                # so heap drain order == sorted order
                entries = sorted(self._active)
                self._active.clear()
                return [e.pod for e in entries]
            out = []
            while self._active and len(out) < max_pods:
                out.append(heapq.heappop(self._active).pod)
            return out

    def restore_window(self, pods: list[Pod]) -> None:
        """Return a popped-but-unscheduled window to the FRONT of the
        queue: restored pods keep their relative order and precede every
        pod currently queued at equal priority — re-popping immediately
        yields the same window. Used by the pipelined scheduler
        (Scheduler.drain_pipeline) to hand back a prefetched window and
        by gang deferral (Scheduler._defer_gang) to requeue a gang
        atomically ahead of its equals. Restoring several windows
        without popping in between re-merges them newest-first — which
        is exactly what _defer_gang relies on: prefetched window first,
        deferred gang second, so the gang leads the next pop."""
        with self._lock:
            base = self._front_floor - len(pods)
            for i, pod in enumerate(pods):
                heapq.heappush(
                    self._active,
                    _Entry((-pod_priority(pod), base + i), pod),
                )
            self._front_floor = base

    def __len__(self) -> int:
        with self._lock:
            return len(self._active) + len(self._backoff)


class NativeBackedQueue:
    """SchedulingQueue surface over the C++ queue (native/queue.cc).

    Pods are handed to the native side as opaque uint64 handles; this
    wrapper owns the handle -> Pod map. Raises RuntimeError at
    construction when the native library is unavailable — callers (the
    Scheduler) then keep the pure-Python queue.
    """

    # the native heap re-pushes restored pods with fresh sequence
    # numbers: BACK of their priority class (see restore_window)
    RESTORES_TO_FRONT = False

    def __init__(
        self,
        *,
        initial_backoff: float = 1.0,
        max_backoff: float = 10.0,
        clock=time.monotonic,
    ):
        from kubernetes_scheduler_tpu import native

        self._q = native.NativeQueue(
            initial_backoff=initial_backoff, max_backoff=max_backoff
        )
        self._clock = clock
        self._pods: dict[int, Pod] = {}
        self._handles = itertools.count(1)
        self._by_uid: dict[str, int] = {}
        # native-queue entries per handle; the handle->Pod mapping may only
        # be dropped once no copy is queued AND the pod is done (so a uid
        # pushed twice survives the first copy's mark_scheduled)
        self._outstanding: dict[int, int] = {}
        # same producer/consumer contract as SchedulingQueue; the lock
        # also serializes entry to the (single-threaded) C++ queue
        self._lock = threading.RLock()

    def _handle(self, pod: Pod) -> int:
        uid = f"{pod.namespace}/{pod.name}"
        h = self._by_uid.get(uid)
        if h is None:
            h = next(self._handles)
            self._by_uid[uid] = h
        self._pods[h] = pod
        # handle memo for mark_scheduled_many: handles are never reused
        # (monotonic counter), so a memoized h still present in _pods is
        # by construction this pod's live entry — the bulk mark path
        # skips the f-string + uid lookup per pod (~2us x 8k per cycle)
        pod.__dict__["_qh"] = (self, h, uid)
        return h

    def _drop_if_done(self, h: int) -> None:
        if self._outstanding.get(h, 0) <= 0:
            # graftlint: disable=lock-discipline -- callers (mark_scheduled, pop_window) hold self._lock
            self._outstanding.pop(h, None)
            pod = self._pods.pop(h, None)
            if pod is not None:
                self._by_uid.pop(f"{pod.namespace}/{pod.name}", None)

    def push(self, pod: Pod) -> None:
        with self._lock:
            h = self._handle(pod)
            self._outstanding[h] = self._outstanding.get(h, 0) + 1
            self._q.push(h, pod_priority(pod))

    def requeue_unschedulable(self, pod: Pod) -> None:
        with self._lock:
            h = self._handle(pod)
            self._outstanding[h] = self._outstanding.get(h, 0) + 1
            self._q.requeue_unschedulable(h, pod_priority(pod), self._clock())

    def mark_scheduled(self, pod: Pod) -> None:
        with self._lock:
            uid = f"{pod.namespace}/{pod.name}"
            h = self._by_uid.get(uid)
            if h is not None:
                self._q.mark_scheduled(h)
                self._drop_if_done(h)

    def mark_scheduled_many(self, pods: list[Pod]) -> None:
        """Batch form: ONE foreign call clears every bind's retry
        counter (native yoda_queue_mark_scheduled_batch), one lock round
        for the Python bookkeeping — the per-bind ctypes dispatch was a
        visible slice of big-backlog cycles. Handle resolution goes
        through the _qh memo (see _handle); pods from another queue or
        with dead handles fall back to the uid path."""
        import numpy as np

        with self._lock:
            pods_d = self._pods
            out_d = self._outstanding
            uid_d = self._by_uid
            handles = []
            append = handles.append
            for pod in pods:
                rec = pod.__dict__.get("_qh")
                if rec is not None and rec[0] is self and rec[1] in pods_d:
                    h, uid = rec[1], rec[2]
                else:
                    uid = f"{pod.namespace}/{pod.name}"
                    h = uid_d.get(uid)
                    if h is None:
                        continue
                append((h, uid))
            if handles:
                self._q.mark_scheduled_batch(
                    np.asarray([h for h, _ in handles], np.uint64)
                )
            # Python bookkeeping drops only AFTER the native marks
            # succeeded (mark-then-drop, like the serial path): a raising
            # native call must leave the maps intact so the binds can be
            # re-marked. A pod appearing twice in one batch resolves its
            # handle twice — harmless, the native mark is an idempotent
            # attempts.erase — where an early drop would instead lose the
            # second lookup mid-batch
            for h, uid in handles:
                # inline _drop_if_done with the uid already in hand
                if out_d.get(h, 0) <= 0:
                    out_d.pop(h, None)
                    if pods_d.pop(h, None) is not None:
                        uid_d.pop(uid, None)

    def restore_window(self, pods: list[Pod]) -> None:
        """Return a popped window to the queue. The native heap assigns
        its own (monotone) sequence numbers, so restored pods re-enter
        at the BACK of their priority class rather than the front —
        priority order is exact, FIFO position among equals is not.
        Callers are the drain path (Scheduler.drain_pipeline, followed
        by a fresh pop or shutdown) and gang deferral
        (Scheduler._defer_gang): a deferred gang re-enters behind
        same-priority arrivals instead of ahead of them, which delays
        its retry but never its correctness. _defer_gang reads
        RESTORES_TO_FRONT and KEEPS the pipelined driver's prefetched
        window on this queue (re-pushing it would put it behind pods
        the serial driver pops later), so serial/pipelined binding
        parity holds on either queue implementation."""
        for pod in pods:
            self.push(pod)

    def pop_window(self, max_pods: int) -> list[Pod]:
        with self._lock:
            handles = self._q.pop_window(max_pods, self._clock())
            pods_d = self._pods
            out_d = self._outstanding
            out = []
            append = out.append
            for h in (
                handles.tolist() if hasattr(handles, "tolist") else handles
            ):
                pod = pods_d.get(h)
                out_d[h] = out_d.get(h, 1) - 1
                if pod is not None:
                    append(pod)
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


def pod_partition_key(pod: Pod) -> str:
    """The partition key: the pod's namespace (tenant boundary). The
    gang identity key is `f"{namespace}/{name}"` (pod_gang above), so
    namespace-keyed partitioning guarantees BY CONSTRUCTION that a gang
    never straddles two partitions — gang atomicity (_defer_gang's
    restore_window dance) stays a single-replica affair."""
    return pod.namespace


def namespace_partition(namespace: str, n_partitions: int) -> int:
    """crc32(namespace) % n — the partition a namespace's pods belong
    to. Exposed for traffic generators / tests that need to TARGET a
    partition (pick a namespace that lands where they want)."""
    if n_partitions <= 1:
        return 0
    return zlib.crc32(namespace.encode("utf-8")) % n_partitions


def pod_partition(pod: Pod, n_partitions: int) -> int:
    """Deterministic partition index in [0, n_partitions): crc32 of the
    namespace, NOT Python's `hash()` — crc32 is stable across processes
    and restarts (hash() is salted per interpreter), so a pod resubmitted
    after a replica crash lands on the same partition and its backoff /
    gang state reconverges instead of forking. The crc is memoized on
    the pod object (immutable spec) like pod_priority; the modulus is
    not, so the same pod re-partitions correctly if the fleet is resized."""
    if n_partitions <= 1:
        return 0
    crc = pod.__dict__.get("_part_crc")
    if crc is None:
        crc = zlib.crc32(pod_partition_key(pod).encode("utf-8"))
        pod.__dict__["_part_crc"] = crc
    return crc % n_partitions


class PartitionedQueue:
    """N independent sub-queues, one per scheduler replica, with pushes
    routed by pod_partition. Each sub-queue is a full SchedulingQueue /
    NativeBackedQueue, so per-partition pop_window / restore_window /
    backoff semantics are EXACTLY the single-queue semantics — gang
    atomicity and the pipelined prefetch slot survive unchanged inside
    a partition, and there is no cross-partition ordering to preserve
    because priorities only ever competed within a tenant's submit
    stream in the first place.

    This class is a router, not a scheduler-facing queue: replicas talk
    to their own partition through a ReplicaCoordinator (host/replica.py)
    and never see the router at pop time."""

    def __init__(
        self,
        n_partitions: int,
        *,
        initial_backoff: float = 1.0,
        max_backoff: float = 10.0,
        prefer_native: bool = True,
        clock=time.monotonic,
    ):
        if n_partitions < 1:
            raise ValueError(f"n_partitions must be >= 1, got {n_partitions}")
        self.n_partitions = n_partitions
        self.partitions = [
            make_queue(
                initial_backoff=initial_backoff,
                max_backoff=max_backoff,
                prefer_native=prefer_native,
                clock=clock,
            )
            for _ in range(n_partitions)
        ]

    def partition_of(self, pod: Pod) -> int:
        return pod_partition(pod, self.n_partitions)

    def push(self, pod: Pod) -> None:
        self.partitions[self.partition_of(pod)].push(pod)

    def partition(self, i: int):
        return self.partitions[i]

    def __len__(self) -> int:
        return sum(len(q) for q in self.partitions)


def make_queue(
    *,
    initial_backoff: float = 1.0,
    max_backoff: float = 10.0,
    prefer_native: bool = True,
    clock=time.monotonic,
):
    """Native queue when the toolchain/library allows, else pure Python."""
    if prefer_native:
        try:
            return NativeBackedQueue(
                initial_backoff=initial_backoff,
                max_backoff=max_backoff,
                clock=clock,
            )
        except (RuntimeError, ImportError):
            pass
    return SchedulingQueue(
        initial_backoff=initial_backoff, max_backoff=max_backoff, clock=clock
    )
