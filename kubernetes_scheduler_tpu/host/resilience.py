"""Unified resilience: deterministic backoff, circuit breaking, and the
degradation ladder.

The system grew a dozen ad-hoc survival paths — scalar fallback,
fetch-failed requeue, resident flush-to-full, capability downgrade,
mirror verify-resync, pipeline flush — each correct alone, none sharing
a retry policy and none owning the question "how degraded are we right
now, and are we climbing back?". This module is the single owner:

- `BackoffPolicy`: exponential backoff with DETERMINISTIC jitter (a
  crc32 hash of (key, attempt) — no RNG, so scenario runs on the
  virtual clock replay bit-for-bit and two hosts never thundering-herd
  in phase).
- `CircuitBreaker`: closed -> open -> half-open with recovery probes.
  Shared by the advisor and bridge paths (host/scheduler.py holds one
  per dependency; bridge/client.RemoteEngine holds its own for the RPC
  surface), so an outage costs ONE probe per recovery window instead
  of a timeout per call.
- `DegradationLadder`: the explicit degradation-ladder state machine —
  one rung set per subsystem (remote->local, resident->full,
  fused->unfused, sharded->dense, mirror->rebuild, policy->scalar),
  each move exactly ONE rung with a recorded reason and entry seq
  (never skips a rung downward silently), recovery only through an
  explicit re-probe, exported as `degradation_rung{subsystem}` and
  journaled through CycleMetrics so chaos runs are replay-pinned like
  everything else. The protocol shape (one-rung demotes, probe-before-
  promote, breaker-open implies a degraded engine rung) is model-
  checked by analysis/model/protocols.py `degradation-ladder`.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from collections import deque
from typing import Callable

log = logging.getLogger("yoda_tpu.resilience")

# ---- deterministic backoff -------------------------------------------------


class BackoffPolicy:
    """Exponential backoff with deterministic jitter.

    delay(attempt) grows `initial * multiplier**attempt` capped at
    `max_delay`, then shaved by up to `jitter_frac` of itself using a
    crc32 hash of (key, attempt) — the jitter de-phases retry storms
    across keys without any RNG, so the same (key, attempt) always
    yields the same delay (scenario determinism; PARITY round 17)."""

    def __init__(
        self,
        *,
        initial: float = 0.5,
        max_delay: float = 8.0,
        multiplier: float = 2.0,
        jitter_frac: float = 0.25,
    ):
        self.initial = float(initial)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter_frac = float(jitter_frac)

    def delay(self, attempt: int, *, key: str = "") -> float:
        base = min(
            self.initial * self.multiplier ** max(0, int(attempt)),
            self.max_delay,
        )
        h = zlib.crc32(f"{key}:{int(attempt)}".encode()) / 2**32
        return base * (1.0 - self.jitter_frac * h)


# ---- circuit breaker -------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """closed -> open -> half-open with single-probe recovery.

    `allow()` answers "may this call go out?": always in CLOSED; in
    OPEN, False until `recovery_window_s` has elapsed, then the breaker
    moves to HALF_OPEN and admits exactly ONE probe; in HALF_OPEN,
    False while that probe is outstanding. `record_success()` closes
    the breaker, `record_failure()` re-opens it (and restarts the
    window) — so a dead dependency costs one probe per window, not a
    timeout per call. The clock is injectable (the scenario harness
    passes the virtual queue clock, making open/half-open transitions
    tick-deterministic)."""

    def __init__(
        self,
        name: str,
        *,
        failure_threshold: int = 3,
        recovery_window_s: float = 8.0,
        clock: Callable[[], float] | None = None,
        on_transition: Callable[[str, str], None] | None = None,
    ):
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.recovery_window_s = float(recovery_window_s)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_outstanding = False
        self._probe_issued_at = 0.0
        # state -> times entered (CLOSED entries = recoveries)
        self.transition_counts: dict[str, int] = {}
        self._on_transition = on_transition

    def _move(self, state: str) -> str:
        """Transition under the lock; returns the new state so the
        caller can fire hooks OUTSIDE the lock."""
        self._state = state
        self.transition_counts[state] = (
            self.transition_counts.get(state, 0) + 1
        )
        return state

    def _fire(self, moved: str | None) -> None:
        if moved is not None and self._on_transition is not None:
            try:
                self._on_transition(self.name, moved)
            except Exception:
                log.exception("breaker %s transition hook failed", self.name)

    def configure(
        self,
        *,
        failure_threshold: int | None = None,
        recovery_window_s: float | None = None,
        clock: Callable[[], float] | None = None,
        on_transition: Callable[[str, str], None] | None = None,
    ) -> "CircuitBreaker":
        """Retune an existing breaker in place — the Scheduler adopts
        an engine-owned breaker (RemoteEngine constructs one per
        target) as THE engine breaker, applying its config knobs,
        clock, and transition hook so one instance governs both the
        dispatch gate and the client's RPC gate."""
        with self._lock:
            if failure_threshold is not None:
                self.failure_threshold = int(failure_threshold)
            if recovery_window_s is not None:
                self.recovery_window_s = float(recovery_window_s)
            if clock is not None:
                self._clock = clock
            if on_transition is not None:
                self._on_transition = on_transition
        return self

    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        moved = None
        with self._lock:
            now = self._clock()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now - self._opened_at >= self.recovery_window_s:
                    moved = self._move(HALF_OPEN)
                    self._probe_outstanding = True
                    self._probe_issued_at = now
                    ok = True
                else:
                    ok = False
            else:  # HALF_OPEN: one probe at a time
                if self._probe_outstanding and (
                    now - self._probe_issued_at < self.recovery_window_s
                ):
                    ok = False
                else:
                    # no probe out — or the outstanding one is a full
                    # recovery window old with no outcome recorded
                    # (leaked: the caller that consumed it never
                    # reached a record_* path). A wedged half-open
                    # would be scalar-forever, so presume the probe
                    # lost and admit a fresh one.
                    self._probe_outstanding = True
                    self._probe_issued_at = now
                    ok = True
        self._fire(moved)
        return ok

    def peek(self) -> bool:
        """allow() without side effects: would a call be admitted right
        now? The scheduler's dispatch gate uses this when the breaker
        is SHARED with the bridge client — the client's allow() at send
        time is the one consuming transition/probe point, and a
        consuming pre-gate would eat the half-open probe the dispatch
        itself is entitled to."""
        with self._lock:
            now = self._clock()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return now - self._opened_at >= self.recovery_window_s
            return not self._probe_outstanding or (
                now - self._probe_issued_at >= self.recovery_window_s
            )

    def record_success(self) -> None:
        moved = None
        with self._lock:
            self._consecutive_failures = 0
            self._probe_outstanding = False
            if self._state != CLOSED:
                moved = self._move(CLOSED)
        self._fire(moved)

    def record_failure(self) -> None:
        moved = None
        with self._lock:
            self._probe_outstanding = False
            self._consecutive_failures += 1
            if self._state == HALF_OPEN or (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                moved = self._move(OPEN)
            elif self._state == OPEN:
                # a failure recorded while already open (a raced probe
                # completing late) restarts the recovery window
                self._opened_at = self._clock()
        self._fire(moved)


# ---- the degradation ladder ------------------------------------------------

# subsystem -> rung names, TOP FIRST. Two-rung ladders today; the demote
# contract (one rung per call, reason + entry seq recorded) is written
# for any depth.
LADDER_RUNGS: dict[str, tuple[str, ...]] = {
    "engine": ("remote", "local"),
    "resident": ("resident", "full"),
    "kernel": ("fused", "unfused"),
    "sharding": ("sharded", "dense"),
    "mirror": ("mirror", "rebuild"),
    "policy": ("policy", "scalar"),
}


class DegradationLadder:
    """The single owner of "how degraded is each subsystem".

    Every subsystem sits on a rung (0 = top). `demote` moves exactly
    ONE rung down, recording the reason and the entry seq — a failure
    path can call it repeatedly but can never silently skip a rung.
    Recovery is two-phase: `probe` marks that the degraded path was
    actually re-attempted, and `promote` climbs one rung only after a
    probe — climbing without re-probing is the bug class the
    `degradation-ladder` protocol model exists to reject. The current
    rung of every subsystem is exported as `degradation_rung{subsystem}`
    (0 = top) and the bounded event log is the chaos-run audit trail;
    the per-cycle `CycleMetrics.degraded` tuple journals the same state
    into the flight recorder."""

    def __init__(self, subsystems: dict[str, tuple[str, ...]] | None = None):
        from kubernetes_scheduler_tpu.host.observe import Gauge

        self._ladders = dict(subsystems or LADDER_RUNGS)
        self._lock = threading.Lock()
        self._rungs = {sub: 0 for sub in self._ladders}
        self._probed = {sub: False for sub in self._ladders}
        self.reasons: dict[str, str] = {}
        self.entry_seq: dict[str, int] = {}
        self.events: deque = deque(maxlen=4096)
        self.gauge = Gauge(
            "degradation_rung",
            "Current degradation-ladder rung per subsystem (0 = top; "
            "higher = more degraded)",
            labels=("subsystem",),
        )
        for sub in self._ladders:
            self.gauge.set(0, subsystem=sub)
        self.collectors = (self.gauge,)

    def rung(self, subsystem: str) -> str:
        with self._lock:
            return self._ladders[subsystem][self._rungs[subsystem]]

    def depth(self, subsystem: str) -> int:
        with self._lock:
            return self._rungs[subsystem]

    def degraded(self) -> tuple[str, ...]:
        """Subsystems currently below their top rung, sorted — the
        per-cycle journal field."""
        with self._lock:
            return tuple(
                sorted(sub for sub, d in self._rungs.items() if d > 0)
            )

    def fully_recovered(self) -> bool:
        with self._lock:
            return all(d == 0 for d in self._rungs.values())

    def _event(self, action, sub, rung, reason, seq):
        self.events.append(
            {
                "action": action, "subsystem": sub, "rung": rung,
                "reason": reason, "seq": int(seq),
            }
        )

    def demote(self, subsystem: str, *, reason: str = "", seq: int = -1) -> bool:
        """One rung down (never more — callers loop if a deeper drop is
        ever warranted, leaving one auditable event per rung). Returns
        False when already at the bottom."""
        with self._lock:
            names = self._ladders[subsystem]
            d = self._rungs[subsystem]
            if d >= len(names) - 1:
                return False
            self._rungs[subsystem] = d + 1
            self._probed[subsystem] = False
            self.reasons[subsystem] = reason
            self.entry_seq[subsystem] = int(seq)
            new_rung = names[d + 1]
            self._event("demote", subsystem, new_rung, reason, seq)
            self.gauge.set(d + 1, subsystem=subsystem)
        log.warning(
            "degradation: %s -> %s (%s, seq=%d)",
            subsystem, new_rung, reason or "-", seq,
        )
        return True

    def probe(self, subsystem: str, *, seq: int = -1) -> bool:
        """Record a recovery probe: the degraded subsystem's better
        path was re-attempted. No-op at the top."""
        with self._lock:
            if self._rungs[subsystem] == 0:
                return False
            self._probed[subsystem] = True
            self._event(
                "probe", subsystem,
                self._ladders[subsystem][self._rungs[subsystem]], "", seq,
            )
            return True

    def promote(self, subsystem: str, *, seq: int = -1) -> bool:
        """One rung up, only after a probe since the last demote — a
        promote with no recorded probe is a caller bug (logged, and the
        climb still requires the probe to be recorded first so the
        event log never shows an un-probed recovery)."""
        with self._lock:
            d = self._rungs[subsystem]
            if d == 0:
                return False
            if not self._probed[subsystem]:
                # recovery must re-probe: record the missing probe and
                # flag the call site rather than silently climbing
                log.warning(
                    "degradation: promote(%s) without a recorded probe "
                    "— recording one (caller should probe first)",
                    subsystem,
                )
                self._event(
                    "probe", subsystem, self._ladders[subsystem][d], "", seq
                )
            self._rungs[subsystem] = d - 1
            self._probed[subsystem] = False
            names = self._ladders[subsystem]
            self._event("promote", subsystem, names[d - 1], "", seq)
            if d - 1 == 0:
                self.reasons.pop(subsystem, None)
                self.entry_seq.pop(subsystem, None)
            self.gauge.set(d - 1, subsystem=subsystem)
        log.info("degradation: %s recovered one rung (seq=%d)", subsystem, seq)
        return True

    def snapshot(self) -> dict:
        """{subsystem: {rung, depth, reason, entry_seq}} — the summary
        surface scenario runs and /metrics debugging read."""
        with self._lock:
            return {
                sub: {
                    "rung": self._ladders[sub][d],
                    "depth": d,
                    "reason": self.reasons.get(sub, ""),
                    "entry_seq": self.entry_seq.get(sub, -1),
                }
                for sub, d in self._rungs.items()
            }
