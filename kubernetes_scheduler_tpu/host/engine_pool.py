"""Fleet-shared device engine: N replicas, ONE resident snapshot.

A ReplicaFleet with private engines pays the cluster state N times — N
device-resident snapshot copies, N uploads per churn event, N kernel
dispatches per round even when every replica is scoring the same
cluster. SharedEnginePool multiplexes every replica's engine traffic
onto ONE Local/Remote engine through per-replica `_EngineView` facades
(the Scheduler's ordinary `engine=` injection seam — schedulers run
unchanged), with two fleet-level levers:

**Upload dedupe (one resident base per fleet).** The pool retains a
host-side COPY of the last snapshot content the inner engine holds
(`_prev`) plus a monotonically fenced epoch. Each dispatch diffs its
snapshot against the base (host.snapshot.snapshot_delta — row values by
content, so the reconstruction is bitwise): an unchanged snapshot ships
a zero-row delta (`upload="dedup"`, ~node_mask bytes), steady-state
churn ships changed rows once per fleet (`upload="delta"`), and
anything delta-inexpressible — layout churn, a replica that raced a
flush, a post-crash resync — transparently falls back to a fenced full
upload (`upload="full"`). The epoch fence is the resident protocol's:
the inner engine folds a delta only at exactly `epoch + 1`
(engine.ResidentState.accepts); any desync degrades to a full upload,
never to stale state.

**Cross-replica dispatch coalescing.** `schedule_batch_async` ENQUEUES
the request and returns an unforced handle; execution happens when any
participant forces a result (or a sync dispatch arrives), and the
executing thread drains EVERYTHING queued by then into coalesced
super-batches — one `schedule_batch_fleet` invocation per group, each
stacked window tagged with its origin view and scored against ITS OWN
snapshot content (the shared base plus that replica's functional
SnapshotDelta, applied inside the program without touching the base).
Results de-multiplex back to each handle, and every replica's
BindTable CAS runs exactly as with private engines — decisions are
bit-identical per replica, so first-bind-wins semantics and union
parity are unchanged (PARITY.md round 20). Windows that arrive while
the device is busy queue behind the executing group and are adopted
before the executor retires — the lost-wakeup-free drain loop — and a
threaded dispatch that would otherwise go out alone waits up to
`coalesce_window_ms` for companions when other fleet threads are
actively dispatching (single-threaded/round-robin drains never wait).

Deferral contract: a view's snapshot/pod arrays must stay unchanged
between its dispatch and its force. The Scheduler's cycle structure
guarantees this — builder and mirror state mutate only in the
completion/finish stages, after the force — and the split-phase fleet
drain (Scheduler.run_cycle_split) dispatches every replica before the
first force for deterministic coalescing under round-robin harnesses.

Failure fan-out: an inner-engine exception while a coalesced group is
in flight is delivered to EVERY participating handle, so each replica
runs its own established fallback chain (scalar re-schedule, breaker
feed, ladder demotion) for its own window — no pod is lost or
double-bound (the BindTable fences re-dispatches exactly like any
other race), and the pool drops its base so the next dispatch re-syncs
with a fenced full upload. Capability state lives in the ONE inner
engine, so a sidecar capability downgrade is relearned once per fleet,
not once per replica.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from kubernetes_scheduler_tpu.host.observe import Counter, Histogram

log = logging.getLogger("yoda_tpu.engine_pool")

# count-valued buckets: "how many windows rode one device dispatch"
COALESCE_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)


def _copy_tree(nt):
    """Pool-owned host copy of a NamedTuple-of-arrays: the base must
    survive in-place mutation of the source arrays (the mirror's
    post-bind self-applies land in the very buffers a snapshot aliased)."""
    return type(nt)(*[np.array(a, copy=True) for a in nt])


def _delta_rows(delta) -> int:
    """Real (non-sentinel) changed rows in a SnapshotDelta — 0 means the
    diff found nothing and the delta is a pure epoch advance."""
    n = int(delta.node_mask.shape[0])
    return (
        int((np.asarray(delta.req_rows) < n).sum())
        + int((np.asarray(delta.util_rows) < n).sum())
        + int((np.asarray(delta.dom_rows) < n).sum())
    )


class _Pending:
    """One enqueued dispatch: inputs captured at enqueue, settled by
    whichever thread ends up executing the drain."""

    __slots__ = (
        "view", "kind", "snapshot", "pods", "kw", "done", "value", "error",
    )

    def __init__(self, view, kind, snapshot, pods, kw):
        self.view = view
        self.kind = kind  # "batch" | "windows"
        self.snapshot = snapshot
        self.pods = pods
        self.kw = kw
        self.done = False
        self.value = None
        self.error = None


class _PoolHandle:
    """Async handle a view hands the scheduler: forcing it makes the
    calling thread the executor for everything queued so far."""

    __slots__ = ("_pool", "_pending")

    def __init__(self, pool, pending):
        self._pool = pool
        self._pending = pending

    def result(self):
        return self._pool._settle(self._pending)


class _EngineView:
    """One replica's engine facade. Presents the plain (non-resident)
    engine surface — `supports_resident()` is False by design, so the
    Scheduler's own resident machinery stays inert and residency is
    managed ONCE at the pool, where the fleet-wide base lives."""

    def __init__(self, pool: "SharedEnginePool", name: str):
        self._pool = pool
        self.name = name
        self.collectors = pool.collectors
        self._closed = False

    def schedule_batch(self, snapshot, pods, **kw):
        return self._pool.dispatch_sync(self, snapshot, pods, kw)

    def schedule_batch_async(self, snapshot, pods, **kw):
        return self._pool.dispatch_async(self, snapshot, pods, kw)

    def schedule_windows(self, snapshot, pods_windows, **kw):
        return self._pool.dispatch_windows(self, snapshot, pods_windows, kw)

    def preempt(self, snapshot, pods, victims, *, k_cap: int):
        return self._pool.preempt(snapshot, pods, victims, k_cap=k_cap)

    def supports_resident(self) -> bool:
        return False

    def supports_windows_resident(self) -> bool:
        return False

    def supports_gangs(self) -> bool:
        inner = self._pool.inner
        sg = getattr(inner, "supports_gangs", None)
        return bool(sg()) if sg is not None else False

    def invalidate_resident(self) -> None:
        self._pool.invalidate()

    def set_trace_id(self, trace_id: int, seq: int = -1) -> None:
        # last-writer-wins across the fleet: sidecar spans attribute to
        # the most recent dispatcher (coalesced groups are one device
        # call serving several trace ids — the pool's counters, not the
        # span join, are the per-replica evidence there)
        st = getattr(self._pool.inner, "set_trace_id", None)
        if st is not None:
            st(trace_id, seq)

    def healthy(self) -> bool:
        h = getattr(self._pool.inner, "healthy", None)
        return bool(h()) if h is not None else True

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._pool._view_closed()


class SharedEnginePool:
    """The fleet-shared engine: build one, hand each replica a
    `view()`, wire the views through the Scheduler's `engine=` seam.
    `inner` defaults to a LocalEngine; pass a RemoteEngine for the
    one-sidecar-per-fleet topology (ONE client session keys ONE
    resident snapshot server-side, and capability latches are learned
    once for the whole fleet)."""

    def __init__(
        self,
        inner=None,
        *,
        coalesce_window_ms: float = 2.0,
        resident: bool = True,
    ):
        if inner is None:
            from kubernetes_scheduler_tpu.engine import LocalEngine

            inner = LocalEngine()
        self.inner = inner
        self.coalesce_window_ms = float(coalesce_window_ms)
        self._resident = bool(resident)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list[_Pending] = []
        self._executing = False
        self._active = 0  # threads currently inside a dispatch/force
        self._prev = None  # pool-owned COPY of the inner resident content
        self._epoch = 0
        self._views: list[_EngineView] = []
        self._open_views = 0
        self._closed = False
        # fleet evidence (plain ints; the shipped metric surface is the
        # three collectors below)
        self.device_dispatches = 0
        self.upload_bytes = {"full": 0, "delta": 0, "dedup": 0}
        # wall time inside _execute: the shared device work a bench can
        # apportion across the participants one fused dispatch served
        self.execute_seconds = 0.0
        self.ctr_coalesced = Counter(
            "coalesced_dispatches_total",
            "Shared-engine device dispatches that carried two or more "
            "replicas' windows in one coalesced super-batch.",
        )
        self.ctr_uploads = Counter(
            "shared_engine_uploads_total",
            "Snapshot uploads through the fleet-shared engine by kind: "
            "full (base resync), delta (changed rows once per fleet), "
            "dedup (zero-row epoch advance — content already resident).",
            labels=("upload",),
        )
        self.hist_batch = Histogram(
            "coalesce_batch_window_count",
            "Windows per shared-engine device dispatch (1 = nothing to "
            "coalesce with).",
            buckets=COALESCE_BUCKETS,
        )
        self.collectors = (
            self.ctr_coalesced, self.ctr_uploads, self.hist_batch,
        )

    # ---- views --------------------------------------------------------

    def view(self, name: str) -> _EngineView:
        v = _EngineView(self, name)
        self._views.append(v)
        self._open_views += 1
        return v

    def _view_closed(self) -> None:
        self._open_views -= 1
        if self._open_views <= 0:
            self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        c = getattr(self.inner, "close", None)
        if c is not None:
            c()

    # ---- dispatch surface --------------------------------------------

    def dispatch_async(self, view, snapshot, pods, kw) -> _PoolHandle:
        p = _Pending(view, "batch", snapshot, pods, dict(kw))
        with self._cond:
            self._pending.append(p)
            self._cond.notify_all()
        return _PoolHandle(self, p)

    def dispatch_sync(self, view, snapshot, pods, kw):
        p = _Pending(view, "batch", snapshot, pods, dict(kw))
        with self._cond:
            self._active += 1
            self._pending.append(p)
            self._cond.notify_all()
        try:
            return self._settle(p, gate=True)
        finally:
            with self._cond:
                self._active -= 1

    def dispatch_windows(self, view, snapshot, pods_windows, kw):
        if not hasattr(self.inner, "schedule_windows"):
            raise NotImplementedError("inner engine lacks schedule_windows")
        p = _Pending(view, "windows", snapshot, pods_windows, dict(kw))
        with self._cond:
            self._pending.append(p)
            self._cond.notify_all()
        return self._settle(p)

    def preempt(self, snapshot, pods, victims, *, k_cap: int):
        # stateless pass-through: the preemption snapshot is an
        # ephemeral build that must never touch the resident base
        with self._cond:
            self.device_dispatches += 1
        return self.inner.preempt(snapshot, pods, victims, k_cap=k_cap)

    def invalidate(self) -> None:
        """Drop the fleet base (engine failure, external resync): the
        next dispatch re-syncs with a fenced full upload."""
        with self._cond:
            self._prev = None
        inv = getattr(self.inner, "invalidate_resident", None)
        if inv is not None:
            try:
                inv()
            except Exception:
                log.debug("inner invalidate_resident failed", exc_info=True)

    # ---- execution ----------------------------------------------------

    def _settle(self, p: _Pending, *, gate: bool = False):
        """Force one pending result. The first forcing thread becomes
        the executor and drains EVERYTHING queued (adopting late
        arrivals before retiring — no lost wakeup); others wait for
        their result to be delivered."""
        with self._cond:
            if (
                gate
                and not p.done
                and not self._executing
                and self.coalesce_window_ms > 0
                and self._active > 1
                and len(self._pending) == 1
            ):
                # threaded lone dispatch with companions en route: give
                # them one short window to land in this super-batch
                self._cond.wait(self.coalesce_window_ms / 1000.0)
            while not p.done:
                if self._executing:
                    self._cond.wait(0.05)
                    continue
                self._executing = True
                try:
                    while self._pending:
                        batch = self._pending
                        self._pending = []
                        self._cond.release()
                        try:
                            self._execute(batch)
                        finally:
                            self._cond.acquire()
                        self._cond.notify_all()
                finally:
                    self._executing = False
                    self._cond.notify_all()
        if p.error is not None:
            raise p.error
        return p.value

    @staticmethod
    def _kw_key(kw: dict):
        try:
            return tuple(sorted(kw.items()))
        except TypeError:
            return None  # unhashable option: schedules alone

    def _resident_ok(self) -> bool:
        if not self._resident:
            return False
        sr = getattr(self.inner, "supports_resident", None)
        try:
            return bool(sr()) if sr is not None else False
        except Exception:
            return False

    def _execute(self, batch: list[_Pending]) -> None:
        """Run one drained batch: windows requests go out individually
        (the backlog scan carries state across its own windows); batch
        requests group by identical engine options and coalesce."""
        t0 = time.perf_counter()
        try:
            self._execute_batch(batch)
            # deliver FORCED results: the executor absorbs the device
            # wall (so execute_seconds measures it) instead of every
            # follower blocking on a future the leader dispatched
            try:
                import jax

                jax.block_until_ready(
                    [p.value for p in batch if p.done and p.error is None]
                )
            except ImportError:
                pass
        finally:
            self.execute_seconds += time.perf_counter() - t0

    def _execute_batch(self, batch: list[_Pending]) -> None:
        groups: list[tuple[object, list[_Pending]]] = []
        by_key: dict = {}
        for p in batch:
            key = self._kw_key(p.kw)
            if p.kind == "windows" or key is None:
                groups.append((None, [p]))
                continue
            g = by_key.get(key)
            if g is None:
                g = []
                by_key[key] = g
                groups.append((key, g))
            g.append(p)
        for _, reqs in groups:
            if reqs[0].kind == "windows":
                self._execute_windows(reqs[0])
            else:
                self._execute_group(reqs)

    def _fail(self, reqs: list[_Pending], e: BaseException) -> None:
        """Deliver one inner-engine failure to every participant and
        drop the base: each replica runs its own fallback/re-dispatch
        for its own window (the BindTable fences the retries), and the
        next dispatch re-syncs with a fenced full upload."""
        with self._cond:
            self._prev = None
        for p in reqs:
            p.error = e
            p.done = True

    def _account(self, kind: str, nbytes: int) -> None:
        self.ctr_uploads.inc(upload=kind)
        self.upload_bytes[kind] += int(nbytes)

    def _classify(self, prev, snapshot):
        """(delta | None, kind, nbytes) of moving the resident content
        from `prev` to `snapshot`: delta=None means full upload."""
        from kubernetes_scheduler_tpu.engine import snapshot_nbytes
        from kubernetes_scheduler_tpu.host.snapshot import snapshot_delta

        if prev is None:
            return None, "full", snapshot_nbytes(snapshot)
        delta = snapshot_delta(prev, snapshot)
        if delta is None:
            return None, "full", snapshot_nbytes(snapshot)
        if _delta_rows(delta) == 0:
            return delta, "dedup", 0
        return delta, "delta", snapshot_nbytes(delta)

    def _execute_group(self, reqs: list[_Pending]) -> None:
        from kubernetes_scheduler_tpu.host.snapshot import snapshot_delta

        inner = self.inner
        n = len(reqs)
        with self._cond:
            self.device_dispatches += 1
        self.hist_batch.observe(float(n))
        if n >= 2:
            self.ctr_coalesced.inc()
        if not self._resident_ok():
            # no resident surface: plain forwarding, full upload each
            # (the inner's own caches may still dedupe bytes)
            for p in reqs:
                if p is not reqs[0]:
                    with self._cond:
                        self.device_dispatches += 1
                try:
                    p.value = inner.schedule_batch(p.snapshot, p.pods, **p.kw)
                    p.done = True
                    self._account("full", 0)
                except Exception as e:  # fan out to the rest
                    self._fail([q for q in reqs if not q.done], e)
                    return
            return
        # resident path: advance the base to the first request's
        # snapshot, then ride every other request as a functional delta
        # against it inside ONE schedule_batch_fleet invocation
        base_req = reqs[0]
        base = base_req.snapshot
        base_delta, base_kind, base_bytes = self._classify(self._prev, base)
        elements = [(None, base_req)]
        tail: list[_Pending] = []
        accounts = [(base_kind, base_bytes)]
        for p in reqs[1:]:
            d = snapshot_delta(base, p.snapshot)
            if d is None:
                # delta-inexpressible divergence (layout/shape churn):
                # this request re-syncs as its own base afterwards
                tail.append(p)
                continue
            if _delta_rows(d) == 0:
                elements.append((None, p))
                accounts.append(("dedup", 0))
            else:
                from kubernetes_scheduler_tpu.engine import snapshot_nbytes

                elements.append((d, p))
                accounts.append(("delta", snapshot_nbytes(d)))
        epoch = self._epoch + 1
        try:
            if len(elements) == 1:
                results = [
                    inner.schedule_resident(
                        base, base_req.pods,
                        delta=base_delta, epoch=epoch, **base_req.kw
                    )
                ]
            elif hasattr(inner, "schedule_batch_fleet"):
                results = list(
                    inner.schedule_batch_fleet(
                        base,
                        [(d, p.pods) for d, p in elements],
                        delta=base_delta, epoch=epoch, **base_req.kw
                    )
                )
            else:
                # resident-capable inner without the fleet surface
                # (remote sidecar): chain sequential resident calls —
                # N RPCs, but the uploads stay deduped
                results = []
                content = None
                eph = epoch
                for i, (_, p) in enumerate(elements):
                    if i == 0:
                        d, content = base_delta, base
                    else:
                        d = snapshot_delta(content, p.snapshot)
                        content = p.snapshot
                        with self._cond:
                            self.device_dispatches += 1
                    results.append(
                        inner.schedule_resident(
                            p.snapshot if i else base, p.pods,
                            delta=d, epoch=eph, **p.kw
                        )
                    )
                    eph += 1
                epoch = eph - 1
                base = content
        except Exception as e:
            self._fail([q for q in reqs if not q.done], e)
            return
        self._epoch = epoch
        # the base content the inner now retains — copied, because the
        # source arrays belong to a replica's builder/mirror and mutate
        # in place after its force
        if base_kind != "dedup" or self._prev is None:
            with self._cond:
                self._prev = _copy_tree(base)
        for (kind, nbytes), (_, p), res in zip(accounts, elements, results):
            self._account(kind, nbytes)
            p.value = res
            p.done = True
        if tail:
            self._execute_group(tail)

    def _execute_windows(self, p: _Pending) -> None:
        inner = self.inner
        with self._cond:
            self.device_dispatches += 1
        self.hist_batch.observe(1.0)
        try:
            swr = getattr(inner, "supports_windows_resident", None)
            if (
                self._resident_ok()
                and swr is not None
                and swr()
                and hasattr(inner, "schedule_windows_resident")
            ):
                delta, kind, nbytes = self._classify(self._prev, p.snapshot)
                epoch = self._epoch + 1
                p.value = inner.schedule_windows_resident(
                    p.snapshot, p.pods, delta=delta, epoch=epoch, **p.kw
                )
                self._epoch = epoch
                if kind != "dedup" or self._prev is None:
                    with self._cond:
                        self._prev = _copy_tree(p.snapshot)
                self._account(kind, nbytes)
            else:
                p.value = inner.schedule_windows(p.snapshot, p.pods, **p.kw)
                self._account("full", 0)
            p.done = True
        except Exception as e:
            self._fail([p], e)

    # ---- evidence -----------------------------------------------------

    def stats(self) -> dict:
        """The fleet-shared engine numbers the bench/scenario harnesses
        assert on."""
        return {
            "device_dispatches": self.device_dispatches,
            "coalesced_dispatches": int(self.ctr_coalesced.total()),
            "uploads": {
                kind: int(self.ctr_uploads.value(upload=kind))
                for kind in ("full", "delta", "dedup")
            },
            "upload_bytes": dict(self.upload_bytes),
            "execute_seconds": round(self.execute_seconds, 4),
            "epoch": self._epoch,
        }
