"""Metrics advisor: the node-utilization sensory input.

Keeps the reference's design (pkg/yoda/advisor/advisor.go) — five PromQL
instant queries joined by hostname into one record per node — but fixes
its pathologies:

- the Prometheus host is configuration, not a hard-coded constant
  (advisor.go:15);
- one fetch per scheduling cycle for the whole batch, not 5 HTTP calls per
  (pod, node) score invocation (scheduler.go:126 calls res.Init() per
  node);
- the result is a dense array block ready for device upload, not a
  map walked per node;
- transport is injectable, so tests run hermetically (the reference's
  tests hit the production endpoints, advisor_test.go:8-18).

Join semantics preserved: series keyed by `kubernetes_io_hostname` with
`instance` as fallback (advisor.go:199-202); nodes missing from a series
keep zeros rather than failing the cycle (advisor.go:190,213 skip
silently); network-IO fetch errors degrade to zeros instead of failing
scheduling (advisor.go:219,242 swallow errors).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Callable

# The five instant queries, functionally equivalent to advisor.go:16-20:
# per-node CPU%, memory%, disk-IO MB/s, network transmit/receive MB/s.
PROM_QUERIES = {
    "cpu_pct": (
        'sum by (kubernetes_io_hostname, instance)'
        '(rate(container_cpu_usage_seconds_total{image!="",pod!=""}[1m]) * 100)'
    ),
    "mem_pct": (
        "(node_memory_MemTotal_bytes-node_memory_MemFree_bytes"
        "-node_memory_Buffers_bytes-node_memory_Cached_bytes)"
        '/node_memory_MemTotal_bytes{kubernetes_io_hostname!=""} * 100'
    ),
    "disk_io": (
        '(rate(node_disk_read_bytes_total{device="vda"}[1m]) '
        '+ rate(node_disk_written_bytes_total{device="vda"}[1m])) /1024/1024'
    ),
    "net_up": (
        "sum by (kubernetes_io_hostname,instance) "
        '(rate (node_network_transmit_bytes_total{kubernetes_io_hostname!=""}[1m]))'
        "/1024/1024"
    ),
    "net_down": (
        "sum by (kubernetes_io_hostname,instance)"
        '(rate (node_network_receive_bytes_total{kubernetes_io_hostname!=""}[1m]))'
        "/1024/1024"
    ),
}

# net_up/net_down failures degrade to zeros (advisor.go:219,242); the other
# three fail the cycle like the reference's PreScore error path
# (scheduler.go:106-109).
SOFT_FAIL_SERIES = {"net_up", "net_down"}


@dataclass
class NodeUtil:
    cpu_pct: float = 0.0
    mem_pct: float = 0.0
    disk_io: float = 0.0
    net_up: float = 0.0
    net_down: float = 0.0


Transport = Callable[[str, dict], dict]


def _util_tuple(u: NodeUtil) -> tuple:
    """Value identity of one node's utilization record — the coalescing
    comparisons must see in-place NodeUtil mutation, so they compare
    values, never object identity."""
    return (u.disk_io, u.cpu_pct, u.mem_pct, u.net_up, u.net_down)


def util_delta(last: dict, snap: dict[str, NodeUtil]) -> dict[str, NodeUtil]:
    """Changed-node diff of a utilization snapshot against `last` (a
    {name: value-tuple} map, UPDATED in place): nodes whose series moved
    since the previous call, plus nodes that vanished (reported as a
    zeros record — the builder's missing-node semantics). The shared
    body of every fetch_changed implementation."""
    changed: dict[str, NodeUtil] = {}
    for name, u in snap.items():
        t = _util_tuple(u)
        if last.get(name) != t:
            changed[name] = u
            last[name] = t
    if len(last) > len(snap):
        for name in [k for k in last if k not in snap]:
            del last[name]
            changed[name] = NodeUtil()
    return changed


class CoalescingAdvisor:
    """Changed-only fetch over any advisor: `fetch_changed()` returns
    {node: NodeUtil} for nodes whose series moved since the previous
    call (first call returns everything), feeding the snapshot mirror's
    utilization events (host/mirror.py) so an idle cluster's state
    fetch applies ZERO rows. The diff itself is O(nodes) of tuple
    compares per call — advisors that can do better (BackgroundAdvisor
    diffs in its refresh thread; bench churn advisors know exactly what
    they perturbed) expose their own fetch_changed and are not wrapped
    (Scheduler wraps only advisors lacking the surface). Unknown
    attributes (stale_served, close) delegate to the inner advisor so
    exporters keep reading through the wrapper."""

    def __init__(self, inner):
        self.inner = inner
        self._last: dict[str, tuple] = {}

    def fetch(self) -> dict[str, NodeUtil]:
        return self.inner.fetch()

    def fetch_changed(self) -> dict[str, NodeUtil]:
        return util_delta(self._last, self.inner.fetch())

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _urllib_transport(url: str, form: dict) -> dict:
    data = urllib.parse.urlencode(form).encode()
    with urllib.request.urlopen(url, data=data, timeout=10) as resp:
        return json.load(resp)


class PrometheusAdvisor:
    """Scrapes the five series and joins them into {node: NodeUtil}."""

    def __init__(self, host: str, *, transport: Transport | None = None):
        self.host = host
        self.transport = transport or _urllib_transport

    def _fetch_series(self, query: str) -> dict[str, float]:
        payload = self.transport(
            f"http://{self.host}/api/v1/query", {"query": query}
        )
        out: dict[str, float] = {}
        for item in payload.get("data", {}).get("result", []):
            metric = item.get("metric", {})
            # join key: kubernetes_io_hostname, falling back to instance
            key = metric.get("kubernetes_io_hostname") or metric.get("instance")
            if not key:
                continue
            value = item.get("value", [None, None])[1]
            try:
                out[key] = float(value)
            except (TypeError, ValueError):
                continue
        return out

    def fetch(self) -> dict[str, NodeUtil]:
        series: dict[str, dict[str, float]] = {}
        for name, query in PROM_QUERIES.items():
            try:
                series[name] = self._fetch_series(query)
            except Exception:
                if name in SOFT_FAIL_SERIES:
                    series[name] = {}
                else:
                    raise
        nodes: dict[str, NodeUtil] = {}
        for name, values in series.items():
            for host, v in values.items():
                nodes.setdefault(host, NodeUtil())
                setattr(nodes[host], name, v)
        return nodes


@dataclass
class StaticAdvisor:
    """Hermetic advisor for tests and simulation."""

    utils: dict[str, NodeUtil] = field(default_factory=dict)

    def fetch(self) -> dict[str, NodeUtil]:
        return self.utils


class BackgroundAdvisor:
    """Cycle-path decoupled advisor: a daemon thread refreshes the inner
    advisor every `interval` seconds and fetch() returns the latest
    snapshot WITHOUT blocking the scheduling cycle on the five
    Prometheus HTTP round-trips. The reference pays those round-trips
    inside the scheduling cycle itself (advisor.Result.Init() from
    PreScore, scheduler.go:104,126 + advisor.go:149-265), and so did
    this host's direct wiring — at a 100ms Prometheus RTT that is most
    of a cycle's latency budget.

    Degradation contract: a snapshot older than `max_staleness` is not
    served. fetch() then falls through to ONE synchronous inner fetch
    (covering startup and advisor recovery); if that raises, the
    exception propagates so Scheduler.run_cycle's fetch-failure path
    requeues the window — exactly the direct wiring's outage behavior,
    just `max_staleness` later. `stale_served` counts fetches served a
    snapshot older than TWICE the refresh interval (one interval of
    slack covers the healthy gap between a scrape completing and the
    next starting) — exported as advisor_stale_served_total.

    The refresh thread starts LAZILY on the first fetch(): an HA standby
    replica constructs the advisor and then blocks waiting for
    leadership without running cycles — it must not scrape Prometheus
    for its whole standby life (the direct wiring only scraped inside
    cycles).
    """

    def __init__(
        self,
        inner,
        *,
        interval: float = 5.0,
        max_staleness: float = 60.0,
        clock: Callable[[], float] | None = None,
        start_thread: bool = True,
    ):
        if float(interval) > float(max_staleness):
            # a budget below the refresh period would put every fetch on
            # the synchronous fallback path WHILE the thread scrapes
            # redundantly — strictly worse than direct wiring
            raise ValueError(
                f"refresh interval ({interval}s) must not exceed "
                f"max_staleness ({max_staleness}s)"
            )
        self.inner = inner
        self.interval = float(interval)
        self.max_staleness = float(max_staleness)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._snap: dict[str, NodeUtil] | None = None
        self._ts: float = float("-inf")
        # changed-node coalescing (fetch_changed): the refresh thread
        # diffs each scrape against the last-seen value map and
        # accumulates the changed records, so the CYCLE path drains them
        # in O(changed) — an idle cluster's state fetch applies nothing
        self._last_tuples: dict[str, tuple] = {}
        self._pending_changed: dict[str, NodeUtil] = {}
        self.stale_served = 0
        self._stop = threading.Event()
        # serializes scrapes: the cycle-path staleness fallback must
        # never run a second set of the five PromQL queries concurrently
        # with the refresh thread's — doubling load on a Prometheus that
        # is already struggling is exactly the wrong failure response
        self._refresh_lock = threading.Lock()
        self._thread = None
        self._want_thread = bool(start_thread)

    def _ensure_thread(self) -> None:
        if not self._want_thread or self._thread is not None:
            return
        with self._lock:
            if self._thread is None and not self._stop.is_set():
                self._thread = threading.Thread(
                    target=self._run, name="advisor-refresh", daemon=True
                )
                self._thread.start()

    def _store(self, snap: dict[str, NodeUtil]) -> None:
        """Adopt one fresh scrape: diff against the last-seen values
        (off the cycle path when called from the refresh thread) and
        accumulate the changed records for fetch_changed."""
        with self._lock:
            self._pending_changed.update(util_delta(self._last_tuples, snap))
            self._snap = snap
            self._ts = self._clock()

    def _refresh_once(self) -> None:
        with self._refresh_lock:
            self._store(self.inner.fetch())

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._refresh_once()
            except Exception:
                # keep serving the last snapshot inside the staleness
                # budget; fetch() surfaces the outage when it expires
                pass
            self._stop.wait(self.interval)

    def fetch(self) -> dict[str, NodeUtil]:
        self._ensure_thread()
        now = self._clock()
        with self._lock:
            snap, ts = self._snap, self._ts
        if snap is not None and now - ts <= self.max_staleness:
            if now - ts > 2 * self.interval:
                self.stale_served += 1
            return snap
        # no usable snapshot (startup, or the refresher has been failing
        # past the budget): one synchronous attempt, errors propagating.
        # Serialized with the refresh thread — and re-checked after
        # taking the scrape lock, because the scrape we were about to
        # duplicate may have just landed
        with self._refresh_lock:
            now = self._clock()
            with self._lock:
                snap, ts = self._snap, self._ts
            if snap is not None and now - ts <= self.max_staleness:
                return snap
            inner_snap = self.inner.fetch()
            self._store(inner_snap)
            return inner_snap

    def fetch_changed(self) -> dict[str, NodeUtil]:
        """Changed-node records since the previous fetch_changed call —
        the snapshot mirror's utilization event feed. Same staleness/
        outage contract as fetch() (it runs first); the drain itself is
        O(changed): idle cycles return {} on one dict swap."""
        self.fetch()
        with self._lock:
            out = self._pending_changed
            self._pending_changed = {}
        return out

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
