"""Metrics advisor: the node-utilization sensory input.

Keeps the reference's design (pkg/yoda/advisor/advisor.go) — five PromQL
instant queries joined by hostname into one record per node — but fixes
its pathologies:

- the Prometheus host is configuration, not a hard-coded constant
  (advisor.go:15);
- one fetch per scheduling cycle for the whole batch, not 5 HTTP calls per
  (pod, node) score invocation (scheduler.go:126 calls res.Init() per
  node);
- the result is a dense array block ready for device upload, not a
  map walked per node;
- transport is injectable, so tests run hermetically (the reference's
  tests hit the production endpoints, advisor_test.go:8-18).

Join semantics preserved: series keyed by `kubernetes_io_hostname` with
`instance` as fallback (advisor.go:199-202); nodes missing from a series
keep zeros rather than failing the cycle (advisor.go:190,213 skip
silently); network-IO fetch errors degrade to zeros instead of failing
scheduling (advisor.go:219,242 swallow errors).
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Callable

# The five instant queries, functionally equivalent to advisor.go:16-20:
# per-node CPU%, memory%, disk-IO MB/s, network transmit/receive MB/s.
PROM_QUERIES = {
    "cpu_pct": (
        'sum by (kubernetes_io_hostname, instance)'
        '(rate(container_cpu_usage_seconds_total{image!="",pod!=""}[1m]) * 100)'
    ),
    "mem_pct": (
        "(node_memory_MemTotal_bytes-node_memory_MemFree_bytes"
        "-node_memory_Buffers_bytes-node_memory_Cached_bytes)"
        '/node_memory_MemTotal_bytes{kubernetes_io_hostname!=""} * 100'
    ),
    "disk_io": (
        '(rate(node_disk_read_bytes_total{device="vda"}[1m]) '
        '+ rate(node_disk_written_bytes_total{device="vda"}[1m])) /1024/1024'
    ),
    "net_up": (
        "sum by (kubernetes_io_hostname,instance) "
        '(rate (node_network_transmit_bytes_total{kubernetes_io_hostname!=""}[1m]))'
        "/1024/1024"
    ),
    "net_down": (
        "sum by (kubernetes_io_hostname,instance)"
        '(rate (node_network_receive_bytes_total{kubernetes_io_hostname!=""}[1m]))'
        "/1024/1024"
    ),
}

# net_up/net_down failures degrade to zeros (advisor.go:219,242); the other
# three fail the cycle like the reference's PreScore error path
# (scheduler.go:106-109).
SOFT_FAIL_SERIES = {"net_up", "net_down"}


@dataclass
class NodeUtil:
    cpu_pct: float = 0.0
    mem_pct: float = 0.0
    disk_io: float = 0.0
    net_up: float = 0.0
    net_down: float = 0.0


Transport = Callable[[str, dict], dict]


def _urllib_transport(url: str, form: dict) -> dict:
    data = urllib.parse.urlencode(form).encode()
    with urllib.request.urlopen(url, data=data, timeout=10) as resp:
        return json.load(resp)


class PrometheusAdvisor:
    """Scrapes the five series and joins them into {node: NodeUtil}."""

    def __init__(self, host: str, *, transport: Transport | None = None):
        self.host = host
        self.transport = transport or _urllib_transport

    def _fetch_series(self, query: str) -> dict[str, float]:
        payload = self.transport(
            f"http://{self.host}/api/v1/query", {"query": query}
        )
        out: dict[str, float] = {}
        for item in payload.get("data", {}).get("result", []):
            metric = item.get("metric", {})
            # join key: kubernetes_io_hostname, falling back to instance
            key = metric.get("kubernetes_io_hostname") or metric.get("instance")
            if not key:
                continue
            value = item.get("value", [None, None])[1]
            try:
                out[key] = float(value)
            except (TypeError, ValueError):
                continue
        return out

    def fetch(self) -> dict[str, NodeUtil]:
        series: dict[str, dict[str, float]] = {}
        for name, query in PROM_QUERIES.items():
            try:
                series[name] = self._fetch_series(query)
            except Exception:
                if name in SOFT_FAIL_SERIES:
                    series[name] = {}
                else:
                    raise
        nodes: dict[str, NodeUtil] = {}
        for name, values in series.items():
            for host, v in values.items():
                nodes.setdefault(host, NodeUtil())
                setattr(nodes[host], name, v)
        return nodes


@dataclass
class StaticAdvisor:
    """Hermetic advisor for tests and simulation."""

    utils: dict[str, NodeUtil] = field(default_factory=dict)

    def fetch(self) -> dict[str, NodeUtil]:
        return self.utils
