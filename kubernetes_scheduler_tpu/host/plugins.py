"""Extension-point plugin surface + the scalar fallback scoring path.

The reference implements six scheduling-framework extension points
(pkg/yoda/scheduler.go:26-31: PreFilter, Filter, PreScore, Score,
NormalizeScore via ScoreExtensions, PreBind). This module keeps that
surface — so behavior stays auditable hook-by-hook against the reference —
and provides `ScalarYodaPlugin`, a pure-Python implementation with the
same per-pod/per-node call pattern. It is the `TPUBatchScore=false`
fallback: no device, no batching, same answers; its per-cycle statistics
memoization uses CycleCache where the reference used Redis.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Protocol

from kubernetes_scheduler_tpu.host.advisor import NodeUtil
from kubernetes_scheduler_tpu.host.cache import CycleCache
from kubernetes_scheduler_tpu.host.snapshot import parse_float_or_zero, pod_resource_request
from kubernetes_scheduler_tpu.host.types import Node, Pod

MAX_NODE_SCORE = 100.0


@dataclass
class CycleState:
    """Per-pod scratch, the framework.CycleState analog (scheduler.go:105)."""

    data: dict = field(default_factory=dict)

    def write(self, key, value):
        self.data[key] = value

    def read(self, key):
        return self.data[key]


class SchedulerPlugin(Protocol):
    def pre_filter(self, state: CycleState, pod: Pod) -> None: ...
    def filter(self, state: CycleState, pod: Pod, node: Node) -> bool: ...
    def pre_score(self, state: CycleState, pod: Pod, nodes: list[Node]) -> None: ...
    def score(self, state: CycleState, pod: Pod, node: Node) -> float: ...
    def normalize_scores(
        self, state: CycleState, pod: Pod, scores: dict[str, float]
    ) -> dict[str, float]: ...
    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> None: ...


# card-metric weights mirroring ops/score.py (algorithm.go:24-35), in the
# snapshot builder's metric order (bandwidth, clock, core, power,
# free_memory, total_memory)
_CARD_WEIGHTS = (1.0, 1.0, 2.0, 1.0, 3.0, 1.0)
_CARD_METRICS = ("bandwidth", "clock", "core", "power", "free_memory", "total_memory")
# free-capacity weights (algorithm.go:178-198): diskIO, cpu, memory
_FC_DISK_W, _FC_CPU_W, _FC_MEM_W = 100.0, 2.0, 3.0

# policies the scalar path scores faithfully; anything else falls back to
# the yoda formula and bumps fallback_policy_mismatch (host/scheduler) —
# with every heuristic policy mirrored, `learned` is the only policy
# with no scalar equivalent (its scores live in device parameters)
SCALAR_POLICIES = (
    "balanced_cpu_diskio",
    "balanced_diskio",
    "free_capacity",
    "card",
    "least_allocated",
    "balanced_allocation",
    "image_locality",
)
# mirrors engine.PRESCALED_PLUGINS (kept import-free here so the scalar
# fallback never pulls jax; tests pin the two tuples equal): plugins whose
# raw output is already on the [0, 100] MaxNodeScore scale — the weighted
# combination min-max normalizes everything else per pod, like the
# upstream framework runtime
PRESCALED_SCALAR = (
    "least_allocated", "balanced_allocation", "image_locality",
    "balanced_diskio",
)
# ImageLocality ramp (mirrors ops/score.py)
_IMG_MIN = 23.0 * 1024 * 1024
_IMG_MAX = 1000.0 * 1024 * 1024


def gpu_demands(pod: Pod) -> tuple[int, float, float]:
    """(want_number, want_memory, want_clock) from the scv labels, exactly
    as host/snapshot.build_pod_batch encodes them — parse_int_or_zero
    strconv semantics included (an unparsable "2.5" means 0, not 2): -1 =
    label absent; a pod with any scv demand label but no explicit number
    wants 1 card."""
    from kubernetes_scheduler_tpu.host.snapshot import parse_int_or_zero

    labels = pod.labels
    has_gpu = any(k in labels for k in ("scv/number", "scv/memory", "scv/clock"))
    if not has_gpu:
        return 0, -1.0, -1.0
    want_n = (
        parse_int_or_zero(labels["scv/number"])
        if "scv/number" in labels
        else 1
    )
    want_mem = (
        float(parse_int_or_zero(labels["scv/memory"]))
        if "scv/memory" in labels
        else -1.0
    )
    want_clock = (
        float(parse_int_or_zero(labels["scv/clock"]))
        if "scv/clock" in labels
        else -1.0
    )
    return want_n, want_mem, want_clock


def card_fit_node(node: Node, want_n: int, want_mem: float, want_clock: float) -> bool:
    """Scalar mirror of feasibility.card_fit's node predicate
    (filter.go:11-58): number / memory / clock demands with the health
    gate and the ==-vs->= clock quirk."""
    if want_n == 0:
        return True
    cards = node.cards
    if want_n > len(cards):
        return False
    healthy = [c for c in cards if c.health == "Healthy"]
    if want_mem >= 0 and sum(1 for c in healthy if c.free_memory >= want_mem) < want_n:
        return False
    if want_clock >= 0 and sum(1 for c in healthy if c.clock == want_clock) < want_n:
        return False
    return True


class ScalarYodaPlugin:
    """The reference's plugin behavior, hook for hook, without the network.

    - pre_filter / filter: log-only pass-through (scheduler.go:91-99 —
      every node passes; real filtering happens in the engine path) —
      except under policy="card", where filter applies the GPU-card
      predicates so fallback decisions match the engine's card path.
    - pre_score: advisor snapshot into CycleState + cache flush
      (scheduler.go:101-113).
    - score: per-cycle statistics computed once and memoized (the
      algorithm.go:47-97 structure, with CycleCache replacing Redis) then
      the live BalancedCpuDiskIO formula (algorithm.go:99-119). The
      `policy` knob swaps in the scalar mirrors of the engine's
      free_capacity (algorithm.go:178-198), card
      (algorithm.go:264-291 + collection.go:30-55) and balanced_diskio
      (algorithm.go:121-176) kernels, so an engine failure under any
      heuristic policy degrades to the SAME policy, not silently to the
      yoda formula; `learned` is the only remaining mismatch case.
    - normalize_scores: min-max to [0, 100] with the highest==lowest guard
      (scheduler.go:158-183).
    - pre_bind: snapshot existence check (scheduler.go:189-196).
    """

    def __init__(
        self,
        utils: dict[str, NodeUtil],
        *,
        truncate: bool = True,
        policy: str = "balanced_cpu_diskio",
        score_plugins: tuple | None = None,
    ):
        if score_plugins:
            bad = [n for n, _ in score_plugins if n not in SCALAR_POLICIES]
            if bad:
                raise ValueError(
                    f"scalar path cannot score plugins {bad}; "
                    f"supported: {SCALAR_POLICIES}"
                )
        elif policy not in SCALAR_POLICIES:
            raise ValueError(
                f"scalar path cannot score policy {policy!r}; "
                f"supported: {SCALAR_POLICIES}"
            )
        self.utils = utils
        self.cache = CycleCache()
        self.truncate = truncate
        self.policy = policy
        # weighted multi-plugin mode (engine.combine_scores mirror):
        # ((name, weight), ...) — scores become the framework's weighted
        # sum; pass truncate=False for exact engine parity (the engine's
        # yoda term never truncates)
        self.score_plugins = tuple(score_plugins or ())

    def pre_filter(self, state, pod):
        return None

    def filter(self, state, pod, node):
        if self.policy == "card":
            return card_fit_node(node, *gpu_demands(pod))
        return True

    def pre_score(self, state, pod, nodes):
        self.cache.flush()
        state.write("nodeInfo", {n.name: self.utils.get(n.name, NodeUtil()) for n in nodes})

    def _ensure_stats(self, state, nodes: list[Node]):
        if "U-AVG" in self.cache:
            return
        info = state.read("nodeInfo")
        u_sum = 0.0
        for n in nodes:
            u = info[n.name].disk_io / 50.0
            v = info[n.name].cpu_pct / 100.0
            self.cache.set(f"U-{n.name}", u)
            self.cache.set(f"V-{n.name}", v)
            u_sum += u
        u_avg = u_sum / len(nodes)
        m_tmp = sum(
            (self.cache.get(f"U-{n.name}") - u_avg) ** 2 for n in nodes
        ) / len(nodes)
        self.cache.set("U-AVG", u_avg)
        self.cache.set("M-tmp", m_tmp)
        self.cache.set("nodeLen", len(nodes))

    def _free_capacity_score(self, node: Node) -> float:
        """Scalar ops/score.free_capacity (CalculateBasicScore2,
        algorithm.go:178-198): 100*(100-floor(DiskIO)) + 2*(100-Cpu) +
        3*(100-Memory)."""
        u = self.utils.get(node.name, NodeUtil())
        return (
            _FC_DISK_W * (100.0 - math.floor(u.disk_io))
            + _FC_CPU_W * (100.0 - u.cpu_pct)
            + _FC_MEM_W * (100.0 - u.mem_pct)
        )

    def _card_score(self, pod: Pod, node: Node, nodes: list[Node]) -> float:
        """Scalar ops/score.card_score + ops/collect.collect_max_card_values:
        per fitting card, sum weight_k * metric_k * 100 / max_k, maxima
        collected over fitting cards of card-fitting nodes, seeded at 1
        (collection.go:31-38). Mirrors the engine's scoring-fit quirk:
        free_memory >= demand AND clock >= demand, no health gate."""
        want_n, want_mem, want_clock = gpu_demands(pod)
        mem = max(want_mem, 0.0)
        clk = max(want_clock, 0.0)

        def fits_for_score(c):
            return c.free_memory >= mem and c.clock >= clk

        maxima = self.cache.get("CARD-MAX")
        if maxima is None:
            maxima = [1.0] * 6
            for nd in nodes:
                if not card_fit_node(nd, want_n, want_mem, want_clock):
                    continue
                for c in nd.cards:
                    if fits_for_score(c):
                        for k, metric in enumerate(_CARD_METRICS):
                            maxima[k] = max(maxima[k], float(getattr(c, metric)))
            self.cache.set("CARD-MAX", maxima)
        total = 0.0
        for c in node.cards:
            if fits_for_score(c):
                total += sum(
                    _CARD_WEIGHTS[k] * float(getattr(c, metric)) * 100.0 / maxima[k]
                    for k, metric in enumerate(_CARD_METRICS)
                )
        return total

    def _balanced_diskio_score(self, state, pod, node, nodes: list[Node]) -> float:
        """Scalar ops/score.balanced_diskio (BalancedDiskIOPriority,
        algorithm.go:121-176): variance-minimization Mj per node, min-max
        rescaled to [0, 100] with the reference's sentinel seeds
        (M_max starts at 0, M_min at 1e6, algorithm.go:122-123) and the
        engine's zero-denominator guard. Whole vector computed once per
        pod, memoized under S- keys like the live formula."""
        memo = self.cache.get(f"S-{node.name}")
        if memo is not None:
            return memo
        scores = self._balanced_diskio_vector(state, pod, nodes)
        result = 0.0
        for nd, s in zip(nodes, scores):
            self.cache.set(f"S-{nd.name}", s)
            if nd.name == node.name:
                result = s
        return result

    def _balanced_diskio_vector(self, state, pod, nodes) -> list[float]:
        self._ensure_stats(state, nodes)
        info = state.read("nodeInfo")
        r_io = parse_float_or_zero(pod.annotations.get("diskIO"))
        n = len(nodes)
        u_avg = self.cache.get("U-AVG")
        m_tmp = self.cache.get("M-tmp")
        ms = []
        for nd in nodes:
            uj = self.cache.get(f"U-{nd.name}")
            fj = (info[nd.name].disk_io + r_io) / 100.0
            f_avg = u_avg - (uj - fj) / n
            ms.append(m_tmp - ((uj - u_avg) ** 2 - (fj - f_avg) ** 2) / n)
        m_max = max(0.0, max(ms))
        m_min = min(1.0e6, min(ms))
        denom = (m_max - m_min) or 1.0
        return [100.0 - 100.0 * (mj - m_min) / denom for mj in ms]

    # ---- upstream resource-shape scorers (ops/score.py mirrors) -------

    def _used_after(self, pod: Pod, node: Node, free, res: str) -> float:
        """alloc - free + this pod's request for `res` (NonZeroRequested
        semantics — `free` was accumulated with the same defaults)."""
        alloc = node.allocatable.get(res, 0.0)
        node_free = free[node.name].get(res, alloc) if free else alloc
        return alloc - node_free + pod_resource_request(pod, res)

    def _least_allocated_score(self, pod, node, free) -> float:
        total = 0.0
        for res in ("cpu", "memory"):
            alloc = node.allocatable.get(res, 0.0)
            used = self._used_after(pod, node, free, res)
            if alloc > 0 and used <= alloc:
                total += (alloc - used) * MAX_NODE_SCORE / alloc
        return total / 2.0

    def _balanced_allocation_score(self, pod, node, free) -> float:
        fracs = []
        for res in ("cpu", "memory"):
            alloc = node.allocatable.get(res, 0.0)
            if alloc <= 0:
                return 0.0
            fracs.append(self._used_after(pod, node, free, res) / alloc)
        if any(f >= 1.0 for f in fracs):
            return 0.0
        return (1.0 - abs(fracs[0] - fracs[1])) * MAX_NODE_SCORE

    def _image_holders(self, nodes) -> dict:
        """Image -> node count, memoized on the node LIST identity (not
        the per-pod CycleCache, which flushes between pods — the map
        depends only on the nodes and must survive the window)."""
        memo = getattr(self, "_holders_memo", None)
        if memo is not None and memo[0] is nodes:
            return memo[1]
        holders: dict = {}
        for nd in nodes:
            for img in nd.images:
                holders[img] = holders.get(img, 0) + 1
        self._holders_memo = (nodes, holders)
        return holders

    def _image_locality_score(self, pod, node, nodes) -> float:
        total_nodes = max(len(nodes), 1)
        holders = self._image_holders(nodes)
        total = 0.0
        for c in pod.containers:
            if c.image and c.image in node.images:
                total += node.images[c.image] * holders[c.image] / total_nodes
        n_c = max(len(pod.containers), 1)
        lo, hi = _IMG_MIN * n_c, _IMG_MAX * n_c
        return min(max((total - lo) / (hi - lo), 0.0), 1.0) * MAX_NODE_SCORE

    # ---- weighted multi-plugin combination (engine.combine_scores) ----

    def _plugin_vector(self, name, state, pod, nodes, free) -> list[float]:
        if name == "balanced_cpu_diskio":
            self._ensure_stats(state, nodes)
            r_io = parse_float_or_zero(pod.annotations.get("diskIO"))
            r_cpu = pod_resource_request(pod, "cpu")
            beta = 1.0 / (1.0 + r_cpu / r_io) if r_io > 0 else 0.0
            alpha = 1.0 - beta
            out = []
            for n in nodes:
                u = self.cache.get(f"U-{n.name}")
                v = self.cache.get(f"V-{n.name}")
                s = 10.0 - 10.0 * abs(alpha * v - beta * u)
                if self.truncate:
                    s = float(int(s)) if s >= 0 else 0.0
                out.append(s)
            return out
        if name == "balanced_diskio":
            return self._balanced_diskio_vector(state, pod, nodes)
        if name == "free_capacity":
            return [self._free_capacity_score(n) for n in nodes]
        if name == "card":
            return [self._card_score(pod, n, nodes) for n in nodes]
        if name == "least_allocated":
            return [self._least_allocated_score(pod, n, free) for n in nodes]
        if name == "balanced_allocation":
            return [
                self._balanced_allocation_score(pod, n, free) for n in nodes
            ]
        if name == "image_locality":
            return [self._image_locality_score(pod, n, nodes) for n in nodes]
        raise ValueError(f"unknown scalar plugin {name!r}")

    @staticmethod
    def _min_max(vec: list[float]) -> list[float]:
        """The framework's rescale (scheduler.go:161-180 /
        ops/normalize.min_max_normalize): highest clamped >= 0, hi==lo
        guard. The ONE implementation behind both normalize_scores (the
        per-pod NormalizeScore hook) and the per-plugin rescale inside
        the weighted combination — they must not drift."""
        hi = max(0.0, *vec)
        lo = min(vec)
        if hi == lo:
            lo -= 1.0
        return [(s - lo) * MAX_NODE_SCORE / (hi - lo) for s in vec]

    def _combined_score(self, state, pod, node, nodes, free) -> float:
        memo = self.cache.get(f"S-{node.name}")
        if memo is not None:
            return memo
        total = [0.0] * len(nodes)
        for name, weight in self.score_plugins:
            vec = self._plugin_vector(name, state, pod, nodes, free)
            if name not in PRESCALED_SCALAR:
                vec = self._min_max(vec)
            for i, s in enumerate(vec):
                total[i] += s * float(weight)
        result = 0.0
        for n, s in zip(nodes, total):
            self.cache.set(f"S-{n.name}", s)
            if n.name == node.name:
                result = s
        return result

    def score(
        self,
        state,
        pod,
        node,
        *,
        all_nodes: list[Node] | None = None,
        free: dict | None = None,
    ):
        nodes = all_nodes or [node]
        if self.score_plugins:
            return self._combined_score(state, pod, node, nodes, free)
        if self.policy == "free_capacity":
            return self._free_capacity_score(node)
        if self.policy == "card":
            return self._card_score(pod, node, nodes)
        if self.policy == "balanced_diskio":
            return self._balanced_diskio_score(state, pod, node, nodes)
        if self.policy == "least_allocated":
            return self._least_allocated_score(pod, node, free)
        if self.policy == "balanced_allocation":
            return self._balanced_allocation_score(pod, node, free)
        if self.policy == "image_locality":
            return self._image_locality_score(pod, node, nodes)
        memo = self.cache.get(f"S-{node.name}")
        if memo is not None:
            return memo
        self._ensure_stats(state, nodes)
        r_io = parse_float_or_zero(pod.annotations.get("diskIO"))
        r_cpu = pod_resource_request(pod, "cpu")
        beta = 1.0 / (1.0 + r_cpu / r_io) if r_io > 0 else 0.0
        alpha = 1.0 - beta
        result = 0.0
        for n in nodes:
            u = self.cache.get(f"U-{n.name}")
            v = self.cache.get(f"V-{n.name}")
            load = abs(alpha * v - beta * u)
            s = 10.0 - 10.0 * load
            if self.truncate:  # uint64() truncation, algorithm.go:113
                s = float(int(s)) if s >= 0 else 0.0
            self.cache.set(f"S-{n.name}", s)
            if n.name == node.name:
                result = s
        return result

    def normalize_scores(self, state, pod, scores):
        self.cache.flush()
        if not scores:
            return {}
        names = list(scores)
        return dict(zip(names, self._min_max([scores[n] for n in names])))

    def pre_bind(self, state, pod, node_name):
        return None


def scalar_schedule_one(
    plugin: ScalarYodaPlugin,
    pod: Pod,
    nodes: list[Node],
    free: dict[str, dict[str, float]],
    score_free: dict[str, dict[str, float]] | None = None,
) -> str | None:
    """One full upstream-style scheduling cycle for one pod: the hook
    sequence of SURVEY.md §3.2, with real resource-fit filtering and
    capacity bookkeeping (which upstream's NodeResourcesFit + binding cycle
    provide around the reference plugin).

    score_free: the capacity state SCORES read (the shape scorers'
    A-Q input). The engine computes a window's score matrices against
    PRE-window state (feasibility stays dynamic), so a fallback
    mirroring it must score against a frozen copy while `free` keeps
    live bookkeeping; None = score against live `free` (single-pod
    cycles, where the two coincide)."""
    state = CycleState()
    plugin.pre_filter(state, pod)
    plugin.pre_score(state, pod, nodes)
    feasible = []
    for node in nodes:
        if not plugin.filter(state, pod, node):
            continue
        ok = True
        for res, avail in free[node.name].items():
            req = pod_resource_request(pod, res)
            if req > 0 and req > avail:
                ok = False
                break
        if ok:
            feasible.append(node)
    if not feasible:
        return None
    scores = {
        n.name: plugin.score(
            state, pod, n, all_nodes=nodes,
            free=score_free if score_free is not None else free,
        )
        for n in feasible
    }
    scores = plugin.normalize_scores(state, pod, scores)
    # deterministic argmax: highest score, first in node order on ties
    best = None
    best_s = -math.inf
    for n in feasible:
        if scores[n.name] > best_s:
            best, best_s = n.name, scores[n.name]
    plugin.pre_bind(state, pod, best)
    for res in free[best]:
        free[best][res] -= pod_resource_request(pod, res)
    return best
