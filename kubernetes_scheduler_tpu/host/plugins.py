"""Extension-point plugin surface + the scalar fallback scoring path.

The reference implements six scheduling-framework extension points
(pkg/yoda/scheduler.go:26-31: PreFilter, Filter, PreScore, Score,
NormalizeScore via ScoreExtensions, PreBind). This module keeps that
surface — so behavior stays auditable hook-by-hook against the reference —
and provides `ScalarYodaPlugin`, a pure-Python implementation with the
same per-pod/per-node call pattern. It is the `TPUBatchScore=false`
fallback: no device, no batching, same answers; its per-cycle statistics
memoization uses CycleCache where the reference used Redis.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Protocol

from kubernetes_scheduler_tpu.host.advisor import NodeUtil
from kubernetes_scheduler_tpu.host.cache import CycleCache
from kubernetes_scheduler_tpu.host.snapshot import parse_float_or_zero, pod_resource_request
from kubernetes_scheduler_tpu.host.types import Node, Pod

MAX_NODE_SCORE = 100.0


@dataclass
class CycleState:
    """Per-pod scratch, the framework.CycleState analog (scheduler.go:105)."""

    data: dict = field(default_factory=dict)

    def write(self, key, value):
        self.data[key] = value

    def read(self, key):
        return self.data[key]


class SchedulerPlugin(Protocol):
    def pre_filter(self, state: CycleState, pod: Pod) -> None: ...
    def filter(self, state: CycleState, pod: Pod, node: Node) -> bool: ...
    def pre_score(self, state: CycleState, pod: Pod, nodes: list[Node]) -> None: ...
    def score(self, state: CycleState, pod: Pod, node: Node) -> float: ...
    def normalize_scores(
        self, state: CycleState, pod: Pod, scores: dict[str, float]
    ) -> dict[str, float]: ...
    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> None: ...


class ScalarYodaPlugin:
    """The reference's plugin behavior, hook for hook, without the network.

    - pre_filter / filter: log-only pass-through (scheduler.go:91-99 —
      every node passes; real filtering happens in the engine path).
    - pre_score: advisor snapshot into CycleState + cache flush
      (scheduler.go:101-113).
    - score: per-cycle statistics computed once and memoized (the
      algorithm.go:47-97 structure, with CycleCache replacing Redis) then
      the live BalancedCpuDiskIO formula (algorithm.go:99-119).
    - normalize_scores: min-max to [0, 100] with the highest==lowest guard
      (scheduler.go:158-183).
    - pre_bind: snapshot existence check (scheduler.go:189-196).
    """

    def __init__(self, utils: dict[str, NodeUtil], *, truncate: bool = True):
        self.utils = utils
        self.cache = CycleCache()
        self.truncate = truncate

    def pre_filter(self, state, pod):
        return None

    def filter(self, state, pod, node):
        return True

    def pre_score(self, state, pod, nodes):
        self.cache.flush()
        state.write("nodeInfo", {n.name: self.utils.get(n.name, NodeUtil()) for n in nodes})

    def _ensure_stats(self, state, nodes: list[Node]):
        if "U-AVG" in self.cache:
            return
        info = state.read("nodeInfo")
        u_sum = 0.0
        for n in nodes:
            u = info[n.name].disk_io / 50.0
            v = info[n.name].cpu_pct / 100.0
            self.cache.set(f"U-{n.name}", u)
            self.cache.set(f"V-{n.name}", v)
            u_sum += u
        u_avg = u_sum / len(nodes)
        m_tmp = sum(
            (self.cache.get(f"U-{n.name}") - u_avg) ** 2 for n in nodes
        ) / len(nodes)
        self.cache.set("U-AVG", u_avg)
        self.cache.set("M-tmp", m_tmp)
        self.cache.set("nodeLen", len(nodes))

    def score(self, state, pod, node, *, all_nodes: list[Node] | None = None):
        nodes = all_nodes or [node]
        memo = self.cache.get(f"S-{node.name}")
        if memo is not None:
            return memo
        self._ensure_stats(state, nodes)
        r_io = parse_float_or_zero(pod.annotations.get("diskIO"))
        r_cpu = pod_resource_request(pod, "cpu")
        beta = 1.0 / (1.0 + r_cpu / r_io) if r_io > 0 else 0.0
        alpha = 1.0 - beta
        result = 0.0
        for n in nodes:
            u = self.cache.get(f"U-{n.name}")
            v = self.cache.get(f"V-{n.name}")
            load = abs(alpha * v - beta * u)
            s = 10.0 - 10.0 * load
            if self.truncate:  # uint64() truncation, algorithm.go:113
                s = float(int(s)) if s >= 0 else 0.0
            self.cache.set(f"S-{n.name}", s)
            if n.name == node.name:
                result = s
        return result

    def normalize_scores(self, state, pod, scores):
        self.cache.flush()
        highest = max(0.0, *scores.values()) if scores else 0.0
        lowest = min(scores.values()) if scores else 0.0
        if highest == lowest:
            lowest -= 1.0
        return {
            name: (s - lowest) * MAX_NODE_SCORE / (highest - lowest)
            for name, s in scores.items()
        }

    def pre_bind(self, state, pod, node_name):
        return None


def scalar_schedule_one(
    plugin: ScalarYodaPlugin,
    pod: Pod,
    nodes: list[Node],
    free: dict[str, dict[str, float]],
) -> str | None:
    """One full upstream-style scheduling cycle for one pod: the hook
    sequence of SURVEY.md §3.2, with real resource-fit filtering and
    capacity bookkeeping (which upstream's NodeResourcesFit + binding cycle
    provide around the reference plugin)."""
    state = CycleState()
    plugin.pre_filter(state, pod)
    plugin.pre_score(state, pod, nodes)
    feasible = []
    for node in nodes:
        if not plugin.filter(state, pod, node):
            continue
        ok = True
        for res, avail in free[node.name].items():
            req = pod_resource_request(pod, res)
            if req > 0 and req > avail:
                ok = False
                break
        if ok:
            feasible.append(node)
    if not feasible:
        return None
    scores = {
        n.name: plugin.score(state, pod, n, all_nodes=nodes) for n in feasible
    }
    scores = plugin.normalize_scores(state, pod, scores)
    # deterministic argmax: highest score, first in node order on ties
    best = None
    best_s = -math.inf
    for n in feasible:
        if scores[n.name] > best_s:
            best, best_s = n.name, scores[n.name]
    plugin.pre_bind(state, pod, best)
    for res in free[best]:
        free[best][res] -= pod_resource_request(pod, res)
    return best
