"""Streaming state ingestion: an event-sourced mirror of SnapshotArrays.

The pre-mirror host loop re-derives cluster state from the full pod/node
lists every cycle (host/snapshot.build_snapshot) and row-diffs whole
matrices to get a SnapshotDelta (snapshot_delta) — both O(nodes) stages
(`snapshot_build` + `delta_derive` in the span attribution), and at 100k
nodes the host-side rebuild, not the device step, is the ceiling.

SnapshotMirror inverts the dataflow: informer pod/node/utilization
events are applied DIRECTLY to a persistent host-side numpy mirror of
the snapshot leaves, accumulating touched-row sets, so each cycle emits
a ready-made SnapshotDelta (same by-value rows, same flush-to-full rules
on static/layout churn as snapshot_delta) in O(events since last cycle).
An idle cluster emits a zero-row delta at ~0 cost; `build_snapshot`
leaves the hot path and is kept only as the flush-to-full path and the
periodic verification cross-check (`verify_interval`), which pins
mirror <-> rebuild BITWISE equality — the PARITY delta/full bindings
guarantee reduces to that check never failing, and a failure resyncs
loudly (full rebuild + mirror_verify_failures_total) instead of serving
drifted state.

Bitwise-equality discipline (why the row math below mirrors the builder
line for line):

- `requested` rows: the builder accumulates per-node contributions as a
  sequential left-fold in running-list order (np.add.at is unbuffered).
  The mirror appends each BOUND pod's cached request-row bytes with the
  same float32 add, and on removal recomputes the node's row from its
  per-node pod list in the SAME order (matrix adds, then the pods-column
  increments, then hostPort increments — the builder's phase order).
- domain tables: raw per-(node, selector) tables take the same per-pod
  += ops; the domain aggregation re-sums only the touched domains with
  float64 accumulation like the builder's Python fold (f32 inputs in
  realistic ranges sum exactly in f64 regardless of association, and
  the verify cross-check backstops the claim).
- utilization: by-value float32 writes, the same scalar cast the
  builder's batch fill applies.

Flush-to-full triggers (mirror -> build_snapshot, emitted delta = None):
any node event (the static block is cached per node SET), selector
drift past the allocated power-of-two bucket, hostPort SLOT GROWTH, and
any verification mismatch. The two heaviest recurring drift classes are
absorbed IN PLACE instead of flushing
(mirror_incremental_extensions_total{kind}): a selector minted into an
existing padding column is filled from the running set
(_extend_selectors — O(running x new selectors), not O(everything)),
and a same-width hostPort remap recomputes only the rows of nodes
hosting port pods (_remap_ports). Mirror-off (snapshot_delta) still
degrades to full uploads on those cycles; the mirror's extension paths
are strictly-better host work with the same emitted arrays, and the
periodic verify cross-check pins that equality.
"""

from __future__ import annotations

import logging
import threading

import numpy as np

from kubernetes_scheduler_tpu.engine import (
    SnapshotArrays,
    SnapshotDelta,
    snapshot_nbytes,
)
from kubernetes_scheduler_tpu.host.observe import Counter
from kubernetes_scheduler_tpu.host.snapshot import (
    FLAG_PLAIN,
    _rows_padded,
    pod_flags,
    pod_request_bytes,
    selector_key,
)

log = logging.getLogger("yoda_tpu.mirror")

# the snapshot leaves the mirror maintains in place (everything else is
# static per node set and flushes to a full rebuild on change — the same
# split snapshot_delta's leaf classification pins at import)
_MUTABLE_LEAVES = (
    "requested",
    "disk_io", "cpu_pct", "mem_pct", "net_up", "net_down",
    "domain_counts", "avoid_counts", "pref_attract", "pref_avoid",
)
_UTIL_LEAVES = ("disk_io", "cpu_pct", "mem_pct", "net_up", "net_down")
_DOMAIN_LEAVES = ("domain_counts", "avoid_counts", "pref_attract", "pref_avoid")


def _pod_key(pod) -> str:
    """Scheduling identity (kube.source.pod_key semantics, duplicated to
    keep the host layer free of kube imports)."""
    return pod.uid or f"{pod.namespace}/{pod.name}"


class CycleTrigger:
    """The condition the event-driven host loop sleeps on
    (config.cycle_trigger="event"): queue pushes and mirror events
    notify(); the loop wait()s with the watchdog timeout. The
    set-then-clear-after-wait protocol cannot lose a wakeup: a notify
    landing between the caller's work check and its wait() leaves the
    event set, so the wait returns immediately."""

    def __init__(self):
        self._evt = threading.Event()
        self.notifies = 0

    def notify(self) -> None:
        self.notifies += 1
        self._evt.set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until notified or `timeout` (the tick watchdog — the
        loop still runs its bookkeeping on silence). Returns True when
        woken by a notify."""
        fired = self._evt.wait(timeout)
        self._evt.clear()
        return fired


class SnapshotMirror:
    """Persistent host-side mirror of SnapshotArrays, fed by events.

    Ownership: after seed(), the mirror's (nodes, running, utils) ARE
    the scheduler's cluster state — `state()` serves them by reference
    and the per-cycle list/fetch callables are consulted only at seed
    time. Event producers (informer hooks, the scheduler's own binds,
    ScenarioWorld, advisor coalescing) keep them current.

    Emitted arrays are frozen: the first event that touches a leaf after
    an emit copies it (copy-on-write), so journaled/retained snapshots
    never mutate underfoot — the flight recorder's delta chain rule
    compares delta bases by identity and depends on this.
    """

    def __init__(
        self,
        builder,
        *,
        verify_interval: int = 0,
        on_dirty=None,
    ):
        self.builder = builder
        # 0 = never cross-check; N = every Nth emit re-runs
        # build_snapshot (ephemeral) and compares every leaf bitwise
        self.verify_interval = int(verify_interval)
        self._on_dirty = on_dirty
        # re-entrant: the public event/emit surfaces hold it and the
        # private row-math helpers re-take it around their own state
        # mutations (self-documenting, and safe if ever called bare)
        self._lock = threading.RLock()
        self.seeded = False
        self.nodes: list = []
        self.running: list = []
        self.utils: dict = {}
        self._running_keys: dict[str, object] = {}
        self._by_node: dict[str, list] = {}
        self._flush = True
        self._flush_reason = "seed"
        self._leaves: dict[str, np.ndarray] = {}
        self._owned: set[str] = set()
        self._static: SnapshotArrays | None = None
        self._raw: tuple | None = None          # mirror-owned raw domain tables
        self._topo_groups: dict = {}
        self._node_index: dict = {}
        self._names_t: tuple = ()
        self._pods_col = 0
        self._port0 = 0
        # selector-table size at adopt: any growth since (a window or
        # the preemption pass minting ids through build_pod_batch)
        # means the raw tables were never matched/sized against the new
        # selector — layout drift, flush
        self._adopt_n_sel = 0
        self._adopt_slots = 0
        self._adopt_ports: dict = {}
        self._req_dirty: set[int] = set()
        self._util_dirty: set[int] = set()
        self._dom_dirty: set[int] = set()
        self._last_emitted: SnapshotArrays | None = None
        # set when an in-place extension patched a STATIC leaf the delta
        # format cannot carry (domain_id columns): the next emit must
        # ship a full upload even though the mirror never rebuilt
        self._force_full_upload = False
        self._emits = 0
        # exported beside the scheduler's collectors (SHIPPED_METRICS)
        self.ctr_events = Counter(
            "events_applied_total",
            "Informer/advisor events applied to the snapshot mirror",
            labels=("kind",),
        )
        self.ctr_rebuilds = Counter(
            "mirror_full_rebuilds_total",
            "Mirror flush-to-full rebuilds, labeled by the flush cause "
            "(seed, node-churn, selector-drift, layout-drift, "
            "port-churn, verify-mismatch)",
            labels=("reason",),
        )
        self.ctr_verify_failures = Counter(
            "mirror_verify_failures_total",
            "Periodic mirror-vs-rebuild cross-checks that found a "
            "bitwise mismatch (resynced by a full rebuild)",
        )
        self.ctr_extensions = Counter(
            "mirror_incremental_extensions_total",
            "Layout drifts absorbed in place instead of flushing to a "
            "full rebuild (selector = new selector columns filled from "
            "the running set; port-remap = hostPort columns recomputed "
            "under a remapped same-width port table)",
            labels=("kind",),
        )
        self.collectors = (
            self.ctr_events, self.ctr_rebuilds, self.ctr_verify_failures,
            self.ctr_extensions,
        )

    # -- seeding / state -------------------------------------------------

    def seed(self, nodes: list, running: list, utils: dict) -> None:
        """Adopt the initial cluster state (one full fetch). The first
        emit() flush-builds the arrays; events apply from now on."""
        with self._lock:
            self.nodes = list(nodes)
            self.running = list(running)
            self.utils = dict(utils)
            self._running_keys = {_pod_key(p): p for p in self.running}
            self._rebuild_by_node()
            self._mark_flush("seed")
            self.seeded = True

    def state(self) -> tuple[list, list, dict]:
        """(nodes, running, utils) by REFERENCE — the running list stays
        the same (append-only between removals) object so the builder's
        prefix-identity caches hold across flush rebuilds."""
        return self.nodes, self.running, self.utils  # graftlint: disable=thread-race -- intended bulk-sync read: the cycle adopts these references at a flush boundary while event writes serialize under self._lock; tearing only stales one cycle and the flush path rebuilds from scratch

    def _rebuild_by_node(self) -> None:
        with self._lock:
            by_node: dict[str, list] = {}
            for p in self.running:
                if p.node_name is not None:
                    by_node.setdefault(p.node_name, []).append(p)
            self._by_node = by_node

    def _mark_flush(self, reason: str) -> None:
        with self._lock:
            if not self._flush:
                self._flush = True
                self._flush_reason = reason

    def _selectors_stable(self) -> bool:
        return len(self.builder.selectors) == self._adopt_n_sel

    def _extend_selectors(self) -> bool:
        """Absorb selector drift IN PLACE: fill the already-allocated
        padding columns for selector ids minted since adopt, instead of
        flushing to a full rebuild.

        The domain tables (and domain_id) are sized to the power-of-two
        selector bucket, so a freshly minted id usually lands in columns
        that already exist as zero padding — the only state a new
        selector actually changes. The fill is the builder's own math
        for exactly those columns: count running pods matching each new
        key (pref/anti term WEIGHTS cannot target a new id — every
        running pod's pref/anti keys were interned when the pod entered
        the running set, so the new columns' weight tables stay zero),
        then aggregate the new columns over their topology domains.
        Returns False — caller flushes — when the drift crosses the
        bucket boundary (array shapes grow; a rebuild must re-size).

        A non-hostname topology also patches domain_id, a STATIC leaf
        the delta format cannot carry ("domain_id is layout and never
        rides a delta" — engine.py): the next emit ships a full upload,
        but the mirror still never rebuilt."""
        with self._lock:
            b = self.builder
            if len(b.selectors) == self._adopt_n_sel:
                return True
            cur_s = int(self._leaves["domain_counts"].shape[1])
            if b._selector_slots() != cur_s:
                return False  # bucket growth: shapes change, rebuild
            new_items = list(b.selectors.items())[self._adopt_n_sel:]
            n_real = len(self.nodes)
            if self._raw is None:
                # adopt saw zero selectors: allocate the raw tables the
                # first minted id needs (bucket width is already 1+)
                self._raw = tuple(
                    np.zeros((n_real, cur_s), np.float32) for _ in range(4)
                )
            raw = self._raw[0]
            node_index = self._node_index
            for pod in self.running:
                i = node_index.get(pod.node_name)
                if i is None:
                    continue
                for key, sid in new_items:
                    if b._key_matches(pod, key):
                        raw[i, sid] += 1
            new_by_topo: dict[str, list[int]] = {}
            for key, sid in new_items:
                new_by_topo.setdefault(key[2], []).append(sid)
            outs = tuple(
                self._writable(name) for name in _DOMAIN_LEAVES
            )
            dom_id = None
            for topo, sids in new_by_topo.items():
                grp = self._topo_groups.get(topo)
                if grp is None:
                    labels = [
                        nd.name
                        if topo == "kubernetes.io/hostname"
                        else nd.labels.get(topo, "")
                        for nd in self.nodes
                    ]
                    members: dict[str, list[int]] = {}
                    for i, lab in enumerate(labels):
                        members.setdefault(lab, []).append(i)
                    grp = self._topo_groups[topo] = {
                        "labels": labels, "members": members, "sids": [],
                    }
                grp["sids"].extend(sids)
                for rows in grp["members"].values():
                    ix = np.ix_(rows, sids)
                    touched = False
                    for table, out in zip(self._raw, outs):
                        vals = table[ix].sum(axis=0, dtype=np.float64)
                        out[ix] = vals
                        if vals.any():
                            touched = True
                    if touched:
                        self._dom_dirty.update(rows)
                if topo != "kubernetes.io/hostname":
                    # hostname columns equal the padding default (every
                    # node its own domain, first index == own index) —
                    # only a label topology moves domain_id
                    if dom_id is None:
                        dom_id = np.array(self._static.domain_id)
                    for rows in grp["members"].values():
                        dom_id[np.ix_(rows, sids)] = rows[0]
            if dom_id is not None:
                self._static = self._static._replace(domain_id=dom_id)
                self._force_full_upload = True
            self._adopt_n_sel = len(b.selectors)
            self.ctr_extensions.inc(kind="selector")
            return True

    def _remap_ports(self, new_index: dict) -> None:
        """Absorb a hostPort REMAP within the existing slot budget: only
        the port-column block of `requested` means something different
        under the new port->column table, so recompute the rows of nodes
        hosting port pods (row-exact, the builder's phase order) instead
        of flushing. The static block survives untouched — every port
        column's capacity is the same 1.0/node and the column NAMES are
        slot-generic (hostport/<i>), so neither alloc nor the resource-
        name tuple moves. Slot GROWTH still flushes (every width in the
        snapshot changes)."""
        with self._lock:
            self._adopt_ports = dict(new_index)
            for name, pods_on in self._by_node.items():
                if any(p.host_ports for p in pods_on):
                    i = self._node_index.get(name)
                    if i is not None:
                        self._recompute_requested_row(i, name)
            self.ctr_extensions.inc(kind="port-remap")

    def _notify(self) -> None:
        if self._on_dirty is not None:
            self._on_dirty()

    # -- event ingestion -------------------------------------------------

    def apply_node_event(self, etype: str, node) -> None:
        """ADDED/MODIFIED/DELETED on a Node: every node-side leaf is
        static per node SET (build_snapshot's _node_static cache), so
        any node event flushes to a full rebuild — the same rule that
        makes snapshot_delta return None on static churn."""
        with self._lock:
            if not self.seeded:
                return
            self.ctr_events.inc(kind="node")
            if etype == "DELETED":
                self.nodes = [nd for nd in self.nodes if nd.name != node.name]
            else:
                for i, nd in enumerate(self.nodes):
                    if nd.name == node.name:
                        self.nodes[i] = node  # MODIFIED keeps position
                        break
                else:
                    self.nodes.append(node)
            self._mark_flush("node-churn")
        self._notify()

    def apply_pod_event(self, etype: str, pod) -> None:
        """A running-set change: BOUND/ADDED/MODIFIED adds or replaces
        the pod, DELETED removes it. Dedup is by scheduling key AND
        object identity, so the scheduler's own post-bind self-apply and
        the informer's later echo of the same Pod object coalesce."""
        with self._lock:
            if not self.seeded:
                return
            key = _pod_key(pod)
            if etype == "DELETED":
                old = self._running_keys.pop(key, None)
                if old is None:
                    return
                self.ctr_events.inc(kind="pod")
                self.running = [p for p in self.running if p is not old]
                lst = self._by_node.get(old.node_name)
                if lst is not None:
                    self._by_node[old.node_name] = [
                        p for p in lst if p is not old
                    ]
                if not self._flush:
                    if self._extend_selectors():
                        self._recompute_node_rows(old.node_name)
                    else:
                        self._mark_flush("selector-drift")
            else:
                existing = self._running_keys.get(key)
                if existing is pod:
                    return  # self-apply echo (same object): no-op
                self.ctr_events.inc(kind="pod")
                if existing is not None:
                    # replace = remove + add (keeps row math exact)
                    self.running = [
                        p for p in self.running if p is not existing
                    ]
                    lst = self._by_node.get(existing.node_name)
                    if lst is not None:
                        self._by_node[existing.node_name] = [
                            p for p in lst if p is not existing
                        ]
                    if not self._flush:
                        if self._extend_selectors():
                            self._recompute_node_rows(existing.node_name)
                        else:
                            self._mark_flush("selector-drift")
                if pod.node_name is None:
                    self._running_keys.pop(key, None)
                    self._notify()
                    return
                # absorb selector drift BEFORE the pod joins the running
                # set: the extension's new-column fill scans running, and
                # _apply_pod_add below counts this pod once against the
                # (now grown) adopted prefix. The incoming pod's own
                # pref/anti keys are minted first — the exact term kinds
                # the builder's running-set intake interns — so a pod
                # introducing a fresh soft-affinity selector extends
                # instead of flushing
                if not self._flush:
                    fl = pod.__dict__.get("_flags_cache")
                    if fl is None:
                        fl = pod_flags(pod)
                    if not fl & FLAG_PLAIN:
                        for term in pod.pod_affinity:
                            if (term.preferred or term.anti) and (
                                selector_key(term)
                                not in self.builder.selectors
                            ):
                                self.builder._selector_id(term)
                        # topology-spread selectors: BOTH
                        # whenUnsatisfiable variants (DoNotSchedule
                        # hard, ScheduleAnyway soft) intern through the
                        # same canonical selector_key, so a bound pod
                        # arriving with either variant of a fresh
                        # spread selector extends the column in place
                        # (the fill scans running before this pod joins;
                        # _apply_pod_domains then counts it once) — an
                        # out-of-band bind with spread constraints used
                        # to leave the selector unminted until a window
                        # used it
                        for sc in pod.topology_spread:
                            if selector_key(sc) not in self.builder.selectors:
                                self.builder._selector_id(sc)
                    if not self._extend_selectors():
                        self._mark_flush("layout-drift")
                self._running_keys[key] = pod
                self.running.append(pod)
                self._by_node.setdefault(pod.node_name, []).append(pod)
                if not self._flush:
                    if not self._pod_compatible(pod):
                        self._mark_flush("layout-drift")
                    else:
                        self._apply_pod_add(pod)
        self._notify()

    def apply_util_events(self, changed: dict) -> None:
        """{node name: NodeUtil} for CHANGED nodes only (the advisor
        coalescing protocol, host/advisor.fetch_changed). By-value f32
        writes; no-op values are filtered so idle fetches stay free."""
        if not changed:
            return
        with self._lock:
            if not self.seeded:
                return
            self.ctr_events.inc(len(changed), kind="util")
            self.utils.update(changed)
            if self._flush:
                return
            for name, u in changed.items():
                i = self._node_index.get(name)
                if i is None:
                    continue
                vals = (u.disk_io, u.cpu_pct, u.mem_pct, u.net_up, u.net_down)
                touched = False
                for leaf, v in zip(_UTIL_LEAVES, vals):
                    v32 = np.float32(v)
                    if self._leaves[leaf][i] != v32:
                        self._writable(leaf)[i] = v32
                        touched = True
                if touched:
                    self._util_dirty.add(i)
        self._notify()

    # -- per-event row math (mirrors the builder line for line) ----------

    def _pod_compatible(self, pod) -> bool:
        """Can this running pod's contribution be applied as rows, or
        does it drift the layout (unknown hostPort column, a preferred/
        anti affinity term minting a selector the tables never matched
        prefix pods against)?"""
        fl = pod.__dict__.get("_flags_cache")
        if fl is None:
            fl = pod_flags(pod)
        if fl & FLAG_PLAIN:
            return True
        if pod.host_ports and any(
            # the ADOPT-TIME mapping, never the live builder index: an
            # ephemeral/preemption build_snapshot between emits remaps
            # builder._port_index under us (the emit-time probe flushes
            # when the remap matters; row math must not race it)
            pt not in self._adopt_ports for pt in pod.host_ports
        ):
            return False
        for term in pod.pod_affinity:
            if (term.preferred or term.anti) and (
                selector_key(term) not in self.builder.selectors
            ):
                return False
        return True

    def _request_row(self, pod) -> np.ndarray:
        return np.frombuffer(
            pod_request_bytes(pod, self._names_t), np.float32
        )

    def _apply_pod_add(self, pod) -> None:
        with self._lock:
            i = self._node_index.get(pod.node_name)
            if i is None:
                return  # unknown node: contributes nothing (builder drops rows < 0)
            row = self._request_row(pod)
            if row[self._pods_col] != 0.0:
                # an explicit "pods" request would interleave differently
                # with the builder's phase order — recompute the whole row
                self._recompute_requested_row(i, pod.node_name)
            else:
                req = self._writable("requested")
                req[i, :] += row
                req[i, self._pods_col] += 1.0
                if pod.host_ports:
                    pidx = self._adopt_ports  # adopt-time mapping (see _pod_compatible)
                    for pt in pod.host_ports:
                        req[i, self._port0 + pidx[pt]] += 1
                self._req_dirty.add(i)
            self._apply_pod_domains(pod, i)

    def _apply_pod_domains(self, pod, i: int) -> None:
        if self._raw is None:
            return
        raw, raw_avoid, raw_attract, raw_avoid_w = self._raw
        b = self.builder
        changed = False
        # snapshot the first adopt-count entries: the scheduler thread
        # can mint ids concurrently (preemption-pass build_pod_batch);
        # insertion order makes the prefix exactly the adopted table,
        # and any later-minted id flushes via the stability guards
        for key, sid in list(b.selectors.items())[: self._adopt_n_sel]:
            if b._key_matches(pod, key):
                raw[i, sid] += 1
                changed = True
        fl = pod.__dict__.get("_flags_cache")
        if fl is None or not fl & FLAG_PLAIN:
            for term in pod.pod_affinity:
                if not (term.preferred or term.anti):
                    continue
                sid = b.selectors.get(selector_key(term))
                if sid is None or sid >= self._adopt_n_sel:
                    # minted after adopt (raced past the intake check):
                    # the tables never saw it — flush, never index past
                    self._mark_flush("selector-drift")
                    return
                if term.preferred:
                    (raw_avoid_w if term.anti else raw_attract)[i, sid] += (
                        term.weight
                    )
                elif term.anti:
                    raw_avoid[i, sid] += 1
                changed = True
        if changed:
            self._reaggregate_node(i)

    def _recompute_node_rows(self, name: str | None) -> None:
        if name is None:
            return
        i = self._node_index.get(name)
        if i is None:
            return
        self._recompute_requested_row(i, name)
        if self._raw is not None:
            raw, raw_avoid, raw_attract, raw_avoid_w = self._raw
            raw[i, :] = 0.0
            raw_avoid[i, :] = 0.0
            raw_attract[i, :] = 0.0
            raw_avoid_w[i, :] = 0.0
            b = self.builder
            # adopt-count prefix snapshot: see _apply_pod_domains
            table = list(b.selectors.items())[: self._adopt_n_sel]
            for pod in self._by_node.get(name, ()):
                for key, sid in table:
                    if b._key_matches(pod, key):
                        raw[i, sid] += 1
                fl = pod.__dict__.get("_flags_cache")
                if fl is None or not fl & FLAG_PLAIN:
                    for term in pod.pod_affinity:
                        if not (term.preferred or term.anti):
                            continue
                        sid = b.selectors.get(selector_key(term))
                        if sid is None or sid >= self._adopt_n_sel:
                            self._mark_flush("selector-drift")
                            return
                        if term.preferred:
                            (raw_avoid_w if term.anti else raw_attract)[
                                i, sid
                            ] += term.weight
                        elif term.anti:
                            raw_avoid[i, sid] += 1
            self._reaggregate_node(i)

    def _recompute_requested_row(self, i: int, name: str) -> None:
        """The builder's full-rescan contribution to one node row, in
        its phase order: matrix adds for every pod on the node (running-
        list order), then the pods-column increments, then hostPorts."""
        with self._lock:
            req = self._writable("requested")
            req[i, :] = 0.0
            pods_on = self._by_node.get(name, ())
            for pod in pods_on:
                req[i, :] += self._request_row(pod)
            for _ in pods_on:
                req[i, self._pods_col] += 1.0
            pidx = self._adopt_ports  # adopt-time mapping (see _pod_compatible)
            for pod in pods_on:
                if pod.host_ports:
                    for pt in pod.host_ports:
                        req[i, self._port0 + pidx[pt]] += 1
            self._req_dirty.add(i)

    def _reaggregate_node(self, i: int) -> None:
        """Re-sum the domain aggregates of every (topology, selector)
        group node i belongs to — O(domain size x selectors sharing the
        topology key), vectorized with float64 accumulation (the
        builder's Python fold is f64 too; f32 inputs in realistic ranges
        sum exactly in f64 under any association, and the periodic
        verify pass backstops the equality)."""
        with self._lock:
            raw = self._raw
            counts = self._writable("domain_counts")
            avoid = self._writable("avoid_counts")
            attract = self._writable("pref_attract")
            avoid_w = self._writable("pref_avoid")
            outs = (counts, avoid, attract, avoid_w)
            for grp in self._topo_groups.values():
                d = grp["labels"][i]
                rows = grp["members"][d]
                sids = grp["sids"]
                ix = np.ix_(rows, sids)
                for table, out in zip(raw, outs):
                    out[ix] = table[ix].sum(axis=0, dtype=np.float64)
                self._dom_dirty.update(rows)

    # -- cycle surface ---------------------------------------------------

    def emit(
        self,
        window: list,
        *,
        pending_all_plain: bool = False,
        prev: SnapshotArrays | None = None,
        max_byte_frac: float = 0.5,
    ) -> tuple[SnapshotArrays, SnapshotDelta | None, bool]:
        """One cycle's (snapshot, delta, rebuilt) in O(events since the
        last emit). `prev` is the snapshot the engine currently retains
        (Scheduler._resident_prev); the delta is non-None only when it
        is BY IDENTITY the mirror's previous emit — any invalidation,
        flush, or skipped cycle degrades to a full upload, exactly like
        snapshot_delta returning None. `rebuilt` reports a flush-to-full
        (build_snapshot ran)."""
        with self._lock:
            if not self.seeded:
                raise RuntimeError("SnapshotMirror.emit before seed()")
            if not self._flush:
                self._check_window(window, pending_all_plain)
            if (
                not self._flush
                and self.verify_interval > 0
                and self._emits > 0
                and self._emits % self.verify_interval == 0
            ):
                self._verify_locked(window, pending_all_plain)
            if self._flush:
                snap = self._rebuild(window, pending_all_plain)
                self._emits += 1
                return snap, None, True
            snap = self._static._replace(**self._leaves)
            delta = None
            if (
                prev is not None
                and prev is self._last_emitted
                and not self._force_full_upload
            ):
                delta = self._make_delta(snap, max_byte_frac)
            self._force_full_upload = False
            self._req_dirty.clear()
            self._util_dirty.clear()
            self._dom_dirty.clear()
            self._owned.clear()  # freeze: next touch copies
            self._last_emitted = snap
            self._emits += 1
            return snap, delta, False

    def _check_window(self, window: list, pending_all_plain: bool) -> None:
        """Window-driven layout drift: a pending pod minting a selector
        (its affinity/spread terms were never matched against the
        running prefix) or moving the hostPort table forces the flush
        build_snapshot would have absorbed."""
        b = self.builder
        if not self._selectors_stable():
            # an out-of-band build_pod_batch (preemption pass, direct
            # callers) minted selector ids since adopt — absorb the new
            # columns in place when they fit the allocated bucket
            if not self._extend_selectors():
                self._mark_flush("selector-drift")
                return
        has_ports = False
        minted = False
        if not pending_all_plain:
            for pod in window:
                fl = pod.__dict__.get("_flags_cache")
                if fl is None:
                    fl = pod_flags(pod)
                if fl & FLAG_PLAIN:
                    continue
                if pod.host_ports:
                    has_ports = True
                # mint window selectors NOW, in build_pod_batch's own
                # scan order (per pod: affinity terms, then spread
                # constraints — ids are append-only so the suffix is
                # exactly what _extend_selectors fills)
                for term in pod.pod_affinity:
                    if selector_key(term) not in b.selectors:
                        b._selector_id(term)
                        minted = True
                for sc in pod.topology_spread:
                    if selector_key(sc) not in b.selectors:
                        b._selector_id(sc)
                        minted = True
        if minted and not self._extend_selectors():
            self._mark_flush("selector-drift")
            return
        if has_ports or self._adopt_ports:
            # refresh the port->column mapping the way build_snapshot
            # would; running pods' port contributions would otherwise
            # sit in stale columns
            b._assign_port_slots(
                self.running,
                [] if pending_all_plain else window,
                ephemeral=True,
                pending_all_plain=pending_all_plain,
            )
            if b._port_slots != self._adopt_slots:
                # slot growth: `requested`/alloc widths change — rebuild
                self._mark_flush("port-churn")
            elif b._port_index != self._adopt_ports:
                self._remap_ports(b._port_index)

    def _rebuild(self, window: list, pending_all_plain: bool) -> SnapshotArrays:
        self.ctr_rebuilds.inc(reason=self._flush_reason or "seed")
        # survives the adopt's reason reset: the degradation ladder
        # records WHY the mirror dropped to its rebuild rung
        self.last_rebuild_reason = self._flush_reason
        log.debug("mirror: full rebuild (%s)", self._flush_reason)
        snap = self.builder.build_snapshot(
            self.nodes, self.utils, self.running,
            pending_pods=window, ephemeral=False,
            pending_all_plain=pending_all_plain,
        )
        self._adopt(snap)
        return snap

    def _adopt(self, snap: SnapshotArrays) -> None:
        with self._lock:
            b = self.builder
            self._static = snap
            self._leaves = {
                name: np.asarray(getattr(snap, name)) for name in _MUTABLE_LEAVES
            }
            self._owned = set()
            self._node_index = b._node_index
            self._names_t = b.resource_names_tuple()
            names = b.resource_names
            self._pods_col = names.index("pods")
            self._port0 = len(names) - b._port_slots
            self._adopt_slots = b._port_slots
            self._adopt_ports = dict(b._port_index)
            self._rebuild_by_node()
            # mirror-owned copies of the raw per-(node, selector) tables —
            # the builder's own _dc_raw cache stays untouched so its prefix
            # bookkeeping remains valid for the next flush rebuild
            self._adopt_n_sel = len(b.selectors)
            if b.selectors:
                rc = b.__dict__.get("_dc_raw")
                self._raw = tuple(t.copy() for t in rc["tables"])
                self._build_topo_groups()
            else:
                self._raw = None
                self._topo_groups = {}
            self._req_dirty.clear()
            self._util_dirty.clear()
            self._dom_dirty.clear()
            self._flush = False
            self._flush_reason = ""
            self._force_full_upload = False
            self._last_emitted = snap

    def _build_topo_groups(self) -> None:
        with self._lock:
            groups: dict = {}
            for key, sid in self.builder.selectors.items():
                topo = key[2]
                grp = groups.get(topo)
                if grp is None:
                    labels = [
                        nd.name
                        if topo == "kubernetes.io/hostname"
                        else nd.labels.get(topo, "")
                        for nd in self.nodes
                    ]
                    members: dict[str, list[int]] = {}
                    for i, lab in enumerate(labels):
                        members.setdefault(lab, []).append(i)
                    grp = groups[topo] = {
                        "labels": labels, "members": members, "sids": [],
                    }
                grp["sids"].append(sid)
            self._topo_groups = groups

    def _writable(self, name: str) -> np.ndarray:
        """Copy-on-write: the first mutation of a leaf after an emit
        copies it, so emitted (journaled / engine-retained / recorded)
        snapshots are immutable."""
        with self._lock:
            if name not in self._owned:
                self._leaves[name] = self._leaves[name].copy()
                self._owned.add(name)
            return self._leaves[name]

    def _make_delta(
        self, snap: SnapshotArrays, max_byte_frac: float
    ) -> SnapshotDelta | None:
        n = int(np.asarray(snap.node_mask).shape[0])
        req = self._leaves["requested"]
        req_changed = np.array(sorted(self._req_dirty), np.int64)
        req_rows = _rows_padded(req_changed, n)
        req_vals = np.zeros((len(req_rows), req.shape[1]), np.float32)
        req_vals[: len(req_changed)] = req[req_changed]
        util_changed = np.array(sorted(self._util_dirty), np.int64)
        util_rows = _rows_padded(util_changed, n)
        util_vals = np.zeros((len(util_rows), 5), np.float32)
        for col, name in enumerate(_UTIL_LEAVES):
            util_vals[: len(util_changed), col] = self._leaves[name][
                util_changed
            ]
        dom_changed = np.array(sorted(self._dom_dirty), np.int64)
        dom_rows = _rows_padded(dom_changed, n)
        s = int(self._leaves["domain_counts"].shape[1])
        dom_vals = np.zeros((len(dom_rows), s, 4), np.float32)
        for col, name in enumerate(_DOMAIN_LEAVES):
            dom_vals[: len(dom_changed), :, col] = self._leaves[name][
                dom_changed
            ]
        delta = SnapshotDelta(
            req_rows=req_rows, req_vals=req_vals,
            util_rows=util_rows, util_vals=util_vals,
            dom_rows=dom_rows, dom_vals=dom_vals,
            node_mask=np.asarray(snap.node_mask, bool),
        )
        if snapshot_nbytes(delta) > max_byte_frac * snapshot_nbytes(snap):
            return None  # same bytes rule as snapshot_delta
        return delta

    # -- verification ----------------------------------------------------

    def _verify_locked(self, window: list, pending_all_plain: bool) -> bool:
        """Cross-check every mirror leaf bitwise against a fresh
        build_snapshot over the SAME state. A mismatch logs, counts, and
        flushes — this very emit then serves the rebuild, so a drifted
        mirror can never produce a decision the rebuild would not."""
        rebuilt = self.builder.build_snapshot(
            self.nodes, self.utils, self.running,
            pending_pods=window, ephemeral=True,
            pending_all_plain=pending_all_plain,
        )
        cur = self._static._replace(**self._leaves)
        bad = []
        for name in SnapshotArrays._fields:
            a = np.asarray(getattr(cur, name))
            b = np.asarray(getattr(rebuilt, name))
            if a.shape != b.shape or a.dtype != b.dtype or not np.array_equal(a, b):
                bad.append(name)
        if bad:
            self.ctr_verify_failures.inc()
            log.error(
                "mirror: verification mismatch on %s; resyncing with a "
                "full rebuild", bad,
            )
            self._mark_flush("verify-mismatch")
            return False
        return True

    def verify(self, window: list | None = None) -> bool:
        """On-demand cross-check (tests; debugging)."""
        with self._lock:
            if not self.seeded or self._flush:
                return True
            return self._verify_locked(window or [], window is None)

    def inject_corruption(
        self, *, leaf: str = "net_up", row: int = 0, delta: float = 1.0
    ) -> bool:
        """Fault-injection surface (sim/faults.py chaos scenarios):
        perturb ONE cell of a mutable mirror leaf WITHOUT marking its
        row dirty — exactly the silent-drift class the periodic bitwise
        verify cross-check exists to catch (the corrupt value would
        ride emitted snapshots but never the delta). Goes through the
        copy-on-write path, so already-emitted (journaled / engine-
        retained) snapshots are never mutated — replay parity holds;
        the NEXT verify pass must detect, count, and resync. Returns
        False when there is nothing to corrupt (unseeded, or a flush is
        already pending and the corruption would be rebuilt away)."""
        with self._lock:
            if not self.seeded or self._flush or leaf not in self._leaves:
                return False
            arr = self._writable(leaf)
            if arr.size == 0:
                return False
            arr[row % arr.shape[0]] += np.float32(delta)
            return True
