"""Snapshot builder: typed cluster objects -> dense device arrays.

The equivalent of the upstream scheduler's node snapshot plus the
reference's per-(pod, node) resource math (CalculateResourceAllocatable-
Request / CalculatePodResourceRequest, pkg/yoda/score/algorithm.go:209-262)
— evaluated once for the whole batch into matrices instead of per plugin
call. Strings (label keys/values, taint keys) are interned to int32 ids so
constraint matching runs as integer tensor compares on device.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

import numpy as np

from kubernetes_scheduler_tpu.engine import (
    PodBatch,
    SnapshotArrays,
    SnapshotDelta,
)
from kubernetes_scheduler_tpu.host.advisor import NodeUtil
from kubernetes_scheduler_tpu.host.queue import pod_gang, pod_priority
from kubernetes_scheduler_tpu.host.types import Node, Pod
from kubernetes_scheduler_tpu.ops import constraints as C
from kubernetes_scheduler_tpu.ops.resources import (
    CANONICAL_NAMES,
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    N_CANONICAL,
)
from kubernetes_scheduler_tpu.utils.padding import bucket_size

log = logging.getLogger("yoda_tpu.host")

_EFFECTS = {
    "NoSchedule": C.NO_SCHEDULE,
    "PreferNoSchedule": C.PREFER_NO_SCHEDULE,
    "NoExecute": C.NO_EXECUTE,
}
_NA_OPS = {
    "In": C.OP_IN,
    "NotIn": C.OP_NOT_IN,
    "Exists": C.OP_EXISTS,
    "DoesNotExist": C.OP_NOT_EXISTS,
}
_CARD_METRICS = ("bandwidth", "clock", "core", "power", "free_memory", "total_memory")


def parse_float_or_zero(s) -> float:
    """strconv semantics used throughout the reference: unparsable -> 0
    (filter.go:60-95, algorithm.go:103)."""
    try:
        return float(s)
    except (TypeError, ValueError):
        return 0.0


def parse_int_or_zero(s) -> int:
    try:
        return int(s)
    except (TypeError, ValueError):
        return 0


class Interner:
    """String -> dense int32 id table (one per vocabulary)."""

    def __init__(self):
        self._table: dict[str, int] = {}

    def id(self, s: str) -> int:
        if s not in self._table:
            self._table[s] = len(self._table)
        return self._table[s]

    def lookup(self, s: str) -> int:
        """Non-inserting probe: -1 for strings outside the vocabulary
        (pod-side readers must not grow a table the node-side matrix was
        already sized against)."""
        return self._table.get(s, -1)

    def __len__(self):
        return len(self._table)


def pod_resource_request(pod: Pod, resource: str) -> float:
    """max(sum(containers), max(initContainers)) + overhead, with the
    non-zero defaults for cpu/memory (algorithm.go:238-262 +
    schedutil.GetNonzeroRequestForResource semantics)."""

    def one(c, res):
        v = c.requests.get(res, 0.0)
        if v == 0.0 and res == "cpu":
            return DEFAULT_MILLI_CPU_REQUEST
        if v == 0.0 and res == "memory":
            return DEFAULT_MEMORY_REQUEST
        return v

    total = sum(one(c, resource) for c in pod.containers)
    for ic in pod.init_containers:
        total = max(total, one(ic, resource))
    return (
        total
        + pod.overhead.get(resource, 0.0)
        + pod.attach_demands.get(resource, 0.0)
    )


def pod_request_vector(pod: Pod, names: tuple[str, ...]) -> np.ndarray:
    """[len(names)] request vector, memoized on the pod object — pod specs
    are immutable in k8s, and long-running pods are re-summed into the
    `requested` matrix EVERY cycle, so this turns the builder's hottest
    loop into a dict hit after each pod's first cycle.

    First-build fast path: the overwhelmingly common one-container /
    no-init / no-overhead pod skips the per-resource generator chain of
    pod_resource_request (measured ~20us -> ~4us per pod; pending pods
    pay first-build once per arrival, so this is the pod-batch builder's
    floor)."""
    return np.asarray(pod_request_row(pod, names), np.float32)


def pod_request_row(pod: Pod, names: tuple[str, ...]) -> tuple:
    """pod_request_vector as a plain TUPLE, the form the builders
    batch-assemble with one np.array call over the whole window/running
    set (one C-speed construction instead of a per-pod ndarray each —
    the difference between ~6us and ~2us per pod in the host loop)."""
    cache = pod.__dict__.get("_req_row_cache")
    # identity check first: the builder interns its names tuple
    # (resource_names_tuple), so steady-state hits never string-compare
    if cache is not None and (cache[0] is names or cache[0] == names):
        return cache[1]
    if (
        len(pod.containers) == 1
        and not pod.init_containers
        and not pod.overhead
        and not pod.attach_demands
    ):
        # fast path: `v or default` applies the non-zero defaults
        # (schedutil.GetNonzeroRequestForResource) for missing AND
        # explicit-zero requests, exactly like pod_resource_request
        req = pod.containers[0].requests
        row = tuple(
            (
                req.get(r) or DEFAULT_MILLI_CPU_REQUEST
                if r == "cpu"
                else req.get(r) or DEFAULT_MEMORY_REQUEST
                if r == "memory"
                else req.get(r, 0.0)
            )
            for r in names
        )
    else:
        row = tuple(pod_resource_request(pod, r) for r in names)
    pod.__dict__["_req_row_cache"] = (names, row)
    return row


def pod_request_bytes(pod: Pod, names: tuple[str, ...]) -> bytes:
    """pod_request_row as raw little-endian float32 BYTES, memoized per
    pod: the per-cycle matrix assemblies (`requested` accumulation over
    the running suffix, build_pod_batch's request block) concatenate
    these with one ``b"".join`` + ``np.frombuffer`` — ~0.1ms for an
    8k-pod window, where ``np.array`` over 8k Python-float tuples
    measured ~27ms (each element is a PyFloat unbox)."""
    cache = pod.__dict__.get("_req_bytes_cache")
    if cache is not None and cache[0] is names:
        return cache[1]
    b = np.asarray(pod_request_row(pod, names), np.float32).tobytes()
    pod.__dict__["_req_bytes_cache"] = (names, b)
    return b


def suffix_start(cache: tuple | None, lst: list) -> int:
    """Prefix-identity probe shared by every per-cycle O(running) scan
    (request accumulation, port collection, selector registration,
    running-set feature flags): given the record stored by a prior
    suffix_record(lst), return the index to resume scanning from — 0
    means the prefix cannot be trusted and the caller must rescan.

    Valid only when the caller passed the SAME list object, it has not
    shrunk, and the element at the old boundary is still the same
    object. The sentinel element catches the realistic in-place
    mutations a bare (identity, length) check cannot: a
    remove-then-append that keeps the length monotone shifts a
    different pod into the boundary slot."""
    if (
        cache is not None
        and cache[0] is lst
        and len(lst) >= cache[1]
        and (cache[1] == 0 or lst[cache[1] - 1] is cache[2])
    ):
        return cache[1]
    return 0


def suffix_record(lst: list) -> tuple:
    """The (list, length, boundary sentinel) record suffix_start checks."""
    n = len(lst)
    return (lst, n, lst[n - 1] if n else None)


# SnapshotArrays leaves that are static per node SET: build_snapshot
# serves them from the _node_static cache, so between two builds with the
# same node set they are the SAME array objects — snapshot_delta checks
# identity first and only falls back to a bytewise compare.
_STATIC_LEAVES = (
    "allocatable", "cards", "card_mask", "card_healthy", "taints",
    "taint_mask", "node_labels", "node_label_mask", "image_scaled",
)
# the domain-membership encoding is LAYOUT (selector axis + topology
# partition): any drift forces a full upload. The four float count
# tables over that layout change with ordinary binds and ride deltas as
# row sets, exactly like `requested`.
_DOMAIN_LAYOUT_LEAVES = ("domain_id",)
_DOMAIN_VALUE_LEAVES = (
    "domain_counts", "avoid_counts", "pref_attract", "pref_avoid",
)
_UTIL_LEAVES = ("disk_io", "cpu_pct", "mem_pct", "net_up", "net_down")

# every SnapshotArrays leaf MUST be classified: an unlisted leaf would be
# neither compared (no full-upload flush when it changes) nor shipped in
# the delta — the engine would silently score stale values, breaking the
# PARITY.md delta/full guarantee with no error. Fails loudly at import
# when a new leaf is added to the struct without a classification.
assert (
    set(_STATIC_LEAVES)
    | set(_DOMAIN_LAYOUT_LEAVES)
    | set(_DOMAIN_VALUE_LEAVES)
    | set(_UTIL_LEAVES)
    | {"requested", "node_mask"}
) == set(SnapshotArrays._fields), (
    "snapshot_delta's leaf classification no longer covers "
    "SnapshotArrays — classify the new leaf (static / layout / "
    "row-diffed) before deltas can be trusted"
)


def _rows_padded(rows: np.ndarray, n: int) -> np.ndarray:
    """Bucket-pad a changed-row index vector with the out-of-range
    sentinel `n` (dropped by both delta appliers), so delta shapes stay
    stable and the jitted device apply rarely recompiles."""
    k = bucket_size(max(len(rows), 1), floor=8, multiple=8)
    out = np.full(k, n, np.int32)
    out[: len(rows)] = rows
    return out


def snapshot_delta(
    prev: SnapshotArrays, new: SnapshotArrays, *, max_byte_frac: float = 0.5
) -> SnapshotDelta | None:
    """The cycle-over-cycle change from `prev` (the snapshot the engine
    retains on device) to `new` (this cycle's full host build), or None
    when the change is not delta-expressible and the host must upload in
    full: static-block churn (node add/remove, column-layout growth,
    label/taint/card edits), selector-axis/domain-membership drift, any
    shape change, or a delta payload exceeding `max_byte_frac` of the
    full snapshot (bytes, not rows — a zone-topology bind legitimately
    touches whole-domain row blocks that are still tiny next to the
    static leaves a full upload re-ships).

    Changed rows ride BY VALUE (the exact float32 contents of the new
    build), so applying the delta reproduces `new` bitwise — the
    PARITY.md delta/full bindings guarantee reduces to this function
    never mis-classifying a changed leaf as clean, which the generic
    row-diff below guarantees by construction (it diffs the full
    matrices rather than trusting any cache's account of what moved)."""
    if (
        prev.requested.shape != new.requested.shape
        or prev.domain_counts.shape != new.domain_counts.shape
    ):
        return None
    for name in _STATIC_LEAVES + _DOMAIN_LAYOUT_LEAVES:
        a, b = getattr(prev, name), getattr(new, name)
        if a is b:
            continue
        if a.shape != b.shape or a.dtype != b.dtype or not np.array_equal(a, b):
            return None
    n = int(new.node_mask.shape[0])
    req_changed = np.flatnonzero(
        (np.asarray(prev.requested) != np.asarray(new.requested)).any(axis=1)
    )
    util_diff = np.zeros(n, bool)
    for name in _UTIL_LEAVES:
        util_diff |= np.asarray(getattr(prev, name)) != np.asarray(
            getattr(new, name)
        )
    util_changed = np.flatnonzero(util_diff)
    dom_diff = np.zeros(n, bool)
    for name in _DOMAIN_VALUE_LEAVES:
        a, b = getattr(prev, name), getattr(new, name)
        if a is not b:
            dom_diff |= (np.asarray(a) != np.asarray(b)).any(axis=1)
    dom_changed = np.flatnonzero(dom_diff)
    req_rows = _rows_padded(req_changed, n)
    req_vals = np.zeros((len(req_rows), new.requested.shape[1]), np.float32)
    req_vals[: len(req_changed)] = np.asarray(new.requested)[req_changed]
    util_rows = _rows_padded(util_changed, n)
    util_vals = np.zeros((len(util_rows), 5), np.float32)
    for col, name in enumerate(_UTIL_LEAVES):
        util_vals[: len(util_changed), col] = np.asarray(getattr(new, name))[
            util_changed
        ]
    dom_rows = _rows_padded(dom_changed, n)
    s = int(new.domain_counts.shape[1])
    dom_vals = np.zeros((len(dom_rows), s, 4), np.float32)
    for col, name in enumerate(_DOMAIN_VALUE_LEAVES):
        dom_vals[: len(dom_changed), :, col] = np.asarray(getattr(new, name))[
            dom_changed
        ]
    delta = SnapshotDelta(
        req_rows=req_rows,
        req_vals=req_vals,
        util_rows=util_rows,
        util_vals=util_vals,
        dom_rows=dom_rows,
        dom_vals=dom_vals,
        node_mask=np.asarray(new.node_mask, bool),
    )
    from kubernetes_scheduler_tpu.engine import snapshot_nbytes

    if snapshot_nbytes(delta) > max_byte_frac * snapshot_nbytes(new):
        return None
    return delta


def shard_snapshot_delta(
    delta: SnapshotDelta, n_shards: int, *, prev_node_mask=None
) -> dict:
    """Route a SnapshotDelta to the node shards that own its rows (the
    mesh-sharded resident engine, parallel/engine.ShardedEngine).

    Returns {shard index: SnapshotDelta} with rows in SHARD-LOCAL
    coordinates — each shard's node slice is [i*n_local, (i+1)*n_local)
    and its pad sentinel is n_local (its own axis length), matching
    _rows_padded's convention. The global delta's own pad sentinel (n)
    falls outside every slice and drops out naturally.

    Shards with no changed rows ship NOTHING (absent key): their
    retained buffers are already current, so per-cycle host->device
    payload scales with the change, not the cluster — the flat-bytes
    property the 100k-node gate pins. Exception: when `prev_node_mask`
    (the mask the engine currently retains) is given, a shard whose
    mask slice changed emits even with no changed rows — the mask rides
    whole on every dense delta precisely because it must stay current.

    Each emitted shard's node_mask is its local slice of the new mask."""
    mask = np.asarray(delta.node_mask, bool)
    n = int(mask.shape[0])
    if n_shards <= 0 or n % n_shards:
        raise ValueError(
            f"node axis {n} does not divide into {n_shards} shards"
        )
    n_local = n // n_shards
    prev = (
        None if prev_node_mask is None else np.asarray(prev_node_mask, bool)
    )
    out: dict[int, SnapshotDelta] = {}
    for i in range(n_shards):
        lo, hi = i * n_local, (i + 1) * n_local

        def pick(rows, vals):
            r = np.asarray(rows)
            sel = (r >= lo) & (r < hi)
            return r[sel] - lo, np.asarray(vals, np.float32)[sel]

        rr, rv = pick(delta.req_rows, delta.req_vals)
        ur, uv = pick(delta.util_rows, delta.util_vals)
        dr, dv = pick(delta.dom_rows, delta.dom_vals)
        mask_changed = prev is not None and not np.array_equal(
            prev[lo:hi], mask[lo:hi]
        )
        if not (len(rr) or len(ur) or len(dr) or mask_changed):
            continue

        def repad(rows, vals, trailing):
            padded = _rows_padded(rows, n_local)
            out_vals = np.zeros((len(padded),) + trailing, np.float32)
            out_vals[: len(rows)] = vals
            return padded, out_vals

        req_rows, req_vals = repad(rr, rv, (rv.shape[1],))
        util_rows, util_vals = repad(ur, uv, (5,))
        dom_rows, dom_vals = repad(dr, dv, dv.shape[1:])
        out[i] = SnapshotDelta(
            req_rows=req_rows,
            req_vals=req_vals,
            util_rows=util_rows,
            util_vals=util_vals,
            dom_rows=dom_rows,
            dom_vals=dom_vals,
            node_mask=mask[lo:hi],
        )
    return out


FLAG_PLAIN = 1   # no constraint family beyond score + resource fit
FLAG_SOFT = 2    # carries preferred (soft) score terms


def selector_key(term) -> tuple:
    """The canonical selector identity (matchLabels, matchExpressions,
    topology key, namespace scope) WITHOUT minting an id — the probe the
    snapshot mirror uses to detect selector drift (host/mirror.py), and
    the key _selector_id interns. One definition, so the drift check and
    the interner cannot disagree on what "the same selector" means."""
    exprs = tuple(
        sorted(
            (e.key, e.operator, tuple(sorted(e.values)))
            for e in getattr(term, "match_expressions", None) or []
        )
    )
    namespaces = getattr(term, "namespaces", None)
    ns_key = None if namespaces is None else tuple(sorted(set(namespaces)))
    return (
        tuple(sorted(term.match_labels.items())),
        exprs,
        term.topology_key,
        ns_key,
    )


def pod_flags(pod: Pod) -> int:
    """Per-pod dispatch flags, memoized on the pod object (specs are
    immutable in k8s): the per-cycle eligibility scans probe EVERY
    window pod every cycle, and a retried pod must not re-pay the
    attribute walk."""
    flags = pod.__dict__.get("_flags_cache")
    if flags is None:
        plain = not (
            pod.tolerations or pod.node_affinity or pod.pod_affinity
            or pod.preferred_node_affinity or pod.topology_spread
            or pod.host_ports or pod.target_node is not None
            or any(
                k.startswith("scv/") and k != "scv/priority"
                for k in pod.labels
            )
        )
        soft = bool(
            pod.preferred_node_affinity
            or any(t.preferred for t in pod.pod_affinity)
            or any(sc.soft for sc in pod.topology_spread)
        )
        flags = (FLAG_PLAIN if plain else 0) | (FLAG_SOFT if soft else 0)
        pod.__dict__["_flags_cache"] = flags
    return flags


# packed per-pod scalar block (diskIO, priority, n_containers, flags):
# build_pod_batch reassembles the whole window's scalar columns with one
# b"".join + np.frombuffer over these instead of three np.fromiter
# generator passes
_SCAL_DT = np.dtype(
    [("rio", "<f4"), ("pri", "<i4"), ("nc", "<i4"), ("fl", "<i4")]
)


def pod_batch_record(pod: Pod, names: tuple[str, ...]) -> tuple:
    """The per-pod scalars every batch build re-derives, as ONE cached
    tuple: (names, request_row, diskIO, priority, n_containers, flags,
    request_row_bytes, scalar_bytes). Computed once per pod
    (Scheduler.submit warms it on the admission path); build_pod_batch
    then assembles its vectorized columns from dict hits instead of
    per-pod attribute walks + parses — the difference between ~5us and
    ~1us per pod per cycle at 8k-pod windows. Only the request row (and
    its bytes form) depends on the column layout, so a names change
    recomputes just those slots."""
    rec = pod.__dict__.get("_batch_rec_cache")
    if rec is not None and rec[0] is names:
        return rec
    row = pod_request_row(pod, names)
    row_b = pod_request_bytes(pod, names)
    if rec is not None:
        rec = (names, row) + rec[2:6] + (row_b, rec[7])
    else:
        rio = parse_float_or_zero(pod.annotations.get("diskIO"))
        pri = pod_priority(pod)
        nc = max(len(pod.containers), 1)
        fl = pod_flags(pod)
        rec = (
            names, row, rio, pri, nc, fl, row_b,
            np.array([(rio, pri, nc, fl)], _SCAL_DT).tobytes(),
        )
    pod.__dict__["_batch_rec_cache"] = rec
    return rec


@dataclass
class SnapshotBuilder:
    """Builds (SnapshotArrays, PodBatch) with shared interning tables.

    Axes are padded to power-of-two buckets (utils/padding.py) so the jitted
    engine recompiles only on bucket growth.
    """

    extended_resources: list[str] = field(default_factory=list)
    # gang co-scheduling (config.gang_scheduling): False leaves the
    # PodBatch gang tensors at their no-gang defaults, so the engine's
    # gang mask is bitwise the identity — gang labels are IGNORED, the
    # config contract for gang-off
    gang_scheduling: bool = True
    label_keys: Interner = field(default_factory=Interner)
    label_values: Interner = field(default_factory=Interner)
    # container-image vocabulary for ImageLocality (ops/score.py): ids
    # shared between build_snapshot's [n, V] scaled-size matrix and
    # build_pod_batch's per-pod image-id lists
    images: Interner = field(default_factory=Interner)
    selectors: dict[tuple, int] = field(default_factory=dict)
    # pre-sized selector bucket (config.mirror_initial_selectors): a warm
    # restart that knows the prior run's peak (`trace stats`
    # peak_selector_slots) starts the power-of-two bucket there, so the
    # early crossings (1 -> 2 -> 4 -> ...) — each a mirror flush-to-full
    # and a fresh XLA compile — never happen. Purely a floor: the live
    # selector count still grows the bucket past it as before
    initial_selectors: int = 0
    # hostPort conflict state (upstream NodePorts): each distinct hostPort
    # in flight becomes a capacity-1 pseudo-resource column, so the
    # engine's existing capacity machinery (greedy decrement, auction
    # admission, cross-window carry) enforces conflicts exactly. Slot
    # COUNT is bucketed so shapes (and compiles) stay stable while port
    # membership changes cycle to cycle.
    _port_slots: int = 0
    _port_index: dict = field(default_factory=dict)  # port -> column offset
    # guards the interned-layout memo, the ONE builder cache the feeder
    # thread also touches (Scheduler.submit precomputes pod rows on the
    # informer/submission path while a cycle may be probing the intern)
    _names_lock: object = field(default_factory=threading.Lock)
    # CSI attach-limit capacity columns (upstream NodeVolumeLimits):
    # attachable-volumes-* keys seen in any node's status.allocatable,
    # grow-only so column layout (and compiles) stay stable
    _attach_cols: list = field(default_factory=list)
    # node-name -> index of the latest snapshot (for target_node encoding)
    _node_index: dict = field(default_factory=dict)
    # selector key -> (match_labels dict, [MatchExpression]) parsed once
    # at intern time (_selector_id); the matching loops are O(pods x
    # selectors) per cycle
    _selector_parsed: dict = field(default_factory=dict)

    @property
    def resource_names(self) -> list[str]:
        return (
            list(CANONICAL_NAMES)
            + self.extended_resources
            + self._attach_cols
            + [f"hostport/{i}" for i in range(self._port_slots)]
        )

    def resource_names_tuple(self) -> tuple[str, ...]:
        """Interned tuple form — ONE object per distinct column layout,
        so pod_request_vector's per-pod cache hits on identity instead
        of tuple comparison (the accumulation loop probes it for every
        running pod every cycle). The intern is the one builder memo the
        feeder thread also touches (Scheduler.submit precomputes pod
        rows on the informer path), so it publishes under its own lock —
        once per cycle and per submit, never per pod."""
        names = tuple(self.resource_names)
        with self._names_lock:
            if names != self.__dict__.get("_names_interned"):
                self.__dict__["_names_interned"] = names
            return self.__dict__["_names_interned"]

    def _node_alloc_vec(
        self, nd: Node, names: tuple[str, ...], n_port0: int
    ) -> np.ndarray:
        """[r] allocatable row, memoized on the Node object (node specs
        change only via informer events, which replace the object)."""
        cache = nd.__dict__.get("_alloc_vec_cache")
        if cache is not None and cache[0] is names:
            return cache[1]
        get = nd.allocatable.get
        vec = np.zeros(len(names), np.float32)
        for j in range(n_port0):
            vec[j] = get(names[j], 0.0)
        nd.__dict__["_alloc_vec_cache"] = (names, vec)
        return vec

    def _node_taint_enc(self, nd: Node) -> np.ndarray | None:
        """[t, 3] interned taint triples per node, memoized on the Node
        object KEYED on this builder's interners (ids are append-only
        within one builder, but a second builder's fresh tables assign
        different ids — an unkeyed cache would silently mis-encode);
        None = no taints."""
        if not nd.taints:
            return None
        cache = nd.__dict__.get("_taint_enc_cache")
        if (
            cache is not None
            and cache[0] is self.label_keys
            and cache[1] is self.label_values
        ):
            return cache[2]
        enc = np.array(
            [
                (
                    self.label_keys.id(t.key),
                    self.label_values.id(t.value),
                    _EFFECTS.get(t.effect, C.NO_SCHEDULE),
                )
                for t in nd.taints
            ],
            np.int32,
        )
        nd.__dict__["_taint_enc_cache"] = (
            self.label_keys, self.label_values, enc,
        )
        return enc

    def _node_label_enc(self, nd: Node) -> np.ndarray:
        """[1 + l, 2] interned (key, value) pairs: the synthetic
        metadata.name entry first (matchFields), then the node's labels.
        Memoized per Node object, keyed on this builder's interners
        (see _node_taint_enc)."""
        cache = nd.__dict__.get("_label_enc_cache")
        if (
            cache is not None
            and cache[0] is self.label_keys
            and cache[1] is self.label_values
        ):
            return cache[2]
        pairs = [
            (self.label_keys.id("metadata.name"), self.label_values.id(nd.name))
        ]
        for k, v in nd.labels.items():
            if k == "metadata.name":
                # reserved for the synthetic field entry: a USER label
                # under this (syntactically legal) key would satisfy
                # matchFields selectors upstream only reads from the
                # object field — skip it, loudly
                log.warning(
                    "node %s: ignoring label 'metadata.name' "
                    "(reserved for matchFields)", nd.name,
                )
                continue
            pairs.append((self.label_keys.id(k), self.label_values.id(v)))
        enc = np.array(pairs, np.int32)
        nd.__dict__["_label_enc_cache"] = (
            self.label_keys, self.label_values, enc,
        )
        return enc

    def _assign_port_slots(
        self,
        running: list[Pod],
        pending: list[Pod],
        *,
        ephemeral: bool = False,
        pending_all_plain: bool = False,
    ) -> None:
        # The running set is scanned with a prefix-identity cache: the
        # host loop passes the SAME (append-only) list every cycle, so
        # only pods bound since the last build are walked. A rebuilt list
        # (live informer) falls back to a full scan. Ports of completed
        # prefix pods may linger a cycle as empty capacity-1 columns —
        # harmless (no node requests them).
        pc = self.__dict__.get("_ports_prefix")
        start = suffix_start(pc[0] if pc else None, running)
        base = pc[1] if start else set()
        for pod in running[start:]:
            # flag probe first: FLAG_PLAIN pods carry no hostPorts, and
            # the dict hit is cheaper than the dataclass attribute walk
            # on the (overwhelmingly common) unconstrained pod
            fl = pod.__dict__.get("_flags_cache")
            if fl is not None and fl & FLAG_PLAIN:
                continue
            if pod.host_ports:
                base.update(pod.host_ports)
        if not ephemeral:
            self.__dict__["_ports_prefix"] = (suffix_record(running), base)
        # a window the caller certifies all-FLAG_PLAIN has no hostPorts
        if pending_all_plain:
            pending = []
        ports = base if not pending else set(base)
        if pending:
            for pod in pending:
                if pod.host_ports:
                    ports.update(pod.host_ports)
        ports = sorted(ports)
        if len(ports) > self._port_slots:
            self._port_slots = bucket_size(len(ports), floor=1, multiple=1)
        self._port_index = {pt: i for i, pt in enumerate(ports)}

    # ---- node side ----------------------------------------------------

    def build_snapshot(
        self,
        nodes: list[Node],
        utils: dict[str, NodeUtil],
        running_pods: list[Pod],
        *,
        pending_pods: list[Pod] | None = None,
        ephemeral: bool = False,
        pending_all_plain: bool = False,
    ) -> SnapshotArrays:
        """ephemeral=True builds against a throwaway running list (the
        preemption pass's `running + cycle_bound` concatenation) without
        RECORDING the prefix caches — an ephemeral list stored there
        would evict the steady-state records the next main-cycle build
        depends on. Reads still probe the caches (and miss, harmlessly,
        on identity).

        pending_all_plain=True is the caller's certificate that every
        pending pod is FLAG_PLAIN (the scheduler aggregates window flags
        once per cycle), letting the port and selector pre-scans skip
        the window entirely."""
        self._assign_port_slots(
            running_pods, pending_pods or [], ephemeral=ephemeral,
            pending_all_plain=pending_all_plain,
        )
        # The node side of the snapshot is static per node SET: every
        # array below depends only on the Node objects (informer updates
        # replace the object, changing its id), so the whole block is
        # cached keyed on the tuple of object identities + the column
        # layout. At 4k nodes the rebuild is ~15ms of Python per cycle
        # for state that changes only on node events. The cache pins the
        # node objects (nodes_ref) so ids cannot be recycled.
        node_ids = tuple(map(id, nodes))
        sc = self.__dict__.get("_node_static")
        if sc is None or sc["ids"] != node_ids:
            # node set changed: rescan for NodeVolumeLimits capacity
            # columns (attachable-volumes-* allocatable keys)
            seen_attach = {
                k
                for nd in nodes
                for k in nd.allocatable
                if k.startswith("attachable-volumes-")
            }
            new_attach = sorted(seen_attach - set(self._attach_cols))
            if new_attach:
                self._attach_cols.extend(new_attach)
            sc = None
        names = self.resource_names
        r = len(names)
        n_port0 = len(names) - self._port_slots  # first port column
        n_real = len(nodes)
        n = bucket_size(n_real)
        names_t = self.resource_names_tuple()

        if sc is not None and sc["names_t"] is names_t:
            node_index = sc["node_index"]
            alloc = sc["alloc"]
            mask = sc["mask"]
            cards, card_mask, card_healthy = sc["cards"]
            taints, taint_mask = sc["taints"]
            labels, label_mask = sc["labels"]
            image_scaled = sc["image_scaled"]
        else:
            node_index = {nd.name: i for i, nd in enumerate(nodes)}
            alloc = np.zeros((n, r), np.float32)
            mask = np.zeros(n, bool)
            mask[:n_real] = True
            # allocatable rows memoized per Node object (informer events
            # replace the object, invalidating naturally)
            if n_real:
                alloc[:n_real] = np.stack(
                    [self._node_alloc_vec(nd, names_t, n_port0) for nd in nodes]
                )
            # every real node offers each hostPort slot exactly once
            alloc[:n_real, n_port0:] = 1.0

            # node-side bucket maxima in one pass (three full-node
            # generator scans otherwise)
            m_cards = m_taints = m_labels = 0
            for nd in nodes:
                if len(nd.cards) > m_cards:
                    m_cards = len(nd.cards)
                if len(nd.taints) > m_taints:
                    m_taints = len(nd.taints)
                if len(nd.labels) > m_labels:
                    m_labels = len(nd.labels)

            # cards
            c_max = bucket_size(m_cards, floor=1, multiple=1)
            cards = np.zeros((n, c_max, 6), np.float32)
            card_mask = np.zeros((n, c_max), bool)
            card_healthy = np.zeros((n, c_max), bool)
            if m_cards:
                for i, nd in enumerate(nodes):
                    for j, card in enumerate(nd.cards):
                        cards[i, j] = [getattr(card, m) for m in _CARD_METRICS]
                        card_mask[i, j] = True
                        card_healthy[i, j] = card.health == "Healthy"

            # taints (per-node encodings memoized — _node_taint_enc)
            t_max = bucket_size(m_taints, floor=1, multiple=1)
            taints = np.zeros((n, t_max, 3), np.int32)
            taint_mask = np.zeros((n, t_max), bool)
            if m_taints:
                for i, nd in enumerate(nodes):
                    enc = self._node_taint_enc(nd)
                    if enc is not None:
                        taints[i, : len(enc)] = enc
                        taint_mask[i, : len(enc)] = True

            # labels — plus one synthetic `metadata.name` entry per node,
            # so node-affinity matchFields (upstream: metadata.name
            # selectors) evaluate through the ordinary label-expression
            # kernel; per-node encodings memoized (_node_label_enc)
            l_max = bucket_size(m_labels + 1, floor=1, multiple=1)
            labels = np.zeros((n, l_max, 2), np.int32)
            label_mask = np.zeros((n, l_max), bool)
            for i, nd in enumerate(nodes):
                enc = self._node_label_enc(nd)
                labels[i, : len(enc)] = enc
                label_mask[i, : len(enc)] = True

            # ImageLocality signal: scaled size = present * sizeBytes *
            # (nodes holding the image / real nodes) — the upstream
            # scaledImageScore's spread ratio, resolved here so the
            # engine kernel is a pure gather (shards along the node axis
            # with no collective). The vocabulary only grows for images a
            # node actually holds; pod-side ids for never-seen images
            # stay -1-free but score 0 (zero column).
            for nd in nodes:
                for img in nd.images:
                    self.images.id(img)
            v = bucket_size(max(len(self.images), 1), floor=1, multiple=1)
            image_scaled = np.zeros((n, v), np.float32)
            if len(self.images) and n_real:
                holders = np.zeros(v, np.float32)
                for nd in nodes:
                    for img in nd.images:
                        holders[self.images.id(img)] += 1.0
                ratio = holders / float(n_real)
                for i, nd in enumerate(nodes):
                    for img, size in nd.images.items():
                        j = self.images.id(img)
                        image_scaled[i, j] = float(size) * ratio[j]
            self.__dict__["_node_static"] = {
                "ids": node_ids,
                "names_t": names_t,
                "nodes_ref": list(nodes),
                "names": [nd.name for nd in nodes],
                "node_index": node_index,
                "alloc": alloc,
                "mask": mask,
                "cards": (cards, card_mask, card_healthy),
                "taints": (taints, taint_mask),
                "labels": (labels, label_mask),
                "image_scaled": image_scaled,
            }
        self._node_index = node_index

        # utilization series are rebuilt EVERY cycle — advisors may
        # legitimately mutate NodeUtil values in place between fetches
        # (StaticAdvisor returns its own dict), so no identity cache is
        # sound here. The fill is batch-assembled: one tuple-comprehension
        # over the cached node-name list into a single np.array, instead
        # of five scalar ndarray writes per node (the span data put the
        # per-element loop at ~4ms of every 4k-node snapshot_build; this
        # path is ~3x less)
        node_names = self.__dict__["_node_static"]["names"]
        get_util = utils.get
        zero5 = (0.0, 0.0, 0.0, 0.0, 0.0)
        util_block = np.zeros((n, 5), np.float32)
        if n_real:
            util_block[:n_real] = np.array(
                [
                    (u.disk_io, u.cpu_pct, u.mem_pct, u.net_up, u.net_down)
                    if (u := get_util(name)) is not None
                    else zero5
                    for name in node_names
                ],
                np.float32,
            )
        disk_io = np.ascontiguousarray(util_block[:, 0])
        cpu_pct = np.ascontiguousarray(util_block[:, 1])
        mem_pct = np.ascontiguousarray(util_block[:, 2])
        net_up = np.ascontiguousarray(util_block[:, 3])
        net_down = np.ascontiguousarray(util_block[:, 4])

        # NonZeroRequested accumulation over running pods
        # (algorithm.go:219-221), incremental: the host loop passes the
        # SAME append-only running list every cycle, so the accumulated
        # matrix is carried across cycles and only pods bound since the
        # last build are summed in (request vectors memoized per pod).
        # A rebuilt list, node-set change, or column-layout change falls
        # back to a full re-accumulation — the round-4 verdict's
        # "incremental snapshot builds" item.
        pods_col = names.index("pods")
        acc = self.__dict__.get("_acc_cache")
        start = 0
        use_acc = False
        if (
            acc is not None
            and acc["names_t"] is names_t
            and acc["node_index"] is node_index
            # port->column mapping can be remapped without a column-count
            # change (slots are bucketed); prefix port contributions
            # would then sit in stale columns
            and acc["port_index"] == self._port_index
        ):
            start = suffix_start(acc["prefix"], running_pods)
            use_acc = start > 0
            pending = acc.get("pending")
            if pending is not None:
                # apply_assignment_deltas (pipelined loop) pre-summed
                # these binds into the retained matrix; trust it ONLY if
                # the informer appended exactly those pod objects right
                # after the recorded prefix. Any other churn means the
                # matrix holds contributions for pods not in the list —
                # rebuild from zeros, never serve a stale delta.
                k = len(pending)
                prefix_valid = start > 0 or (
                    acc["prefix"][1] == 0
                    and acc["prefix"][0] is running_pods
                )
                if (
                    prefix_valid
                    and len(running_pods) >= start + k
                    and all(
                        running_pods[start + i] is pending[i]
                        for i in range(k)
                    )
                ):
                    start += k
                    use_acc = True
                else:
                    start = 0
                    use_acc = False
        if use_acc:
            requested = acc["requested"].copy()
        else:
            requested = np.zeros((n, r), np.float32)
        suffix = running_pods[start:] if start else running_pods
        if suffix:
            rows = np.fromiter(
                (node_index.get(pod.node_name, -1) for pod in suffix),
                np.int64, count=len(suffix),
            )
            # request rows as cached BYTES, one frombuffer for the whole
            # suffix (np.array over 8k Python-float tuples measured
            # ~27ms/cycle; this path is ~3ms probe loop + ~0.1ms join)
            mat = np.frombuffer(
                b"".join([
                    c[1]
                    if (c := pod.__dict__.get("_req_bytes_cache"))
                    is not None and c[0] is names_t
                    else pod_request_bytes(pod, names_t)
                    for pod in suffix
                ]),
                np.float32,
            ).reshape(len(suffix), r)
            keep = rows >= 0
            np.add.at(requested, rows[keep], mat[keep])
            np.add.at(requested[:, pods_col], rows[keep], 1.0)
            for pod in suffix:
                if pod.host_ports and pod.node_name in node_index:
                    i = node_index[pod.node_name]
                    for pt in pod.host_ports:
                        requested[i, n_port0 + self._port_index[pt]] += 1
        if not ephemeral:
            self.__dict__["_acc_cache"] = {
                "prefix": suffix_record(running_pods),
                "names_t": names_t,
                "node_index": node_index,
                "port_index": dict(self._port_index),
                "requested": requested.copy(),
            }

        (domain_counts, domain_id, avoid_counts,
         pref_attract, pref_avoid) = self._domain_counts(
            nodes,
            running_pods,
            [] if pending_all_plain else (pending_pods or []),
            n,
            ephemeral=ephemeral,
        )

        # HOST-side numpy arrays, deliberately NOT jnp (make_snapshot
        # would device_put them): on a remote/tunneled device every
        # later host-side probe (np.asarray for option checks, shapes,
        # gRPC packing) would pay a device readback round-trip — ~100 ms
        # each over the dev tunnel, measured dominating the host loop.
        # The engine's jit call (or the bridge codec) transfers the
        # buffers exactly once either way.
        return SnapshotArrays(
            allocatable=alloc, requested=requested, disk_io=disk_io,
            cpu_pct=cpu_pct, mem_pct=mem_pct, net_up=net_up,
            net_down=net_down, node_mask=mask, cards=cards,
            card_mask=card_mask, card_healthy=card_healthy, taints=taints,
            taint_mask=taint_mask, node_labels=labels,
            node_label_mask=label_mask, domain_counts=domain_counts,
            domain_id=domain_id, avoid_counts=avoid_counts,
            pref_attract=pref_attract, pref_avoid=pref_avoid,
            image_scaled=image_scaled,
        )

    def apply_assignment_deltas(
        self, bound_pods: list[Pod], node_rows, request_rows
    ) -> bool:
        """Incremental snapshot carry for the pipelined host loop: fold
        a cycle's successful binds into the retained accumulated
        `requested` matrix in place — one vectorized scatter-add of the
        dispatched PodBatch's dense request rows (which already carry
        the pods column and the hostPort columns, exactly the suffix
        scan's contribution) — so the NEXT build_snapshot skips
        re-walking them when the informer appends exactly these pod
        objects to the running list.

        Returns False (accumulator untouched) when nothing is retained
        or the layout moved underneath: column set, node set, or port
        mapping changed, or a previous delta is still unconfirmed. The
        next build then does the ordinary suffix scan. The anticipated
        suffix is verified by identity at the next build (see the
        `pending` check there): any informer event that breaks it —
        node add/remove rebuilds node_index, running-set churn fails
        the suffix identity check, an advisor refresh only touches the
        per-cycle utilization series which are rebuilt every build
        anyway — forces the full re-accumulation, so a stale delta is
        never silently trusted."""
        acc = self.__dict__.get("_acc_cache")
        if acc is None or not bound_pods:
            return False
        # hostPort-bearing pods take the suffix scan: the dense batch
        # SETS a port cell to 1 where the scan INCREMENTS per host_ports
        # entry, so a duplicated port (TCP+UDP on one number) would
        # diverge between the delta and a full rebuild — and these pods
        # are rare enough that the rescan costs nothing
        for pd in bound_pods:
            fl = pd.__dict__.get("_flags_cache")
            if (fl is None or not fl & FLAG_PLAIN) and pd.host_ports:
                return False
        req = acc["requested"]
        rows = np.asarray(node_rows, np.int64).reshape(-1)
        mat = np.asarray(request_rows, np.float32)
        if (
            acc["names_t"] is not self.resource_names_tuple()
            or acc["node_index"] is not self._node_index
            or acc["port_index"] != self._port_index
            or acc.get("pending") is not None
            or mat.shape != (len(bound_pods), req.shape[1])
            or rows.shape != (len(bound_pods),)
            or bool((rows < 0).any())
            or bool((rows >= req.shape[0]).any())
        ):
            return False
        np.add.at(req, rows, mat)
        acc["pending"] = list(bound_pods)
        return True

    def _selector_id(self, term) -> int:
        """Selector identity = (matchLabels, matchExpressions, topology
        key, namespace scope); expressions are canonicalized so
        semantically identical selectors share one id/domain-count
        column. The parsed form is memoized per key: the matching loops
        probe O(pods x selectors) per cycle and must not re-build
        dicts/dataclasses per probe."""
        from kubernetes_scheduler_tpu.host.types import MatchExpression

        key = selector_key(term)
        exprs = key[1]
        if key not in self.selectors:
            self.selectors[key] = len(self.selectors)
            self._selector_parsed[key] = (
                dict(key[0]),
                [
                    MatchExpression(key=k, operator=o, values=list(vs))
                    for k, o, vs in exprs
                ],
            )
        return self.selectors[key]

    def _key_matches(self, pod: Pod, key) -> bool:
        """Does a pod satisfy an interned selector key — labels AND
        namespace scope (upstream inter-pod selectors match only the
        listed namespaces; None = all)? matchLabels-only selectors (the
        common case) stay a plain tuple walk; expression selectors use
        the memoized parsed form."""
        from kubernetes_scheduler_tpu.host.types import (
            MatchExpression,
            labels_match,
        )

        items, exprs, _topo, ns_key = key
        if ns_key is not None and pod.namespace not in ns_key:
            return False
        if not exprs:
            return all(pod.labels.get(k) == v for k, v in items)
        parsed = self._selector_parsed.get(key)
        if parsed is None:  # selectors persisted from an older builder
            parsed = (
                dict(items),
                [
                    MatchExpression(key=k, operator=o, values=list(vs))
                    for k, o, vs in exprs
                ],
            )
            self._selector_parsed[key] = parsed
        return labels_match(pod.labels, parsed[0], parsed[1])

    def _selector_slots(self) -> int:
        return bucket_size(
            max(len(self.selectors), self.initial_selectors, 1),
            floor=1, multiple=1,
        )

    def _domain_counts(
        self,
        nodes: list[Node],
        running: list[Pod],
        pending: list[Pod],
        n: int,
        *,
        ephemeral: bool = False,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """For every distinct (selector, topology_key) used by the pending
        window: count running pods matching the selector, aggregated over
        each node's topology domain (exact for matchLabels selectors —
        conjunction checked per running pod host-side, which is O(pods x
        selectors) once per cycle).

        Also returns domain_id[n, S]: each node's topology domain for
        selector s, encoded as the index of the first node in that domain,
        so the engine's in-window placement counts stay statically shaped
        (ops/assign.py AffinityState); and avoid_counts[n, S]: running
        AVOIDERS per domain — pods whose required anti-affinity terms use
        selector s — gating the reverse anti-affinity direction (upstream
        InterPodAffinity checks existing pods' anti terms against the
        incoming pod too)."""
        for pod in pending:
            # plain pods (cached flags) carry neither affinity terms nor
            # spread constraints — skip their attribute walk
            fl = pod.__dict__.get("_flags_cache")
            if fl is not None and fl & FLAG_PLAIN:
                continue
            for term in pod.pod_affinity:
                self._selector_id(term)
            for sc in pod.topology_spread:
                self._selector_id(sc)
        # running pods' terms also define selectors: REQUIRED anti terms
        # gate the reverse hard direction; PREFERRED terms feed the
        # symmetric soft scoring (pref_attract/pref_avoid). Selector
        # registration is append-only, so the scan runs with a
        # prefix-identity cache: only pods added to the (same, append-
        # only) running list since the last build are walked.
        start = suffix_start(self.__dict__.get("_dc_prefix"), running)
        for pod in running[start:] if start else running:
            fl = pod.__dict__.get("_flags_cache")
            if fl is not None and fl & FLAG_PLAIN:
                continue  # plain pods carry no pod_affinity terms
            for term in pod.pod_affinity:
                if term.preferred or term.anti:
                    self._selector_id(term)
        if not ephemeral:
            self.__dict__["_dc_prefix"] = suffix_record(running)
        s = self._selector_slots()
        if not self.selectors:
            counts = np.zeros((n, s), np.float32)
            domain_id = np.tile(
                np.arange(n, dtype=np.int32)[:, None], (1, s)
            )
            return counts, domain_id, counts.copy(), counts.copy(), counts.copy()
        # Incremental raw tables (ROADMAP follow-up: skip the rebuild of
        # provably-unchanged snapshot sections). The O(running x
        # selectors) matching scan is the dominant cost here, and the
        # host loop passes the SAME append-only running list cycle after
        # cycle — so the per-node raw tables are carried across builds
        # and only pods appended since the last build are matched.
        # Invalidation is exact: any node-set change (object identities),
        # any selector minted since (prefix pods were never matched
        # against it), or a rebuilt/shrunk running list falls back to the
        # full scan. Accumulation order is prefix-then-suffix, the same
        # order the full scan sums in — bitwise identical outputs.
        n_real = len(nodes)
        node_ids = tuple(map(id, nodes))
        rc = self.__dict__.get("_dc_raw")
        start = 0
        if (
            rc is not None
            and rc["node_ids"] == node_ids
            and rc["n_sel"] == len(self.selectors)
            and rc["s"] == s
        ):
            start = suffix_start(rc["prefix"], running)
        if start:
            raw, raw_avoid, raw_attract_w, raw_avoid_w = rc["tables"]
            if ephemeral:
                # a throwaway build must never mutate the retained tables
                raw = raw.copy()
                raw_avoid = raw_avoid.copy()
                raw_attract_w = raw_attract_w.copy()
                raw_avoid_w = raw_avoid_w.copy()
        else:
            rc = None
            raw = np.zeros((n_real, s), np.float32)
            raw_avoid = np.zeros((n_real, s), np.float32)
            raw_attract_w = np.zeros((n_real, s), np.float32)
            raw_avoid_w = np.zeros((n_real, s), np.float32)
        suffix = running[start:] if start else running
        if suffix:
            node_index = {nd.name: i for i, nd in enumerate(nodes)}
            for pod in suffix:
                i = node_index.get(pod.node_name)
                if i is None:
                    continue
                for key, sid in self.selectors.items():
                    if self._key_matches(pod, key):
                        raw[i, sid] += 1
                for term in pod.pod_affinity:
                    # intern ONLY the term kinds the pre-intern loop above
                    # registered (preferred/anti): a required attract term
                    # of a running pod would otherwise mint a fresh
                    # selector id AFTER the arrays were sized to s — an
                    # index crash
                    if term.preferred:
                        sid = self._selector_id(term)
                        (raw_avoid_w if term.anti else raw_attract_w)[i, sid] += term.weight
                    elif term.anti:
                        raw_avoid[i, self._selector_id(term)] += 1
        if not ephemeral:
            unchanged = rc is not None and not suffix
            if not unchanged:
                rc = {
                    "node_ids": node_ids,
                    # pin the node OBJECTS so their ids cannot be
                    # recycled under the cache (same rule as the
                    # _node_static cache's nodes_ref)
                    "nodes_ref": list(nodes),
                    "n_sel": len(self.selectors),
                    "s": s,
                    "tables": (raw, raw_avoid, raw_attract_w, raw_avoid_w),
                    "out": None,
                }
                self.__dict__["_dc_raw"] = rc
            rc["prefix"] = suffix_record(running)
            if unchanged and rc["out"] is not None:
                # nothing moved since the last build: serve the SAME
                # output arrays, so snapshot_delta's identity fast path
                # skips diffing the four [n, S] tables entirely
                return rc["out"]
        counts = np.zeros((n, s), np.float32)
        avoid = np.zeros((n, s), np.float32)
        attract_w = np.zeros((n, s), np.float32)
        avoid_w = np.zeros((n, s), np.float32)
        # default: every node is its own (hostname) domain
        domain_id = np.tile(
            np.arange(n, dtype=np.int32)[:, None], (1, s)
        )
        # aggregate over topology domains
        for (_items, _exprs, topo, _ns), sid in self.selectors.items():
            sums: dict[str, list[float]] = {}
            first: dict[str, int] = {}
            for i, nd in enumerate(nodes):
                d = nd.name if topo == "kubernetes.io/hostname" else nd.labels.get(topo, "")
                acc = sums.setdefault(d, [0.0, 0.0, 0.0, 0.0])
                acc[0] += raw[i, sid]
                acc[1] += raw_avoid[i, sid]
                acc[2] += raw_attract_w[i, sid]
                acc[3] += raw_avoid_w[i, sid]
                first.setdefault(d, i)
            for i, nd in enumerate(nodes):
                d = nd.name if topo == "kubernetes.io/hostname" else nd.labels.get(topo, "")
                counts[i, sid], avoid[i, sid], attract_w[i, sid], avoid_w[i, sid] = sums[d]
                domain_id[i, sid] = first[d]
        out = (counts, domain_id, avoid, attract_w, avoid_w)
        if not ephemeral:
            self.__dict__["_dc_raw"]["out"] = out
        return out

    # ---- pod side ------------------------------------------------------

    def build_pod_batch(self, pods: list[Pod], recs: list | None = None) -> PodBatch:
        names = self.resource_names
        r = len(names)
        p_real = len(pods)
        p = bucket_size(p_real)
        names_t = self.resource_names_tuple()
        # one cached record per pod (request row, diskIO, priority,
        # container count, dispatch flags, byte-packed forms) — warmed on
        # the admission path (Scheduler.submit), so a steady-state window
        # costs one inline dict probe per pod here instead of the
        # attribute walks + parses (the probe is inlined because even the
        # memoized function call measured ~1.3us x 8k pods per cycle).
        # The scheduler's _window_flags pass hands its records in so one
        # cycle walks the window once. A handed-in list is only trusted
        # when its layout matches: build_snapshot may have grown the
        # column set (new hostPort slots / attach columns) since the
        # records were assembled.
        if recs is not None and recs and recs[0][0] is not names_t:
            recs = None
        if recs is None:
            recs = [
                rc
                if (rc := pd.__dict__.get("_batch_rec_cache")) is not None
                and rc[0] is names_t
                else pod_batch_record(pd, names_t)
                for pd in pods
            ]

        request = np.zeros((p, r), np.float32)
        r_io = np.zeros(p, np.float32)
        priority = np.zeros(p, np.int32)
        pod_mask = np.zeros(p, bool)
        pod_mask[:p_real] = True
        want_number = np.zeros(p, np.int32)
        want_memory = np.full(p, -1.0, np.float32)
        want_clock = np.full(p, -1.0, np.float32)
        n_containers = np.ones(p, np.int32)

        pods_col = names.index("pods")
        # scalar columns from the cached byte blocks: ONE join+frombuffer
        # for the window (np.array over 8k Python-float tuples measured
        # ~27ms/cycle, three np.fromiter passes another ~3ms; this path
        # is C-speed throughout — round-4 verdict "what's weak" #1)
        if p_real:
            request[:p_real] = np.frombuffer(
                b"".join([rc[6] for rc in recs]), np.float32
            ).reshape(p_real, r)
            request[:p_real, pods_col] = 1
            scal = np.frombuffer(b"".join([rc[7] for rc in recs]), _SCAL_DT)
            # diskIO annotation (algorithm.go:103; unparsable -> 0)
            r_io[:p_real] = scal["rio"]
            # spec.priority (PriorityClass) wins; else the scv/priority
            # label (sort.go:12-18) — one definition with the queue's
            priority[:p_real] = scal["pri"]
            # ImageLocality threshold scale = container count
            n_containers[:p_real] = scal["nc"]
            flags_vec = scal["fl"]
            m_cont = int(scal["nc"].max())
            plain_vec = (flags_vec & FLAG_PLAIN) != 0
            all_plain = bool(plain_vec.all())
            constrained = (
                () if all_plain else np.flatnonzero(~plain_vec).tolist()
            )
        else:
            m_cont = 0
            all_plain = True
            constrained = ()

        # bucket maxima in one pass over the CONSTRAINED pods only
        # (FLAG_PLAIN pods — the common shape — carry none of these)
        m_tol = m_na = m_nav = m_aff = m_sp_h = m_sp_s = 0
        m_pref = m_prefv = 0
        for i in constrained:
            pd = pods[i]
            if pd.tolerations:
                m_tol = max(m_tol, len(pd.tolerations))
            if pd.node_affinity:
                m_na = max(m_na, len(pd.node_affinity))
                for e in pd.node_affinity:
                    if len(e.values) > m_nav:
                        m_nav = len(e.values)
            if pd.pod_affinity:
                m_aff = max(m_aff, len(pd.pod_affinity))
            if pd.topology_spread:
                soft_n = sum(1 for sc in pd.topology_spread if sc.soft)
                m_sp_s = max(m_sp_s, soft_n)
                m_sp_h = max(m_sp_h, len(pd.topology_spread) - soft_n)
            if pd.preferred_node_affinity:
                m_pref = max(m_pref, len(pd.preferred_node_affinity))
                for w in pd.preferred_node_affinity:
                    if len(w.expr.values) > m_prefv:
                        m_prefv = len(w.expr.values)

        l_max = bucket_size(m_tol, floor=1, multiple=1)
        tols = np.zeros((p, l_max, 4), np.int32)
        tol_mask = np.zeros((p, l_max), bool)
        e_max = bucket_size(m_na, floor=1, multiple=1)
        v_max = bucket_size(m_nav, floor=1, multiple=1)
        na_key = np.zeros((p, e_max), np.int32)
        na_op = np.zeros((p, e_max), np.int32)
        na_vals = np.zeros((p, e_max, v_max), np.int32)
        na_val_mask = np.zeros((p, e_max, v_max), bool)
        na_mask = np.zeros((p, e_max), bool)
        na_term = np.zeros((p, e_max), np.int32)
        k_max = bucket_size(m_aff, floor=1, multiple=1)
        aff = np.full((p, k_max), -1, np.int32)
        anti = np.full((p, k_max), -1, np.int32)
        pref_aff = np.full((p, k_max), -1, np.int32)
        pref_aff_w = np.zeros((p, k_max), np.float32)
        pref_anti = np.full((p, k_max), -1, np.int32)
        pref_anti_w = np.zeros((p, k_max), np.float32)
        ks_max = bucket_size(m_sp_h, floor=1, multiple=1)
        spread_sel = np.full((p, ks_max), -1, np.int32)
        spread_max = np.ones((p, ks_max), np.int32)
        kss_max = bucket_size(m_sp_s, floor=1, multiple=1)
        soft_spread_sel = np.full((p, kss_max), -1, np.int32)
        target_node = np.full(p, -1, np.int32)
        ep_max = bucket_size(m_pref, floor=1, multiple=1)
        pv_max = bucket_size(m_prefv, floor=1, multiple=1)
        pna_key = np.zeros((p, ep_max), np.int32)
        pna_op = np.zeros((p, ep_max), np.int32)
        pna_vals = np.zeros((p, ep_max, pv_max), np.int32)
        pna_val_mask = np.zeros((p, ep_max, pv_max), bool)
        pna_mask = np.zeros((p, ep_max), bool)
        pna_weight = np.zeros((p, ep_max), np.float32)
        # default: every expression its own preferred term
        pna_term = np.tile(np.arange(ep_max, dtype=np.int32), (p, 1))

        ki_max = bucket_size(m_cont, floor=1, multiple=1)
        image_ids = np.full((p, ki_max), -1, np.int32)

        # gang co-scheduling (ops/gang.py): dense window-local slot ids
        # by first appearance + the declared size. Gang pods carry an
        # scv/ label, so they are always in `constrained` — plain
        # windows never pay this pass. With the knob off the tensors
        # stay at their no-gang defaults (the engine mask is then the
        # identity): gang labels are ignored entirely.
        gang_id = np.full(p, -1, np.int32)
        gang_size = np.zeros(p, np.int32)
        if self.gang_scheduling:
            gang_slots: dict[str, int] = {}
            for i in constrained:
                g = pod_gang(pods[i])
                if g is not None:
                    gang_id[i] = gang_slots.setdefault(g[0], len(gang_slots))
                    gang_size[i] = g[1]

        n_port0 = len(names) - self._port_slots
        has_image_vocab = len(self.images) > 0
        if has_image_vocab:
            # container images mapped through the node-side vocabulary
            # (lookup-only — an image on no node scores 0 and must not
            # grow the table the snapshot matrix was sized against);
            # with no vocabulary every id stays -1
            for i, pod in enumerate(pods):
                for j, c in enumerate(pod.containers[:ki_max]):
                    if c.image:
                        image_ids[i, j] = self.images.lookup(c.image)
        for i in constrained:
            pod = pods[i]
            labels = pod.labels
            has_gpu_labels = (
                "scv/number" in labels
                or "scv/memory" in labels
                or "scv/clock" in labels
            )
            for pt in pod.host_ports:
                # ports outside the table mean build_snapshot did not see
                # this window (_assign_port_slots) — fail loud
                request[i, n_port0 + self._port_index[pt]] = 1
            if pod.target_node is not None:
                # unknown node name -> out-of-range index: infeasible
                # everywhere (constraints.node_name_fit)
                target_node[i] = self._node_index.get(pod.target_node, p + 2**20)
            j_hard = j_soft = 0
            for sc in pod.topology_spread:
                if sc.soft:
                    soft_spread_sel[i, j_soft] = self._selector_id(sc)
                    j_soft += 1
                else:
                    spread_sel[i, j_hard] = self._selector_id(sc)
                    spread_max[i, j_hard] = sc.max_skew
                    j_hard += 1
            # GPU demands (filter.go:11-50): a pod with any scv demand label
            # but no explicit number wants 1 card
            if has_gpu_labels:
                want_number[i] = (
                    parse_int_or_zero(pod.labels["scv/number"])
                    if "scv/number" in pod.labels
                    else 1
                )
                if "scv/memory" in pod.labels:
                    want_memory[i] = parse_int_or_zero(pod.labels["scv/memory"])
                if "scv/clock" in pod.labels:
                    want_clock[i] = parse_int_or_zero(pod.labels["scv/clock"])
            for j, t in enumerate(pod.tolerations):
                tols[i, j] = (
                    -1 if t.key is None else self.label_keys.id(t.key),
                    self.label_values.id(t.value),
                    C.TOL_EXISTS if t.operator == "Exists" else C.TOL_EQUAL,
                    0 if not t.effect else _EFFECTS.get(t.effect, 0),
                )
                tol_mask[i, j] = True
            for j, e in enumerate(pod.node_affinity):
                na_key[i, j] = self.label_keys.id(e.key)
                na_op[i, j] = _NA_OPS[e.operator]
                na_mask[i, j] = True
                # OR-group id (upstream nodeSelectorTerms); the engine
                # requires ids < E, and convert.py emits dense ids
                na_term[i, j] = min(e.term, e_max - 1)
                for q, v in enumerate(e.values):
                    na_vals[i, j, q] = self.label_values.id(v)
                    na_val_mask[i, j, q] = True
            for j, term in enumerate(pod.pod_affinity):
                sid = self._selector_id(term)
                if term.preferred:
                    (pref_anti if term.anti else pref_aff)[i, j] = sid
                    (pref_anti_w if term.anti else pref_aff_w)[i, j] = term.weight
                else:
                    (anti if term.anti else aff)[i, j] = sid
            # preferred-term group ids re-densified per pod: distinct
            # caller ids map to distinct dense ids (each expression
            # introduces at most one new group, so ids stay < ep_max —
            # a clamp would silently MERGE independent terms)
            pref_groups: dict[int, int] = {}
            next_gid = 0
            for j, wexpr in enumerate(pod.preferred_node_affinity):
                e = wexpr.expr
                pna_key[i, j] = self.label_keys.id(e.key)
                pna_op[i, j] = _NA_OPS[e.operator]
                pna_mask[i, j] = True
                pna_weight[i, j] = wexpr.weight
                if wexpr.term is None:
                    pna_term[i, j] = next_gid
                    next_gid += 1
                else:
                    if wexpr.term not in pref_groups:
                        pref_groups[wexpr.term] = next_gid
                        next_gid += 1
                    pna_term[i, j] = pref_groups[wexpr.term]
                for q, v in enumerate(e.values):
                    pna_vals[i, j, q] = self.label_values.id(v)
                    pna_val_mask[i, j, q] = True

        # pod_matches: does pending pod p's label set satisfy selector s —
        # the engine needs this to update in-window domain counts when the
        # greedy scan places each pod (ops/assign.py AffinityState)
        s = self._selector_slots()
        pod_matches = np.zeros((p, s), bool)
        for i, pod in enumerate(pods):
            for key, sid in self.selectors.items():
                if self._key_matches(pod, key):
                    pod_matches[i, sid] = True

        # numpy, not device arrays — see build_snapshot's return comment
        return PodBatch(
            request=request, r_io=r_io, priority=priority, pod_mask=pod_mask,
            want_number=want_number, want_memory=want_memory,
            want_clock=want_clock, tolerations=tols, tol_mask=tol_mask,
            na_key=na_key, na_op=na_op, na_vals=na_vals,
            na_val_mask=na_val_mask, na_mask=na_mask, na_term=na_term,
            affinity_sel=aff,
            anti_affinity_sel=anti, pod_matches=pod_matches,
            pna_key=pna_key, pna_op=pna_op, pna_vals=pna_vals,
            pna_val_mask=pna_val_mask, pna_mask=pna_mask,
            pna_weight=pna_weight, pna_term=pna_term,
            pref_affinity_sel=pref_aff,
            pref_affinity_weight=pref_aff_w, pref_anti_sel=pref_anti,
            pref_anti_weight=pref_anti_w, target_node=target_node,
            spread_sel=spread_sel, spread_max=spread_max,
            soft_spread_sel=soft_spread_sel,
            image_ids=image_ids, n_containers=n_containers,
            gang_id=gang_id, gang_size=gang_size,
        )
