"""Shadow-mode serving: score production traffic through a candidate
config, live, without owning a single bind.

The ShadowScheduler TAILS a flight-recorder journal as the primary
writes it (trace/recorder.JournalTailer: rotation boundaries followed,
truncated tails re-polled, resume by seq) and re-dispatches every
device-path cycle through a CANDIDATE engine/config. What comes out is
a decision diff (bindings changed, candidate score deltas, gangs whose
admission diverged) and a latency diff (candidate step time against the
recorded engine_seconds), exported on the shadow's OWN /metrics
endpoint and span stream — the continuous rollout gate: run the
candidate beside the fleet instead of before it, and promote when the
divergence and latency series say so.

Isolation contract, by construction rather than convention:
- zero writes to the bind path — this module never imports the
  Scheduler, never opens the journal for writing, never talks to the
  cluster; its only inputs are journal bytes and its only outputs are
  its own metrics/span files.
- a wedged candidate cannot stall the shadow, let alone the primary:
  every candidate dispatch is guarded by a CircuitBreaker — failures
  count, the breaker opens, tailing continues (records still fold into
  the reconstruction so the delta chain stays anchored), and scoring
  resumes on the half-open probe.

Reconstruction reuses the replay primitives (trace/replay.py): the
recorded PodBatch and folded SnapshotArrays are bit-exact copies of
what the live cycle dispatched, so a candidate configured identically
to the primary MUST diff to zero — that is PARITY.md round 21, and the
determinism tests pin it for the serial and pipelined sources.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from kubernetes_scheduler_tpu.host.observe import (
    Counter,
    Gauge,
    Histogram,
    HttpMetricsServer,
    PREFIX,
    SpanRecorder,
)
from kubernetes_scheduler_tpu.host.resilience import CircuitBreaker
from kubernetes_scheduler_tpu.trace.recorder import JournalTailer, TraceError
from kubernetes_scheduler_tpu.trace.replay import (
    engine_kw_from_record,
    pod_batch_from_record,
)

log = logging.getLogger("yoda_tpu.shadow")

MODES = ("serial", "pipelined")


def candidate_kw(recorded_kw: dict, config) -> dict:
    """The candidate cycle options: the RECORDED kw (affinity/soft
    probes are properties of the traffic, not the config under test)
    with the scoring surface swapped for the candidate's — policy,
    assigner, normalizer, score plugins, auction knobs. `fused` is kept
    only inside the candidate's fusable domain; the engine falls back
    silently anyway, but the shadow should not claim a kernel the
    candidate config could never run."""
    kw = dict(recorded_kw)
    kw["policy"] = config.policy
    kw["assigner"] = config.assigner
    kw["normalizer"] = config.normalizer
    sp = config.score_plugins_tuple()
    if sp is None:
        kw.pop("score_plugins", None)
    else:
        kw["score_plugins"] = sp
    if "auction_rounds" in kw:
        kw["auction_rounds"] = config.auction_rounds
        kw["auction_price_frac"] = config.auction_price_frac
    kw["fused"] = bool(
        kw.get("fused")
        and sp is None
        and config.policy == "balanced_cpu_diskio"
        and config.normalizer in ("none", "min_max")
    )
    return kw


def _gang_admissions(gang_id, gang_size, idx) -> dict:
    """gang_id -> fully-admitted? over the window's real rows. A gang
    is admitted all-or-nothing (ops/gang.py), so 'every member bound'
    is the admission bit the shadow diffs."""
    out: dict = {}
    gid = np.asarray(gang_id).reshape(-1)[: len(idx)]
    gsz = np.asarray(gang_size).reshape(-1)[: len(idx)]
    for g in np.unique(gid):
        if g < 0:
            continue
        rows = gid == g
        if not int(np.asarray(gsz)[rows].max(initial=0)):
            continue
        out[int(g)] = bool((np.asarray(idx)[rows] >= 0).all())
    return out


class ShadowScheduler:
    """Tail a journal, re-score each cycle through a candidate config,
    export the decision/latency diff. Read-only by construction."""

    def __init__(
        self,
        journal_path: str,
        config,
        *,
        engine=None,
        mode: str = "serial",
        resume_seq: int | None = None,
        span_path: str | None = None,
    ):
        if mode not in MODES:
            raise ValueError(f"unknown shadow mode {mode!r}; expected {MODES}")
        self.config = config
        self.mode = mode
        self.tailer = JournalTailer(journal_path, resume_seq=resume_seq)
        if engine is None:
            from kubernetes_scheduler_tpu.engine import LocalEngine

            engine = LocalEngine()
        self.engine = engine
        self.breaker = CircuitBreaker(
            "shadow-candidate",
            failure_threshold=config.breaker_failure_threshold,
            recovery_window_s=config.breaker_recovery_window_s,
        )
        self.spans = (
            SpanRecorder(span_path, process="shadow")
            if span_path is not None
            else None
        )
        self._server: HttpMetricsServer | None = None
        # reconstruction state: the previous device record's snapshot
        # (delta folding base) — None until the first full snapshot,
        # and again after a resume lands mid-chain
        self._prev_snapshot = None
        self._unanchored_skips = 0
        # latency accumulation for the ratio gauge
        self._recorded_engine_s = 0.0
        self._candidate_engine_s = 0.0
        self._score_delta_sum = 0.0
        self._score_delta_n = 0
        self._rot_seen = 0
        self._rec_seen = 0
        self.ctr_records = Counter(
            "shadow_records_applied_total",
            "Journal records the shadow tailer decoded and applied",
        )
        self.ctr_cycles = Counter(
            "shadow_cycles_total",
            "Shadow re-score outcomes (scored / skipped / unanchored / "
            "breaker_open / error)",
            labels=("result",),
        )
        self.ctr_bindings_changed = Counter(
            "shadow_bindings_changed_total",
            "Window rows the candidate placed differently than the primary",
        )
        self.ctr_pods_compared = Counter(
            "shadow_pods_compared_total",
            "Window rows diffed between candidate and primary decisions",
        )
        self.ctr_gangs_diverged = Counter(
            "shadow_gangs_diverged_total",
            "Gangs whose all-or-nothing admission diverged from the primary",
        )
        self.ctr_candidate_errors = Counter(
            "shadow_candidate_errors_total",
            "Candidate dispatches that raised (counted into the breaker)",
        )
        self.ctr_breaker_skips = Counter(
            "shadow_breaker_skips_total",
            "Cycles not re-scored because the candidate breaker was open",
        )
        self.ctr_rotations = Counter(
            "shadow_rotations_followed_total",
            "Journal rotation boundaries the tailer crossed live",
        )
        self.ctr_tail_recoveries = Counter(
            "shadow_tail_recoveries_total",
            "Truncated-tail-then-grew recoveries while tailing",
        )
        self.g_divergence = Gauge(
            "shadow_divergence_ratio",
            "bindings_changed / pods_compared over the shadow's lifetime",
        )
        self.g_latency = Gauge(
            "shadow_latency_ratio",
            "Candidate engine seconds / recorded engine seconds (cumulative)",
        )
        self.g_score_delta = Gauge(
            "shadow_score_delta_mean",
            "Mean candidate-score gain over the primary's placement on "
            "rows the candidate moved",
        )
        self.g_lag = Gauge(
            "shadow_lag_seconds",
            "Wall-clock age of the last applied journal record",
        )
        self.h_step = Histogram(
            "shadow_candidate_step_duration_seconds",
            "Candidate engine dispatch wall time per shadow cycle",
        )
        self.collectors = (
            self.ctr_records, self.ctr_cycles, self.ctr_bindings_changed,
            self.ctr_pods_compared, self.ctr_gangs_diverged,
            self.ctr_candidate_errors, self.ctr_breaker_skips,
            self.ctr_rotations, self.ctr_tail_recoveries,
            self.g_divergence, self.g_latency, self.g_score_delta,
            self.g_lag, self.h_step,
        )
        self._resident_state: dict = {}

    # ---- exporter ----------------------------------------------------------

    def _render(self) -> str:
        lines: list[str] = []
        for c in self.collectors:
            lines.extend(c.render(prefix=PREFIX))
        return "\n".join(lines) + "\n"

    def serve(self, port: int, host: str = "127.0.0.1") -> int:
        self._server = HttpMetricsServer(self._render)
        return self._server.serve(port, host=host)

    # ---- candidate dispatch ------------------------------------------------

    def _candidate_result(self, snapshot, pods, kw, batch_window: int):
        """One candidate engine call -> (flat node_idx, [p, n] scores).
        Mirrors trace/replay's dispatch surface so the shadow exercises
        the same serial/pipelined paths the replayer pins."""
        if batch_window > 0:
            from kubernetes_scheduler_tpu.engine import stack_windows

            windows = stack_windows(pods, batch_window)
            res = self.engine.schedule_windows(snapshot, windows, **kw)
        elif self.mode == "pipelined" and hasattr(
            self.engine, "schedule_batch_async"
        ):
            res = self.engine.schedule_batch_async(snapshot, pods, **kw).result()
        else:
            res = self.engine.schedule_batch(snapshot, pods, **kw)
        idx = np.asarray(res.node_idx).reshape(-1)
        scores = np.asarray(res.scores)
        scores = scores.reshape(-1, scores.shape[-1])
        return idx, scores

    # ---- record processing -------------------------------------------------

    def _fold(self, rec: dict):
        """Fold the record into the reconstruction; None for records
        that carry no snapshot (scalar cycles) or that cannot anchor
        (resume landed mid-chain — wait for the next full snapshot,
        which the recorder guarantees at every rotation boundary)."""
        from kubernetes_scheduler_tpu.engine import (
            SnapshotArrays,
            SnapshotDelta,
            apply_snapshot_delta_np,
        )

        if "snapshot" in rec:
            snap = SnapshotArrays(**rec["snapshot"])
        elif "delta" in rec:
            if self._prev_snapshot is None:
                return None
            snap = apply_snapshot_delta_np(
                self._prev_snapshot, SnapshotDelta(**rec["delta"])
            )
        else:
            return None
        self._prev_snapshot = snap
        return snap

    def process_record(self, rec: dict) -> None:
        """Apply one journal record: fold state, re-score through the
        candidate (breaker permitting), account the diff. Never raises
        for a candidate failure — tailing must outlive the candidate."""
        t_cycle = time.perf_counter()
        self.ctr_records.inc()
        wall = rec.get("wall_time")
        if wall is not None:
            self.g_lag.set(max(0.0, time.time() - float(wall)))
        ss = self.spans.begin() if self.spans is not None else None
        unanchored = "delta" in rec and self._prev_snapshot is None
        snapshot = self._fold(rec)
        if ss is not None:
            ss.add("reconstruct", t_cycle, time.perf_counter())
        result = "scored"
        try:
            if (
                snapshot is None
                or "pods" not in rec
                or rec.get("path") not in ("device", "backlog")
            ):
                result = "unanchored" if unanchored else "skipped"
                if unanchored:
                    self._unanchored_skips += 1
            elif not self.breaker.allow():
                self.ctr_breaker_skips.inc()
                result = "breaker_open"
            else:
                self._score_cycle(rec, snapshot, ss)
        except TraceError:
            # malformed record content (e.g. a backlog record with no
            # batch_window): not a candidate fault, not breaker food
            log.exception("shadow: unusable record seq=%s", rec.get("seq"))
            result = "skipped"
        except Exception:
            log.exception(
                "shadow: candidate dispatch failed seq=%s", rec.get("seq")
            )
            self.ctr_candidate_errors.inc()
            self.breaker.record_failure()
            result = "error"
        self.ctr_cycles.inc(result=result)
        if ss is not None:
            ss.add(
                "cycle", t_cycle, time.perf_counter(),
                path=rec.get("path", "scalar"), result=result,
            )
            self.spans.flush(ss, seq=rec.get("seq"))

    def _score_cycle(self, rec: dict, snapshot, ss) -> None:
        recorded_idx = np.asarray(
            (rec.get("assign") or {}).get("node_idx", np.zeros(0, np.int32))
        ).reshape(-1)
        pods = pod_batch_from_record(rec["pods"])
        kw = candidate_kw(engine_kw_from_record(rec), self.config)
        bw = 0
        if rec["path"] == "backlog":
            bw = int(rec.get("batch_window") or 0)
            if bw <= 0:
                raise TraceError(
                    f"backlog record seq={rec.get('seq')} lacks batch_window"
                )
        t_eng = time.perf_counter()
        idx, scores = self._candidate_result(snapshot, pods, kw, bw)
        cand_s = time.perf_counter() - t_eng
        self.breaker.record_success()
        self.h_step.observe(cand_s)
        if ss is not None:
            ss.add(
                "candidate_step", t_eng, time.perf_counter(),
                backlog=rec["path"] == "backlog",
            )
        t_diff = time.perf_counter()
        pod_keys = rec.get("pod_keys") or []
        n_real = len(pod_keys) if pod_keys else recorded_idx.shape[0]
        want = recorded_idx[:n_real]
        cand = idx[:n_real].astype(np.int32)
        n = min(want.shape[0], cand.shape[0])
        changed = int((want[:n] != cand[:n]).sum()) + abs(
            want.shape[0] - cand.shape[0]
        )
        self.ctr_pods_compared.inc(n_real)
        if changed:
            self.ctr_bindings_changed.inc(changed)
        # candidate's own scoring margin on the rows it moved: how much
        # better the candidate believes its placement is than what the
        # primary did (its normalized score units — a decision-quality
        # signal, not a latency one)
        moved = np.flatnonzero(want[:n] != cand[:n])
        for i in moved:
            ci, wi = int(cand[i]), int(want[i])
            if 0 <= ci < scores.shape[1] and 0 <= wi < scores.shape[1]:
                self._score_delta_sum += float(
                    scores[i, ci] - scores[i, wi]
                )
                self._score_delta_n += 1
        if self._score_delta_n:
            self.g_score_delta.set(
                self._score_delta_sum / self._score_delta_n
            )
        gangs_rec = _gang_admissions(
            pods.gang_id, pods.gang_size, want[:n]
        )
        gangs_cand = _gang_admissions(
            pods.gang_id, pods.gang_size, cand[:n]
        )
        diverged = sum(
            1
            for g in set(gangs_rec) | set(gangs_cand)
            if gangs_rec.get(g) != gangs_cand.get(g)
        )
        if diverged:
            self.ctr_gangs_diverged.inc(diverged)
        compared = self.ctr_pods_compared.value()
        if compared:
            self.g_divergence.set(
                self.ctr_bindings_changed.value() / compared
            )
        rec_s = float((rec.get("metrics") or {}).get("engine_seconds", 0.0))
        self._recorded_engine_s += rec_s
        self._candidate_engine_s += cand_s
        if self._recorded_engine_s > 0:
            self.g_latency.set(
                self._candidate_engine_s / self._recorded_engine_s
            )
        if ss is not None:
            ss.add(
                "decision_diff", t_diff, time.perf_counter(),
                changed=changed, gangs_diverged=diverged,
            )

    # ---- driver ------------------------------------------------------------

    def _sync_tail_counters(self) -> None:
        t = self.tailer
        if t.rotations_followed > self._rot_seen:
            self.ctr_rotations.inc(t.rotations_followed - self._rot_seen)
            self._rot_seen = t.rotations_followed
        if t.truncations_recovered > self._rec_seen:
            self.ctr_tail_recoveries.inc(
                t.truncations_recovered - self._rec_seen
            )
            self._rec_seen = t.truncations_recovered

    def catch_up(self, *, limit: int | None = None) -> int:
        """Drain every record currently readable; returns the count."""
        done = 0
        while True:
            budget = None if limit is None else limit - done
            if budget is not None and budget <= 0:
                return done
            recs = self.tailer.poll(max_records=budget or 256)
            if not recs:
                self._sync_tail_counters()
                return done
            for rec in recs:
                self.process_record(rec)
            done += len(recs)
            self._sync_tail_counters()

    def run(
        self,
        *,
        follow: bool = False,
        poll_interval_s: float = 0.25,
        idle_timeout_s: float | None = None,
        limit: int | None = None,
        sleep=time.sleep,
    ) -> dict:
        """Tail until caught up (follow=False), or until the journal
        goes idle for idle_timeout_s (follow=True). Returns summary()."""
        applied = 0
        idle_since = time.monotonic()
        while True:
            got = self.catch_up(
                limit=None if limit is None else limit - applied
            )
            applied += got
            if limit is not None and applied >= limit:
                break
            if got:
                idle_since = time.monotonic()
                continue
            if not follow:
                break
            if (
                idle_timeout_s is not None
                and time.monotonic() - idle_since >= idle_timeout_s
            ):
                break
            sleep(poll_interval_s)
        return self.summary()

    def summary(self) -> dict:
        compared = int(self.ctr_pods_compared.value())
        changed = int(self.ctr_bindings_changed.value())
        return {
            "records_applied": int(self.ctr_records.value()),
            "cycles": {
                ("".join(k)): int(v)
                for k, v in self.ctr_cycles.breakdown().items()
            },
            "pods_compared": compared,
            "bindings_changed": changed,
            "divergence_ratio": (changed / compared) if compared else 0.0,
            "gangs_diverged": int(self.ctr_gangs_diverged.value()),
            "score_delta_mean": (
                self._score_delta_sum / self._score_delta_n
                if self._score_delta_n
                else 0.0
            ),
            "candidate_errors": int(self.ctr_candidate_errors.value()),
            "breaker_skips": int(self.ctr_breaker_skips.value()),
            "breaker_state": self.breaker.state(),
            "unanchored_skips": self._unanchored_skips,
            "recorded_engine_seconds": round(self._recorded_engine_s, 6),
            "candidate_engine_seconds": round(self._candidate_engine_s, 6),
            "latency_ratio": (
                self._candidate_engine_s / self._recorded_engine_s
                if self._recorded_engine_s > 0
                else 0.0
            ),
            "tail": self.tailer.stats(),
        }

    def close(self) -> None:
        if self.spans is not None:
            self.spans.close()
        if self._server is not None:
            self._server.close()
            self._server = None
