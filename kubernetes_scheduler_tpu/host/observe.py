"""Observability: metrics export, cycle timing spans, device profiling.

The reference *consumes* metrics but exports none — its own metrics
endpoint is disabled (MetricsBindAddress: "", scheduler.go:64) and its
only introspection is leveled klog spam (SURVEY.md §5). This module
provides what that design was missing, around the north-star numbers in
BASELINE.json:

- `render_prometheus` / `MetricsExporter`: scheduling throughput, bind
  latency p50/p99, batch sizes, engine (device) step time, fallback
  count, in Prometheus text exposition format on /metrics — so the same
  Prometheus the advisor scrapes from can scrape the scheduler back.
- `CycleTracer`: structured per-cycle spans (host snapshot build, device
  step, bind fan-out) logged as JSON lines.
- `profile_device_step`: wraps one engine call in a jax.profiler trace
  for XLA-level inspection (op time on the MXU/VPU, transfer time).
"""

from __future__ import annotations

import contextlib
import http.server
import json
import logging
import threading
import time

log = logging.getLogger("yoda_tpu.observe")

PREFIX = "yoda_tpu"


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[i]


def summarize(metrics, totals: dict | None = None) -> dict:
    """Aggregate host.scheduler.CycleMetrics.

    `totals` (Scheduler.totals) supplies the monotonic run counters when
    given; the metrics window is a bounded deque, so summing it would
    make the *_total Prometheus counters decrease after eviction (every
    decrease reads as a counter reset to rate()/increase()). Quantiles
    and rates always come from the recent window — that is what a
    latency percentile should mean on a long-lived process anyway."""
    cycles = [m for m in metrics if m.pods_in > 0]
    lat = sorted(m.cycle_seconds for m in cycles)
    eng = sorted(m.engine_seconds for m in cycles if m.engine_seconds > 0)
    total_s = sum(lat)
    bound = sum(m.pods_bound for m in cycles)
    if totals is None:
        totals = {
            "cycles": len(cycles),
            "pods_bound": bound,
            "pods_unschedulable": sum(m.pods_unschedulable for m in cycles),
            "pods_dropped": sum(m.pods_dropped for m in cycles),
            "pods_preempted": sum(
                getattr(m, "pods_preempted", 0) for m in cycles
            ),
            "victims_evicted": sum(
                getattr(m, "victims_evicted", 0) for m in cycles
            ),
            "fallback_cycles": sum(1 for m in cycles if m.used_fallback),
            "fetch_failures": sum(
                1 for m in cycles if getattr(m, "fetch_failed", False)
            ),
            "fallback_policy_mismatch": sum(
                1 for m in cycles if getattr(m, "policy_mismatch", False)
            ),
            "pipeline_flushes": sum(
                getattr(m, "pipeline_flushes", 0) for m in cycles
            ),
            "host_overlap_seconds": sum(
                getattr(m, "host_overlap_seconds", 0.0) for m in cycles
            ),
            "delta_uploads": sum(
                getattr(m, "delta_uploads", 0) for m in cycles
            ),
            "full_uploads": sum(
                getattr(m, "full_uploads", 0) for m in cycles
            ),
            "delta_bytes_saved": sum(
                getattr(m, "delta_bytes_saved", 0) for m in cycles
            ),
        }
    return {
        "cycles_total": totals["cycles"],
        "pods_bound_total": totals["pods_bound"],
        "pods_unschedulable_total": totals["pods_unschedulable"],
        "pods_dropped_total": totals.get("pods_dropped", 0),
        "pods_preempted_total": totals.get("pods_preempted", 0),
        "victims_evicted_total": totals.get("victims_evicted", 0),
        "fallback_cycles_total": totals["fallback_cycles"],
        "fetch_failures_total": totals.get("fetch_failures", 0),
        "fallback_policy_mismatch_total": totals.get(
            "fallback_policy_mismatch", 0
        ),
        # pipelined loop (config.pipeline_depth): flush count is the
        # hazard-rate signal (speculative state discarded for informer
        # churn / engine failure / non-device cycles); overlap seconds
        # is the host work hidden under in-flight engine calls — the
        # win the pipeline exists for, observable in production
        "pipeline_flushes_total": totals.get("pipeline_flushes", 0),
        "host_overlap_seconds_total": totals.get("host_overlap_seconds", 0.0),
        # resident cluster state (config.resident_state): delta vs full
        # uploads and the payload bytes the deltas avoided shipping —
        # the delta hit rate IS the steady-state health signal (full
        # uploads after warmup mean layout churn or engine flapping)
        "delta_uploads_total": totals.get("delta_uploads", 0),
        "full_uploads_total": totals.get("full_uploads", 0),
        "delta_bytes_saved_total": totals.get("delta_bytes_saved", 0),
        "scheduling_pods_per_sec": bound / total_s if total_s > 0 else 0.0,
        "bind_latency_p50_seconds": _quantile(lat, 0.50),
        "bind_latency_p99_seconds": _quantile(lat, 0.99),
        "engine_step_p50_seconds": _quantile(eng, 0.50),
        "engine_step_p99_seconds": _quantile(eng, 0.99),
        "batch_size_mean": (sum(m.pods_in for m in cycles) / len(cycles))
        if cycles
        else 0.0,
    }


_HELP = {
    "cycles_total": "Scheduling cycles with at least one pending pod",
    "pods_bound_total": "Pods bound to nodes",
    "pods_unschedulable_total": "Pod placements rejected (requeued with backoff)",
    "pods_dropped_total": "Pods forgotten after a bind-time lifecycle race (404/409)",
    "pods_preempted_total": "Unschedulable pods that triggered a preemption (PostFilter)",
    "victims_evicted_total": "Running pods evicted to make room for preemptors",
    "fallback_cycles_total": "Cycles served by the scalar fallback path",
    "fetch_failures_total": "Cycles aborted by a cluster-source/advisor fetch failure (window requeued)",
    "fallback_policy_mismatch_total": "Fallback cycles scored with the yoda formula because config.policy has no scalar mirror",
    "pipeline_flushes_total": "Speculative pipeline state discarded (informer/layout churn, engine failure, non-device cycle)",
    "host_overlap_seconds_total": "Host work overlapped with in-flight engine calls (pipelined loop)",
    "delta_uploads_total": "Resident-state cycles served by a SnapshotDelta applied on the engine",
    "full_uploads_total": "Resident-state cycles that shipped the full snapshot (first upload, churn, or flush)",
    "delta_bytes_saved_total": "Snapshot payload bytes delta uploads avoided shipping to the engine",
    "scheduling_pods_per_sec": "Bound pods per second of cycle time",
    "bind_latency_p50_seconds": "Median end-to-end cycle latency",
    "bind_latency_p99_seconds": "p99 end-to-end cycle latency",
    "engine_step_p50_seconds": "Median device (engine) step time",
    "engine_step_p99_seconds": "p99 device (engine) step time",
    "batch_size_mean": "Mean pods per scheduling window",
    "advisor_stale_served_total": (
        "Cycles served a utilization snapshot older than twice the "
        "advisor refresh interval (BackgroundAdvisor brown-out signal)"
    ),
    # cycle flight recorder (config.trace_path; trace/recorder.py)
    "cycles_recorded_total": "Scheduling cycles journaled by the flight recorder",
    "trace_bytes_total": "Journal bytes written by the flight recorder",
    "trace_records_dropped_total": (
        "Cycle records the flight recorder failed to journal "
        "(encode/IO error — the scheduling loop never pays for these)"
    ),
}


def render_prometheus(
    metrics, totals: dict | None = None, extra: dict | None = None
) -> str:
    rows = summarize(metrics, totals)
    if extra:
        rows = {**rows, **extra}
    out = []
    for key, value in rows.items():
        name = f"{PREFIX}_{key}"
        kind = "counter" if key.endswith("_total") else "gauge"
        out.append(f"# HELP {name} {_HELP[key]}")
        out.append(f"# TYPE {name} {kind}")
        out.append(f"{name} {value}")
    return "\n".join(out) + "\n"


class MetricsExporter:
    """Serves /metrics (Prometheus text format) and /healthz for a live
    Scheduler, on a daemon thread."""

    def __init__(self, scheduler):
        self.scheduler = scheduler
        self._server: http.server.ThreadingHTTPServer | None = None

    def serve(self, port: int) -> int:
        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/metrics":
                    sched = exporter.scheduler
                    if hasattr(sched, "metrics_snapshot"):
                        window, totals = sched.metrics_snapshot()
                    else:
                        window, totals = list(sched.metrics), None
                    stale = getattr(
                        getattr(sched, "advisor", None), "stale_served", None
                    )
                    extra = {}
                    if stale is not None:
                        extra["advisor_stale_served_total"] = stale
                    rec = getattr(sched, "recorder", None)
                    if rec is not None:
                        extra.update(
                            cycles_recorded_total=rec.cycles_recorded,
                            trace_bytes_total=rec.bytes_written,
                            trace_records_dropped_total=rec.records_dropped,
                        )
                    extra = extra or None
                    body = render_prometheus(window, totals, extra).encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                log.debug("metrics http: " + fmt, *args)

        self._server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self._server.server_address[1]

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class CycleTracer:
    """Structured timing spans for one scheduling cycle, emitted as one
    JSON line (the replacement for the reference's klog.V(4) spam)."""

    def __init__(self, sink=None):
        self.sink = sink or (lambda line: log.info("%s", line))
        self._spans: dict[str, float] = {}

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._spans[name] = self._spans.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def emit(self, **fields) -> None:
        record = {"ts": time.time(), **fields}
        record.update(
            {f"span_{k}_seconds": round(v, 6) for k, v in self._spans.items()}
        )
        self.sink(json.dumps(record))
        self._spans.clear()


def profile_device_step(engine_call, out_dir: str):
    """Run one engine call under a jax.profiler trace; the resulting
    TensorBoard protobufs in `out_dir` break the step into XLA ops."""
    import jax

    with jax.profiler.trace(out_dir):
        result = engine_call()
        # graftlint: disable=host-sync -- profiling needs the device barrier; never on the cycle path
        jax.block_until_ready(result)
    return result
