"""Observability: metrics export, per-cycle spans, device profiling.

The reference *consumes* metrics but exports none — its own metrics
endpoint is disabled (MetricsBindAddress: "", scheduler.go:64) and its
only introspection is leveled klog spam (SURVEY.md §5). This module
provides what that design was missing, around the north-star numbers in
BASELINE.json:

- `render_prometheus` / `MetricsExporter`: scheduling throughput, bind
  latency p50/p99, batch sizes, engine (device) step time, fallback
  count, in Prometheus text exposition format on /metrics — so the same
  Prometheus the advisor scrapes from can scrape the scheduler back.
- `Histogram`/`Counter`/`Gauge`: real labeled Prometheus series beside
  the legacy window-quantile gauges (`path=serial|pipelined|fallback`,
  `upload=delta|full`, `rpc=schedule_batch|...`) — shared by the host
  exporter and the sidecar's own exporter (bridge/server.py).
- `SpanRecorder`: per-cycle structured spans with a monotonically-
  assigned trace id, emitted as Chrome-trace-event JSON to a rotating,
  disk-budgeted directory (trace/spans.py); the same id rides gRPC
  metadata so sidecar-side spans join the host timeline
  (`yoda-tpu spans merge`).
- `profile_device_step`: wraps one engine call in a jax.profiler trace
  for XLA-level inspection (op time on the MXU/VPU, transfer time) —
  armed on demand through /debug/profile?cycles=N.

Metric-name contract (enforced by graftlint's `metric-hygiene` family):
every exported name carries a HELP entry, ends in a unit (or `_total`)
suffix, and is pinned in SHIPPED_METRICS — dashboards and alerts
reference metrics by name, so a shipped name is never removed.
"""

from __future__ import annotations

import bisect
import contextlib
import http.server
import json
import logging
import threading
import time

log = logging.getLogger("yoda_tpu.observe")

PREFIX = "yoda_tpu"


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[i]


def summarize(metrics, totals: dict | None = None) -> dict:
    """Aggregate host.scheduler.CycleMetrics.

    `totals` (Scheduler.totals) supplies the monotonic run counters when
    given; the metrics window is a bounded deque, so summing it would
    make the *_total Prometheus counters decrease after eviction (every
    decrease reads as a counter reset to rate()/increase()). Quantiles
    and rates always come from the recent window — that is what a
    latency percentile should mean on a long-lived process anyway."""
    cycles = [m for m in metrics if m.pods_in > 0]
    lat = sorted(m.cycle_seconds for m in cycles)
    eng = sorted(m.engine_seconds for m in cycles if m.engine_seconds > 0)
    total_s = sum(lat)
    bound = sum(m.pods_bound for m in cycles)
    if totals is None:
        totals = {
            "cycles": len(cycles),
            "pods_bound": bound,
            "pods_unschedulable": sum(m.pods_unschedulable for m in cycles),
            "pods_dropped": sum(m.pods_dropped for m in cycles),
            "pods_preempted": sum(
                getattr(m, "pods_preempted", 0) for m in cycles
            ),
            "victims_evicted": sum(
                getattr(m, "victims_evicted", 0) for m in cycles
            ),
            "fallback_cycles": sum(1 for m in cycles if m.used_fallback),
            "fetch_failures": sum(
                1 for m in cycles if getattr(m, "fetch_failed", False)
            ),
            "fallback_policy_mismatch": sum(
                1 for m in cycles if getattr(m, "policy_mismatch", False)
            ),
            "pipeline_flushes": sum(
                getattr(m, "pipeline_flushes", 0) for m in cycles
            ),
            "host_overlap_seconds": sum(
                getattr(m, "host_overlap_seconds", 0.0) for m in cycles
            ),
            "delta_uploads": sum(
                getattr(m, "delta_uploads", 0) for m in cycles
            ),
            "full_uploads": sum(
                getattr(m, "full_uploads", 0) for m in cycles
            ),
            "delta_bytes_saved": sum(
                getattr(m, "delta_bytes_saved", 0) for m in cycles
            ),
            "sharded_cycles": sum(
                getattr(m, "sharded_cycles", 0) for m in cycles
            ),
            "shard_delta_bytes": sum(
                sum(getattr(m, "shard_delta_bytes", ()) or ())
                for m in cycles
            ),
            "gangs_admitted": sum(
                getattr(m, "gangs_admitted", 0) for m in cycles
            ),
            "gangs_deferred": sum(
                getattr(m, "gangs_deferred", 0) for m in cycles
            ),
            "gang_pods_masked": sum(
                getattr(m, "gang_pods_masked", 0) for m in cycles
            ),
            "advisor_stale_cycles": sum(
                1 for m in cycles if getattr(m, "advisor_stale", False)
            ),
            "degraded_cycles": sum(
                1 for m in cycles if getattr(m, "degraded", ())
            ),
        }
    return {
        "cycles_total": totals["cycles"],
        "pods_bound_total": totals["pods_bound"],
        "pods_unschedulable_total": totals["pods_unschedulable"],
        "pods_dropped_total": totals.get("pods_dropped", 0),
        "pods_preempted_total": totals.get("pods_preempted", 0),
        "victims_evicted_total": totals.get("victims_evicted", 0),
        "fallback_cycles_total": totals["fallback_cycles"],
        "fetch_failures_total": totals.get("fetch_failures", 0),
        "fallback_policy_mismatch_total": totals.get(
            "fallback_policy_mismatch", 0
        ),
        # pipelined loop (config.pipeline_depth): flush count is the
        # hazard-rate signal (speculative state discarded for informer
        # churn / engine failure / non-device cycles); overlap seconds
        # is the host work hidden under in-flight engine calls — the
        # win the pipeline exists for, observable in production
        "pipeline_flushes_total": totals.get("pipeline_flushes", 0),
        "host_overlap_seconds_total": totals.get("host_overlap_seconds", 0.0),
        # resident cluster state (config.resident_state): delta vs full
        # uploads and the payload bytes the deltas avoided shipping —
        # the delta hit rate IS the steady-state health signal (full
        # uploads after warmup mean layout churn or engine flapping)
        "delta_uploads_total": totals.get("delta_uploads", 0),
        "full_uploads_total": totals.get("full_uploads", 0),
        "delta_bytes_saved_total": totals.get("delta_bytes_saved", 0),
        # mesh-sharded engine (config.sharded_engine): device cycles
        # served shard-local across the mesh — the per-shard routed
        # byte split rides the {shard}-labeled shard_delta_bytes_total
        # counter (Scheduler.ctr_shard_bytes) beside this aggregate
        "sharded_cycles_total": totals.get("sharded_cycles", 0),
        # gang co-scheduling (config.gang_scheduling; ops/gang.py):
        # all-or-nothing admissions, unit deferrals, and the tentative
        # placements the rule rescinded — deferred/admitted is the
        # gang-health ratio, masked is the capacity the rule protected
        "gangs_admitted_total": totals.get("gangs_admitted", 0),
        "gangs_deferred_total": totals.get("gangs_deferred", 0),
        "gang_pods_masked_total": totals.get("gang_pods_masked", 0),
        # resilience layer (host/resilience.py): cycles served the
        # last-good utilization snapshot under the advisor stale-TTL
        # grace mode, and cycles that ran with ANY degradation-ladder
        # subsystem below its top rung — the composed-degradation
        # health signal chaos runs assert bounds on
        "advisor_stale_cycles_total": totals.get("advisor_stale_cycles", 0),
        "degraded_cycles_total": totals.get("degraded_cycles", 0),
        "scheduling_pods_per_sec": bound / total_s if total_s > 0 else 0.0,
        "bind_latency_p50_seconds": _quantile(lat, 0.50),
        "bind_latency_p99_seconds": _quantile(lat, 0.99),
        "engine_step_p50_seconds": _quantile(eng, 0.50),
        "engine_step_p99_seconds": _quantile(eng, 0.99),
        "batch_size_mean": (sum(m.pods_in for m in cycles) / len(cycles))
        if cycles
        else 0.0,
    }


_HELP = {
    "cycles_total": "Scheduling cycles with at least one pending pod",
    "pods_bound_total": "Pods bound to nodes",
    "pods_unschedulable_total": "Pod placements rejected (requeued with backoff)",
    "pods_dropped_total": "Pods forgotten after a bind-time lifecycle race (404/409)",
    "pods_preempted_total": "Unschedulable pods that triggered a preemption (PostFilter)",
    "victims_evicted_total": "Running pods evicted to make room for preemptors",
    "fallback_cycles_total": "Cycles served by the scalar fallback path",
    "fetch_failures_total": "Cycles aborted by a cluster-source/advisor fetch failure (window requeued)",
    "fallback_policy_mismatch_total": "Fallback cycles scored with the yoda formula because config.policy has no scalar mirror",
    "pipeline_flushes_total": "Speculative pipeline state discarded (informer/layout churn, engine failure, non-device cycle)",
    "host_overlap_seconds_total": "Host work overlapped with in-flight engine calls (pipelined loop)",
    "delta_uploads_total": "Resident-state cycles served by a SnapshotDelta applied on the engine",
    "full_uploads_total": "Resident-state cycles that shipped the full snapshot (first upload, churn, or flush)",
    "delta_bytes_saved_total": "Snapshot payload bytes delta uploads avoided shipping to the engine",
    "sharded_cycles_total": "Device cycles served by the mesh-sharded engine (config.sharded_engine)",
    "gangs_admitted_total": "Gangs whose every member bound in one cycle (all-or-nothing admission)",
    "gangs_deferred_total": "Gangs requeued as a unit (members missing, partial device fit, or a scalar-fallback cycle)",
    "gang_pods_masked_total": "Tentative placements rescinded by the gang all-or-nothing rule",
    "scheduling_pods_per_sec": "Bound pods per second of cycle time",
    "bind_latency_p50_seconds": "Median end-to-end cycle latency",
    "bind_latency_p99_seconds": "p99 end-to-end cycle latency",
    "engine_step_p50_seconds": "Median device (engine) step time",
    "engine_step_p99_seconds": "p99 device (engine) step time",
    "batch_size_mean": "Mean pods per scheduling window",
    "advisor_stale_served_total": (
        "Cycles served a utilization snapshot older than twice the "
        "advisor refresh interval (BackgroundAdvisor brown-out signal)"
    ),
    # cycle flight recorder (config.trace_path; trace/recorder.py)
    "cycles_recorded_total": "Scheduling cycles journaled by the flight recorder",
    "trace_bytes_total": "Journal bytes written by the flight recorder",
    "trace_records_dropped_total": (
        "Cycle records the flight recorder failed to journal "
        "(encode/IO error — the scheduling loop never pays for these)"
    ),
    # per-cycle span telemetry (config.span_path; trace/spans.py)
    "spans_written_total": "Span events written to the Chrome-trace files",
    "span_bytes_total": "Bytes written to the Chrome-trace span files",
    "spans_dropped_total": (
        "Cycle span sets the recorder failed to encode/write "
        "(the scheduling loop never pays for these)"
    ),
    # resilience layer (host/resilience.py; sim/faults.py chaos runs)
    "advisor_stale_cycles_total": (
        "Cycles served the last-good utilization snapshot under the "
        "advisor stale-TTL grace mode (config.advisor_stale_ttl_s)"
    ),
    "degraded_cycles_total": (
        "Cycles that ran with any degradation-ladder subsystem below "
        "its top rung"
    ),
}


# every metric name this process has EVER exported, pinned: dashboards
# and alerts reference metrics by name, so a shipped name is never
# removed — graftlint's metric-hygiene family checks this registry
# against the declared surfaces (this file's _HELP keys plus every
# Histogram/Counter/Gauge construction in the package) both ways.
SHIPPED_METRICS = (
    "cycles_total",
    "pods_bound_total",
    "pods_unschedulable_total",
    "pods_dropped_total",
    "pods_preempted_total",
    "victims_evicted_total",
    "fallback_cycles_total",
    "fetch_failures_total",
    "fallback_policy_mismatch_total",
    "pipeline_flushes_total",
    "host_overlap_seconds_total",
    "delta_uploads_total",
    "full_uploads_total",
    "delta_bytes_saved_total",
    "sharded_cycles_total",
    "gangs_admitted_total",
    "gangs_deferred_total",
    "gang_pods_masked_total",
    "scheduling_pods_per_sec",
    "bind_latency_p50_seconds",
    "bind_latency_p99_seconds",
    "engine_step_p50_seconds",
    "engine_step_p99_seconds",
    "batch_size_mean",
    "advisor_stale_served_total",
    "cycles_recorded_total",
    "trace_bytes_total",
    "trace_records_dropped_total",
    "spans_written_total",
    "span_bytes_total",
    "spans_dropped_total",
    # labeled histogram layer (host, fed by Scheduler._record)
    "cycle_duration_seconds",
    "engine_step_duration_seconds",
    "snapshot_uploads_total",
    # streaming state ingestion (host/mirror.SnapshotMirror): events
    # applied by kind, flush-to-full rebuilds labeled by flush cause
    # (`reason`: seed / node-churn / selector-drift / layout-drift /
    # port-churn / verify-mismatch), and verification mismatches (the
    # mirror<->rebuild bitwise cross-check)
    "events_applied_total",
    "mirror_full_rebuilds_total",
    "mirror_verify_failures_total",
    # layout drifts absorbed in place (selector column fill / hostPort
    # remap) instead of flushing to a full rebuild
    "mirror_incremental_extensions_total",
    # mesh-sharded resident engine: routed delta payload per owning
    # shard (host labels shard index; the sharded sidecar's twin does
    # too)
    "shard_delta_bytes_total",
    # SLO watchdog (config.cycle_slo_ms; host labels by driver path,
    # the sidecar's own breach counter labels by rpc)
    "slo_breaches_total",
    # resilience layer (host/resilience.py): stale-grace cycle counts,
    # composed-degradation cycle counts, the per-subsystem ladder rung
    # gauge, circuit-breaker state transitions (labeled by breaker +
    # state entered), and the bridge client's health-probe failure
    # split (transport-down vs deadline-exceeded)
    "advisor_stale_cycles_total",
    "degraded_cycles_total",
    "degradation_rung",
    "breaker_transitions_total",
    "engine_health_failures_total",
    # sidecar exporter (bridge/server.EngineService)
    "device_step_duration_seconds",
    "rpcs_served_total",
    "resident_applies_total",
    "resident_sessions_count",
    # replicated fleet (host/replica.py): CAS wins per replica and
    # cross-replica conflicts resolved first-bind-wins (each one is a
    # loser requeued through restore_window, never a lost pod)
    "replica_binds_total",
    "bind_conflicts_total",
    # fleet-shared device engine (host/engine_pool.SharedEnginePool):
    # device dispatches that carried >= 2 replicas' windows in one
    # coalesced super-batch, windows per dispatch, and snapshot uploads
    # by kind (`upload`: full = base resync, delta = changed rows once
    # per fleet, dedup = zero-row epoch advance)
    "coalesced_dispatches_total",
    "coalesce_batch_window_count",
    "shared_engine_uploads_total",
    # shadow-mode serving (host/shadow.py): the candidate exporter's
    # decision/latency-diff series — journal records tailed and scored
    # (cycles labeled by `result`: scored / skipped / unanchored /
    # breaker_open / error), binding divergence vs the recorded primary,
    # gang admission flips, candidate wall-time vs recorded engine time,
    # tail-follow health (rotations followed, torn-tail recoveries),
    # and how far behind the live writer the shadow is running
    "shadow_records_applied_total",
    "shadow_cycles_total",
    "shadow_bindings_changed_total",
    "shadow_pods_compared_total",
    "shadow_gangs_diverged_total",
    "shadow_candidate_errors_total",
    "shadow_breaker_skips_total",
    "shadow_rotations_followed_total",
    "shadow_tail_recoveries_total",
    "shadow_divergence_ratio",
    "shadow_latency_ratio",
    "shadow_score_delta_mean",
    "shadow_lag_seconds",
    "shadow_candidate_step_duration_seconds",
)


def render_prometheus(
    metrics, totals: dict | None = None, extra: dict | None = None
) -> str:
    rows = summarize(metrics, totals)
    if extra:
        rows = {**rows, **extra}
    out = []
    for key, value in rows.items():
        name = f"{PREFIX}_{key}"
        kind = "counter" if key.endswith("_total") else "gauge"
        # an extra key without a registered HELP entry still renders (an
        # empty HELP line) — a metrics endpoint must never 500 over one
        # undocumented sample (the KeyError regression)
        out.append(f"# HELP {name} {_HELP.get(key, '')}".rstrip())
        out.append(f"# TYPE {name} {kind}")
        out.append(f"{name} {value}")
    return "\n".join(out) + "\n"


# ---- labeled Prometheus series (histograms / counters / gauges) -----------

# sub-second-to-seconds ladder covering everything from a colocated
# sidecar's ~1ms device step to a tunneled dev chip's multi-second tail
DURATION_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


def _fmt_labels(names: tuple, values: tuple, extra: str = "") -> str:
    parts = [
        '%s="%s"' % (n, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for n, v in zip(names, values)
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Histogram:
    """Thread-safe labeled Prometheus histogram (cumulative buckets in
    the exposition, per-bucket counts internally). Appends/observes come
    from the scheduling (or RPC worker) thread while /metrics scrapes
    render concurrently — every touch of the series map holds the
    lock."""

    def __init__(
        self,
        name: str,
        help: str,
        *,
        labels: tuple = (),
        buckets: tuple = DURATION_BUCKETS,
    ):
        self.name = name
        self.help = help
        self.labels = tuple(labels)
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        # label values -> [per-bucket counts..., +Inf count], sum
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        key = tuple(str(labels[name]) for name in self.labels)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * (len(self.buckets) + 1), 0.0]
                self._series[key] = s
            s[0][i] += 1
            s[1] += value

    def render(self, prefix: str = PREFIX) -> list[str]:
        name = f"{prefix}_{self.name}"
        out = [f"# HELP {name} {self.help}", f"# TYPE {name} histogram"]
        with self._lock:
            series = {k: (list(v[0]), v[1]) for k, v in self._series.items()}
        for key in sorted(series):
            counts, total = series[key]
            running = 0
            for bound, c in zip(self.buckets, counts):
                running += c
                lbl = _fmt_labels(self.labels, key, 'le="%g"' % bound)
                out.append(f"{name}_bucket{lbl} {running}")
            running += counts[-1]
            lbl = _fmt_labels(self.labels, key, 'le="+Inf"')
            out.append(f"{name}_bucket{lbl} {running}")
            plain = _fmt_labels(self.labels, key)
            out.append(f"{name}_sum{plain} {total}")
            out.append(f"{name}_count{plain} {running}")
        return out


class Counter:
    """Thread-safe labeled monotonic counter (name must end `_total`)."""

    def __init__(self, name: str, help: str, *, labels: tuple = ()):
        self.name = name
        self.help = help
        self.labels = tuple(labels)
        self._lock = threading.Lock()
        self._series: dict[tuple, float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        key = tuple(str(labels[name]) for name in self.labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        """Current count for one label tuple (label-free counters:
        value()) — the public read surface for summaries and tests, so
        nothing couples to the internal series layout."""
        key = tuple(str(labels[name]) for name in self.labels)
        with self._lock:
            return self._series.get(key, 0)

    def total(self) -> float:
        """Sum across every label tuple — what the label-free ancestor
        of a counter reported before it grew labels (the bench rows sum
        `mirror_full_rebuilds_total` over its `reason` breakdown)."""
        with self._lock:
            return sum(self._series.values())

    def breakdown(self) -> dict:
        """label-values tuple -> count snapshot (single-label counters:
        {("seed",): 1, ...}); for bench rows and tests that assert the
        per-reason split without reaching into `_series`."""
        with self._lock:
            return dict(self._series)

    def render(self, prefix: str = PREFIX) -> list[str]:
        name = f"{prefix}_{self.name}"
        out = [f"# HELP {name} {self.help}", f"# TYPE {name} counter"]
        with self._lock:
            series = dict(self._series)
        for key in sorted(series):
            out.append(
                f"{name}{_fmt_labels(self.labels, key)} {series[key]}"
            )
        return out


class Gauge:
    """Set-at-render scalar sample (the sidecar sets it from live state
    inside its render callback). With `labels`, one sample per label
    tuple (the degradation ladder's `degradation_rung{subsystem}`
    surface); label-free construction keeps the legacy single-sample
    shape."""

    def __init__(self, name: str, help: str, *, labels: tuple = ()):
        self.name = name
        self.help = help
        self.labels = tuple(labels)
        # label values -> current sample; label-free gauges live under ()
        self._series: dict[tuple, float] = {(): 0.0} if not labels else {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        key = tuple(str(labels[name]) for name in self.labels)
        with self._lock:
            self._series[key] = value

    def render(self, prefix: str = PREFIX) -> list[str]:
        name = f"{prefix}_{self.name}"
        out = [f"# HELP {name} {self.help}", f"# TYPE {name} gauge"]
        with self._lock:
            series = dict(self._series)
        for key in sorted(series):
            out.append(
                f"{name}{_fmt_labels(self.labels, key)} {series[key]}"
            )
        return out


# ---- per-cycle spans (Chrome trace events, merged across the bridge) ------


# every span (stage) name this package has EVER emitted, pinned: span
# names are a CONTRACT now — `spans report`'s attribution tables,
# `spans diff`'s regression gate, and Perfetto bookmarks all reference
# stages by name, so a shipped name is never removed and a new stage is
# registered consciously. graftlint's `span-hygiene` family checks this
# registry both ways against the names the code actually emits
# (Scheduler._span / SpanSet.add call sites).
SHIPPED_SPANS = (
    # host cycle stages (host/scheduler.py, both drivers)
    "queue_pop",
    "state_fetch",
    "snapshot_build",
    "delta_derive",
    # streaming ingestion (config.snapshot_mirror): advisor changed-node
    # drain applied as mirror events, and the mirror's O(events) emit —
    # the stage that REPLACES snapshot_build + delta_derive on the hot
    # path (those names survive for mirror-off runs and the ~0-cost
    # delta_derive evidence under the mirror)
    "event_apply",
    "mirror_emit",
    "engine_step",
    "bind",
    "recorder_write",
    "host_overlap",
    "scalar_cycle",
    "cycle",
    # sidecar RPC stages (bridge/server.py), joined on trace id
    "deserialize",
    "delta_apply",
    "device_step",
    "serialize",
    # post-hoc replay stages (trace/replay.py --spans)
    "reconstruct",
    # shadow-mode serving (host/shadow.py --spans): the candidate
    # engine's re-score of a tailed cycle and the decision-diff verdict
    # (bindings changed / gangs flipped vs the recorded primary)
    "candidate_step",
    "decision_diff",
)


class SpanSet:
    """One cycle's spans: (name, start, end, args) perf_counter pairs
    plus the cycle's trace id. Collection appends two floats per span —
    cheap enough for the dispatch path; Chrome-event encoding happens in
    SpanRecorder.flush, from the cycle's completion stage (the flight-
    recorder discipline: telemetry never costs the device dispatch)."""

    __slots__ = ("trace_id", "spans")

    def __init__(self, trace_id: int):
        self.trace_id = trace_id
        self.spans: list[tuple] = []

    def add(self, name: str, t0: float, t1: float, **args) -> None:
        self.spans.append((name, t0, t1, args))

    @contextlib.contextmanager
    def span(self, name: str, **args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, t0, time.perf_counter(), **args)


class SpanRecorder:
    """Monotonic trace ids + Chrome-event encoding over the rotating
    span files (trace/spans.py).

    The host assigns ids (`begin()`); the sidecar opens its SpanSets
    under the id it received over gRPC metadata (`begin(trace_id=...)`),
    which is what makes `spans merge` able to join the two timelines.
    Timestamps are mapped to epoch microseconds through one wall/perf
    anchor pair taken at construction, so both processes share the wall
    clock domain without per-span time.time() calls."""

    def __init__(
        self,
        path: str,
        *,
        file_bytes: int = 32 << 20,
        max_bytes: int = 128 << 20,
        process: str = "host",
    ):
        from kubernetes_scheduler_tpu.trace.spans import SpanWriter

        self._writer = SpanWriter(
            path,
            file_bytes=file_bytes,
            max_bytes=max_bytes,
            process_name=process,
        )
        self.path = path
        self.process = process
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()
        self._next_id = 1
        self._id_lock = threading.Lock()
        self.spans_dropped = 0

    @property
    def spans_written(self) -> int:
        return self._writer.events_written

    @property
    def bytes_written(self) -> int:
        return self._writer.bytes_written

    def begin(self, trace_id: int | None = None) -> SpanSet:
        if trace_id is None:
            with self._id_lock:
                trace_id = self._next_id
                self._next_id += 1
        return SpanSet(trace_id)

    def _ts_us(self, t_perf: float) -> float:
        return (self._wall0 + (t_perf - self._perf0)) * 1e6

    def flush(self, ss: SpanSet, *, seq: int | None = None, tid: int = 0) -> None:
        """Encode and write one cycle's spans. Every event carries the
        trace id; `seq` cross-links the cycle to its flight-recorder
        record so a replayed cycle can be found in the timeline. Never
        raises into the scheduling loop — a failed write logs, counts,
        and drops the set."""
        try:
            events = []
            for name, t0, t1, args in ss.spans:
                a = {"trace_id": ss.trace_id}
                if seq is not None:
                    a["seq"] = seq
                if args:
                    a.update(args)
                events.append(
                    {
                        "name": name,
                        "ph": "X",
                        "cat": self.process,
                        "ts": round(self._ts_us(t0), 3),
                        "dur": round((t1 - t0) * 1e6, 3),
                        "pid": self._writer.pid,
                        "tid": tid,
                        "args": a,
                    }
                )
            self._writer.append(events)
        except Exception:
            log.exception("spans: cycle flush failed; dropping span set")
            # the sidecar's recorder is shared by concurrent RPC workers
            with self._id_lock:
                self.spans_dropped += 1

    def close(self) -> None:
        self._writer.close()


# ---- HTTP exporters -------------------------------------------------------


class HttpMetricsServer:
    """Minimal threaded HTTP exporter: /metrics from a render callable,
    /healthz, and (when armed with a profile callable) the on-demand
    /debug/profile?cycles=N endpoint. The host's MetricsExporter and
    the sidecar's exporter (bridge/server.py) are both this class with
    different render sources."""

    def __init__(self, render, *, profile=None):
        self._render = render      # () -> str (Prometheus exposition)
        self._profile = profile    # (cycles: int) -> dict, or None
        self._server: http.server.ThreadingHTTPServer | None = None

    def serve(self, port: int, host: str = "0.0.0.0") -> int:
        """Bind `host`:`port` (0 = ephemeral) and serve on a daemon
        thread; returns the bound port. The bind host is configurable
        (SchedulerConfig.metrics_bind_host) — tests bind loopback, the
        deploy manifests bind all interfaces for the scrape."""
        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    try:
                        body = exporter._render().encode()
                    except Exception:
                        log.exception("metrics render failed")
                        self.send_error(500)
                        return
                    ctype = "text/plain; version=0.0.4"
                elif path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                elif path == "/debug/profile":
                    if exporter._profile is None:
                        self.send_error(404)
                        return
                    from urllib.parse import parse_qs

                    try:
                        cycles = int(
                            parse_qs(query).get("cycles", ["1"])[0]
                        )
                    except ValueError:
                        self.send_error(400, "cycles must be an integer")
                        return
                    cycles = max(1, min(cycles, 1000))
                    try:
                        report = exporter._profile(cycles)
                    except Exception as e:
                        log.exception("profile arm failed")
                        report = {"armed": 0, "error": str(e)}
                    body = (json.dumps(report) + "\n").encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                log.debug("metrics http: " + fmt, *args)

        self._server = http.server.ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self._server.server_address[1]

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class MetricsExporter(HttpMetricsServer):
    """Serves /metrics (Prometheus text format), /healthz, and
    /debug/profile for a live Scheduler, on a daemon thread. The
    exposition is the legacy summarize() gauges plus the scheduler's
    labeled collectors (prom_collectors) and the recorder/span-writer
    running totals."""

    def __init__(self, scheduler):
        super().__init__(self._render_scheduler, profile=self._arm_profile)
        self.scheduler = scheduler

    def _arm_profile(self, cycles: int) -> dict:
        armer = getattr(self.scheduler, "arm_profile", None)
        if armer is None:
            return {"armed": 0, "error": "scheduler has no profile surface"}
        return armer(cycles)

    def _render_scheduler(self) -> str:
        sched = self.scheduler
        if hasattr(sched, "metrics_snapshot"):
            window, totals = sched.metrics_snapshot()
        else:
            window, totals = list(sched.metrics), None
        stale = getattr(
            getattr(sched, "advisor", None), "stale_served", None
        )
        extra = {}
        if stale is not None:
            extra["advisor_stale_served_total"] = stale
        rec = getattr(sched, "recorder", None)
        if rec is not None:
            extra.update(
                cycles_recorded_total=rec.cycles_recorded,
                trace_bytes_total=rec.bytes_written,
                trace_records_dropped_total=rec.records_dropped,
            )
        spans = getattr(sched, "spans", None)
        if spans is not None:
            extra.update(
                spans_written_total=spans.spans_written,
                span_bytes_total=spans.bytes_written,
                spans_dropped_total=spans.spans_dropped,
            )
        body = render_prometheus(window, totals, extra or None)
        for collector in getattr(sched, "prom_collectors", ()):
            body += "\n".join(collector.render()) + "\n"
        return body


def profile_device_step(engine_call, out_dir: str):
    """Run one engine call under a jax.profiler trace; the resulting
    TensorBoard protobufs in `out_dir` break the step into XLA ops."""
    import jax

    with jax.profiler.trace(out_dir):
        result = engine_call()
        # graftlint: disable=host-sync -- profiling needs the device barrier; never on the cycle path
        jax.block_until_ready(result)
    return result
