"""Replicated scheduler fleet: first-bind-wins over a partitioned queue.

This module lands the primitives the `replica-bind` protocol model
(analysis/model/protocols.py) was checked against BEFORE any of this
code existed — no-double-bind and bound-pod-never-re-popped hold for
every interleaving of the abstract transitions, and the
`unfenced-replica-bind` mutant proves the epoch fence is load-bearing.
The mapping, transition by transition (the model anchors in
protocols.py bind to exactly these defs, so drift fails lint):

  pop_{r}        -> ReplicaCoordinator.pop_window: filter already-bound
                    pods out of the popped window (drop_bound below) and
                    record the bind-table epoch each surviving pod was
                    seen at — the fence the CAS compares against.
  bind_win_{r}   -> BindTable.try_bind: ONE compare-and-swap under ONE
                    lock — pod unbound AND seen epoch current, else the
                    bind is rejected. Success installs the winner and
                    advances the epoch, fencing every other replica's
                    in-flight copy of the pod.
  bind_lose_{r}  -> ReplicaCoordinator.bind_lose: the losing replica
                    returns the pod through restore_window (front-of-
                    partition semantics preserved) — the pod is NOT
                    lost, it re-pops next cycle and resolves via
                    drop_bound. FencedBinder then raises with
                    status=409, which Scheduler._bind already treats as
                    "bound by a racer" (mark_scheduled, never requeue).
  drop_bound_{r} -> ReplicaCoordinator.drop_bound: a re-popped pod the
                    table shows bound is discarded via mark_scheduled
                    (retry-counter cleanup; on the native queue this
                    also releases the handle when no copy remains).

Partitioning (host/queue.pod_partition) makes conflicts the EXCEPTION:
each replica owns a crc32(namespace) partition, so two replicas only
race on a pod during partition handoff (fleet resize, membership churn,
double-submit) — the protocol makes those races safe, the partitioning
makes them rare. Gangs never straddle partitions by construction (the
gang key is namespace-prefixed), so gang atomicity stays single-replica.

Membership — which replica owns which partition — is leader.
ReplicaMembership: N slot leases, each an ordinary fenced lease; the
slot index IS the partition index.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable

from kubernetes_scheduler_tpu.host.observe import Counter
from kubernetes_scheduler_tpu.host.queue import (
    PartitionedQueue,
    pod_partition,
)
from kubernetes_scheduler_tpu.host.types import Pod

log = logging.getLogger("yoda_tpu.replica")


def _pod_key(pod: Pod) -> str:
    return f"{pod.namespace}/{pod.name}"


class BindConflictError(RuntimeError):
    """Raised by FencedBinder when the bind-table CAS rejects a bind:
    another replica bound the pod first (or the epoch moved — a stale
    pop). status=409 deliberately: it is the SAME race the live API
    server answers 409 Conflict for, and Scheduler._bind's existing
    404/409 arm (drop, never requeue) is exactly the right resolution —
    the loser's requeue already happened via bind_lose before this
    raise, so the scheduler must NOT requeue it a second time."""

    status = 409


class BindTable:
    """The shared first-bind-wins table: pod key -> (epoch, holder).

    One lock, one dict — the whole cross-replica protocol reduces to
    try_bind's compare-and-swap, which is why it was model-checkable.
    Epochs start at 0 and advance only on a successful bind; a replica
    must present the epoch it popped the pod at (stale-epoch fencing),
    so a pod that was bound and re-exposed between a loser's pop and
    its bind attempt still cannot double-bind.

    The table also keeps a per-key win count as run evidence: wins > 1
    for any key is a double bind, and `double_binds` is asserted == 0
    by the bench row, the replica scenario, and `make replica-smoke`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # key -> [epoch, holder, wins]
        self._recs: dict[str, list] = {}

    def _rec(self, key: str) -> list:
        rec = self._recs.get(key)
        if rec is None:
            rec = self._recs[key] = [0, "", 0]
        return rec

    def epoch(self, key: str) -> int:
        with self._lock:
            return self._rec(key)[0]

    def holder(self, key: str) -> str:
        """The replica that bound this key, or "" while unbound."""
        with self._lock:
            rec = self._recs.get(key)
            return rec[1] if rec is not None else ""

    def try_bind(self, key: str, seen_epoch: int, replica: str) -> bool:
        """The CAS: install `replica` as the binder of `key` iff the key
        is unbound AND `seen_epoch` matches the key's current epoch (the
        fence — a stale pop presents an old epoch and is rejected even
        if the key looks unbound). Success advances the epoch."""
        with self._lock:
            rec = self._rec(key)
            if rec[1] != "":
                return False  # first bind already won
            if seen_epoch != rec[0]:
                return False  # stale-epoch fencing: late/stale pop
            rec[1] = replica
            rec[0] += 1
            rec[2] += 1
            return True

    @property
    def double_binds(self) -> int:
        """Keys bound more than once — 0 by construction; exported as
        run evidence, not as a tolerated failure mode."""
        with self._lock:
            return sum(1 for rec in self._recs.values() if rec[2] > 1)

    @property
    def bound(self) -> int:
        with self._lock:
            return sum(1 for rec in self._recs.values() if rec[1] != "")

    def holders(self) -> dict:
        """key -> winning replica snapshot (bound keys only)."""
        with self._lock:
            return {
                k: rec[1] for k, rec in self._recs.items() if rec[1] != ""
            }


class ReplicaCoordinator:
    """One replica's view of its queue partition, fenced by the shared
    BindTable. Presents the full SchedulingQueue surface, so a Scheduler
    takes it via the `queue=` injection seam and runs UNCHANGED — the
    protocol lives entirely in this wrapper plus FencedBinder.

    restore_window / requeue_unschedulable / mark_scheduled forward to
    the partition's own queue, so per-partition ordering semantics
    (front-restore on the Python queue, back-restore on the native
    heap), gang atomicity, and the pipelined prefetch slot are exactly
    the single-queue semantics.
    """

    def __init__(
        self,
        replica: str,
        inner,
        table: BindTable,
        *,
        binds_counter: Counter | None = None,
        conflicts_counter: Counter | None = None,
    ):
        self.replica = replica
        self.inner = inner
        self.table = table
        self.RESTORES_TO_FRONT = getattr(inner, "RESTORES_TO_FRONT", False)
        self._clock = inner._clock
        self._binds_counter = binds_counter
        self._conflicts_counter = conflicts_counter
        # pod key -> bind-table epoch at pop time (the fence operand)
        self._seen: dict[str, int] = {}
        # pod key -> clock at bind_lose, for requeue-to-resolution latency
        self._lost_at: dict[str, float] = {}
        self.binds = 0
        self.conflicts = 0
        self.pods_discarded = 0  # drop_bound count
        self.requeue_latencies: list[float] = []

    # -- queue surface -------------------------------------------------

    def push(self, pod: Pod) -> None:
        self.inner.push(pod)

    def pop_window(self, max_pods: int) -> list[Pod]:
        """Pop from this replica's partition, dropping pods the bind
        table already shows bound (the drop_bound transition) and
        recording the epoch each surviving pod was seen at — try_bind
        compares against exactly this value (the stale-epoch fence)."""
        out = []
        table = self.table
        for pod in self.inner.pop_window(max_pods):
            key = _pod_key(pod)
            if table.holder(key) != "":
                self.drop_bound(pod)
                continue
            self._seen[key] = table.epoch(key)
            out.append(pod)
        return out

    def restore_window(self, pods: list[Pod]) -> None:
        self.inner.restore_window(pods)

    def requeue_unschedulable(self, pod: Pod) -> None:
        self.inner.requeue_unschedulable(pod)

    def mark_scheduled(self, pod: Pod) -> None:
        self.inner.mark_scheduled(pod)

    def mark_scheduled_many(self, pods: list[Pod]) -> None:
        if hasattr(self.inner, "mark_scheduled_many"):
            self.inner.mark_scheduled_many(pods)
        else:
            for pod in pods:
                self.inner.mark_scheduled(pod)

    def __len__(self) -> int:
        return len(self.inner)

    # -- protocol transitions -----------------------------------------

    def drop_bound(self, pod: Pod) -> None:
        """A re-popped pod the table shows bound (drop_bound_{r}): it
        already ran its lifecycle on the winning replica — discard it
        here via mark_scheduled (clears this partition's retry counter;
        on the native queue, releases the handle once no copy remains).
        Closes the loser's requeue loop: bind_lose restored the pod,
        this drop retires it."""
        self.pods_discarded += 1
        key = _pod_key(pod)
        lost_at = self._lost_at.pop(key, None)
        if lost_at is not None:
            self.requeue_latencies.append(self._clock() - lost_at)
        self._seen.pop(key, None)
        self.inner.mark_scheduled(pod)

    def bind_win(self, pod: Pod) -> bool:
        """Attempt the CAS (bind_win_{r}): True installs this replica as
        the pod's binder and fences every other in-flight copy."""
        key = _pod_key(pod)
        won = self.table.try_bind(
            key, self._seen.pop(key, -1), self.replica
        )
        if won:
            self.binds += 1
            if self._binds_counter is not None:
                self._binds_counter.inc(replica=self.replica)
            lost_at = self._lost_at.pop(key, None)
            if lost_at is not None:
                self.requeue_latencies.append(self._clock() - lost_at)
        return won

    def bind_lose(self, pod: Pod) -> None:
        """The CAS lost (bind_lose_{r}): first bind won elsewhere, or
        the epoch moved under a stale pop. Requeue the pod through
        restore_window — front-of-partition on the Python queue, so it
        re-pops next cycle and resolves via drop_bound. The pod is
        never lost: either the winner's bind stands (drop_bound retires
        our copy) or — epoch races without a standing bind — the next
        pop re-records a fresh epoch and the bind retries."""
        self.conflicts += 1
        if self._conflicts_counter is not None:
            self._conflicts_counter.inc()
        key = _pod_key(pod)
        self._lost_at.setdefault(key, self._clock())
        self.inner.restore_window([pod])


class FencedBinder:
    """Binder wrapper running the CAS before every real bind.

    Deliberately does NOT define bind_many: Scheduler._apply_assignments
    only takes the bulk-bind path when the binder offers it, and the
    per-pod path is where the 404/409 conflict semantics live — the
    same reason the live KubeBinder keeps per-pod binds (scheduler.py's
    RecordingBinder.bind_many docstring).

    On CAS loss the pod is FIRST requeued via bind_lose (restore_window
    on its own partition), THEN BindConflictError(status=409) propagates
    to Scheduler._bind, which drops its copy (mark_scheduled +
    pods_dropped) exactly as it would an API-server 409 — no double
    requeue, no lost pod.
    """

    def __init__(self, inner, coordinator: ReplicaCoordinator):
        self._inner = inner
        self.coordinator = coordinator

    @property
    def bindings(self):
        """Recorded bindings of the wrapped binder (simulation /
        scenario binders record; the live KubeBinder does not)."""
        return self._inner.bindings

    def bind(self, pod: Pod, node_name: str) -> None:
        coord = self.coordinator
        if not coord.bind_win(pod):
            coord.bind_lose(pod)
            raise BindConflictError(
                f"first bind won on another replica: {_pod_key(pod)} "
                f"(held by {coord.table.holder(_pod_key(pod)) or 'epoch race'})"
            )
        self._inner.bind(pod, node_name)


class ReplicaFleet:
    """N Schedulers over one PartitionedQueue and one BindTable.

    Each replica is a FULL Scheduler — its own journal (per-replica
    trace_path subdirectory, so `trace replay` pins each replica's
    cycles independently), its own span recorder, its own degradation
    ladder and prom collectors — wired to its partition through a
    ReplicaCoordinator and to its binder through a FencedBinder. The
    fleet adds the two cross-replica metrics the protocol calls for:
    replica_binds_total{replica} (CAS wins per replica) and
    bind_conflicts_total (CAS losses, i.e. conflicts RESOLVED — each
    one is a loser requeued and retired, never a lost pod).

    In production each replica is its own process holding a membership
    slot (leader.ReplicaMembership; slot index == partition index) and
    its own /metrics exporter; this in-process fleet is the simulation/
    bench/scenario harness for the same topology.
    """

    def __init__(
        self,
        config,
        *,
        n_replicas: int,
        advisor_factory: Callable[[int], object],
        list_nodes,
        list_running_pods,
        binder_factory: Callable[[int], object] | None = None,
        engine_factory: Callable[[int], object] | None = None,
        evictor_factory: Callable[[int], object] | None = None,
        queue_clock=None,
        prefer_native: bool | None = None,
    ):
        from kubernetes_scheduler_tpu.host.scheduler import (
            RecordingBinder,
            Scheduler,
        )

        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.config = config
        self.n_replicas = n_replicas
        self.table = BindTable()
        self.ctr_binds = Counter(
            "replica_binds_total",
            "Pods bound per scheduler replica (bind-table CAS wins).",
            labels=("replica",),
        )
        self.ctr_conflicts = Counter(
            "bind_conflicts_total",
            "Cross-replica bind conflicts resolved first-bind-wins "
            "(loser requeued through restore_window; never a lost pod).",
        )
        if prefer_native is None:
            if config.feature_gates.native_host:
                from kubernetes_scheduler_tpu import native

                prefer_native = native.available()
            else:
                prefer_native = False
        self.queue = PartitionedQueue(
            n_replicas,
            initial_backoff=config.initial_backoff_seconds,
            max_backoff=config.max_backoff_seconds,
            prefer_native=prefer_native,
            **({"clock": queue_clock} if queue_clock is not None else {}),
        )
        # fleet-shared device engine (config.shared_engine): ONE
        # Local/Remote engine behind a SharedEnginePool, each replica
        # wired through a per-replica view — one resident snapshot and
        # one upload per churn event for the whole fleet, concurrent
        # windows coalesced into one device invocation. engine_factory
        # is consulted ONCE (replica 0) for the pool's inner engine;
        # decisions stay bit-identical to private engines (PARITY.md
        # round 20), so the BindTable protocol above is untouched.
        self.engine_pool = None
        if getattr(config, "shared_engine", False):
            from kubernetes_scheduler_tpu.host.engine_pool import (
                SharedEnginePool,
            )

            self.engine_pool = SharedEnginePool(
                engine_factory(0) if engine_factory else None,
                coalesce_window_ms=config.coalesce_window_ms,
            )
        self.coordinators: list[ReplicaCoordinator] = []
        self.schedulers = []
        for i in range(n_replicas):
            name = f"r{i}"
            coord = ReplicaCoordinator(
                name,
                self.queue.partition(i),
                self.table,
                binds_counter=self.ctr_binds,
                conflicts_counter=self.ctr_conflicts,
            )
            # per-replica journal/span directories: each replica's
            # cycles replay independently (`trace replay <dir>/r0`)
            cfg_r = dataclasses.replace(
                config,
                trace_path=(
                    f"{config.trace_path}/{name}" if config.trace_path else None
                ),
                span_path=(
                    f"{config.span_path}/{name}" if config.span_path else None
                ),
            )
            binder = (
                binder_factory(i) if binder_factory else RecordingBinder()
            )
            sched = Scheduler(
                cfg_r,
                advisor=advisor_factory(i),
                binder=FencedBinder(binder, coord),
                evictor=evictor_factory(i) if evictor_factory else None,
                list_nodes=list_nodes,
                list_running_pods=list_running_pods,
                engine=(
                    self.engine_pool.view(name)
                    if self.engine_pool is not None
                    else engine_factory(i) if engine_factory else None
                ),
                queue_clock=queue_clock,
                queue=coord,
            )
            self.coordinators.append(coord)
            self.schedulers.append(sched)

    # -- submission ----------------------------------------------------

    def partition_of(self, pod: Pod) -> int:
        return pod_partition(pod, self.n_replicas)

    def submit(self, pod: Pod) -> None:
        """Route the pod to its partition's replica (deterministic
        crc32(namespace) assignment — same partition across restarts)."""
        self.schedulers[self.partition_of(pod)].submit(pod)

    def submit_overlap(self, pod: Pod, replicas=None) -> None:
        """Hand the SAME pod to several replicas — the partition-handoff
        overlap (membership churn re-homing a namespace while the old
        owner still holds queued copies). This is the conflict-storm
        generator: every overlap pod races, first bind wins, the loser
        resolves through bind_lose -> drop_bound, and the run evidence
        must still show zero double binds."""
        for i in replicas if replicas is not None else range(self.n_replicas):
            self.schedulers[i].submit(pod)

    # -- drains --------------------------------------------------------

    def run_until_empty(self, *, max_cycles: int = 1000) -> dict:
        """Drain every replica CONCURRENTLY (one thread per replica) —
        the real fleet topology, and the interleavings the protocol was
        checked against. Returns per-replica summaries + fleet evidence."""
        results = [None] * self.n_replicas
        errors = [None] * self.n_replicas
        start = threading.Barrier(self.n_replicas)

        def _drain(i):
            try:
                start.wait(timeout=30)
            except threading.BrokenBarrierError:
                pass
            try:
                results[i] = self.schedulers[i].run_until_empty(
                    max_cycles=max_cycles
                )
            except Exception as e:  # surfaced below, never swallowed
                errors[i] = e
                log.exception("replica r%d drain failed", i)

        threads = [
            threading.Thread(target=_drain, args=(i,), daemon=True)
            for i in range(self.n_replicas)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for e in errors:
            if e is not None:
                raise e
        return self.evidence(results)

    def run_round_split(self) -> list:
        """One deterministic fleet round through the split-phase cycle
        seam (Scheduler.run_cycle_split): dispatch EVERY replica's
        window first, then complete them in order. With a shared engine
        all N windows sit in the pool's queue when the first force
        arrives, so the round coalesces into one device invocation —
        round-robin harnesses get the coalescing a threaded fleet gets
        from timing, deterministically. Works (as a plain pipelined
        cycle per replica) with private engines too."""
        handles = [s.run_cycle_split() for s in self.schedulers]
        return [h.complete() for h in handles]

    def run_sequential(self, *, max_cycles: int = 1000) -> dict:
        """Drain replicas one at a time, timing each drain — the
        deterministic scaling probe. N single-host processes would run
        their partitions in true parallel; under one GIL the honest
        aggregate rate is total_bound / max(per-replica busy seconds),
        which this returns alongside the per-replica wall times."""
        results = []
        busy = []
        for sched in self.schedulers:
            t0 = time.perf_counter()
            results.append(sched.run_until_empty(max_cycles=max_cycles))
            busy.append(time.perf_counter() - t0)
        ev = self.evidence(results)
        ev["replica_busy_seconds"] = busy
        ev["aggregate_drain_seconds"] = max(busy) if busy else 0.0
        return ev

    # -- evidence ------------------------------------------------------

    def evidence(self, results=None) -> dict:
        """The fleet-level numbers every replica harness asserts on:
        zero double binds, conflicts resolved, accounting intact."""
        lat = [
            s for c in self.coordinators for s in c.requeue_latencies
        ]
        ev = {
            "replicas": self.n_replicas,
            "binds_per_replica": {
                c.replica: c.binds for c in self.coordinators
            },
            "total_binds": sum(c.binds for c in self.coordinators),
            "bind_conflicts_total": self.ctr_conflicts.value(),
            "pods_discarded": sum(
                c.pods_discarded for c in self.coordinators
            ),
            "double_binds": self.table.double_binds,
            "requeue_latency_count": len(lat),
            "requeue_latency_mean_s": (sum(lat) / len(lat)) if lat else 0.0,
            "requeue_latency_max_s": max(lat) if lat else 0.0,
        }
        if self.engine_pool is not None:
            ev["shared_engine"] = self.engine_pool.stats()
        if results is not None:
            ev["replica_results"] = results
        return ev

    def prom_collectors(self, replica: int):
        """Collector tuple for replica i's exporter: the scheduler's own
        collectors (per-replica degradation_rung, cycle histograms, ...)
        plus the fleet counters (shared objects — every replica's
        /metrics shows the fleet's conflict picture)."""
        return tuple(self.schedulers[replica].prom_collectors) + (
            self.ctr_binds,
            self.ctr_conflicts,
        )

    @property
    def bindings(self):
        """Union of all replicas' recorded bindings (simulation binders)."""
        out = []
        for sched in self.schedulers:
            out.extend(sched.binder.bindings)
        return out
