"""Lease-based leader election: active/passive scheduler replicas.

The reference gets HA from upstream kube-scheduler's lease leader election
(deploy/yoda-scheduler.yaml:10-17: leaderElect on resourceLock
"endpointsleases" in kube-system). This module provides the same
active/passive failover contract with a pluggable lease backend:

- `FileLease` (here) — a shared-filesystem lease for simulation, tests,
  and single-host pod pairs (atomic claim via O_EXCL + fsync'd renew
  records).
- `kube.lease.KubeLease` — the Kubernetes coordination.k8s.io/v1 backend
  behind the same `Lease` protocol (resourceVersion CAS on the cluster
  Lease object), selected with `--lease-kube`.

Semantics mirror k8s.io/client-go leaderelection: a lease carries (holder
identity, acquire time, renew time, duration); a candidate acquires when
the lease is unheld or expired; the holder renews every `retry_period`
and loses leadership when renewal fails or the lease was stolen.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import os
import threading
import time
from typing import Protocol

log = logging.getLogger("yoda_tpu.leader")


@dataclasses.dataclass
class LeaseRecord:
    holder: str
    acquired_at: float
    renewed_at: float
    duration: float

    def expired(self, now: float) -> bool:
        return now > self.renewed_at + self.duration


class Lease(Protocol):
    def read(self) -> LeaseRecord | None: ...
    def try_claim(self, record: LeaseRecord, previous: LeaseRecord | None) -> bool: ...
    def clear(self, holder: str) -> None: ...


class FileLease:
    """Lease on a shared filesystem. Claims are atomic: a new lease file is
    written to a temp path and linked into place only if the current
    content still matches `previous` (compare-and-swap under an O_EXCL
    lock file)."""

    def __init__(self, path: str):
        self.path = path
        self._lock_path = path + ".lock"

    def read(self) -> LeaseRecord | None:
        try:
            with open(self.path) as f:
                return LeaseRecord(**json.load(f))
        except (FileNotFoundError, json.JSONDecodeError, TypeError):
            return None

    def _locked(self):
        class _Lock:
            def __enter__(inner):
                deadline = time.monotonic() + 5.0
                while True:
                    try:
                        inner.fd = os.open(
                            self._lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY
                        )
                        return inner
                    except FileExistsError:
                        if time.monotonic() > deadline:
                            # stale lock (holder died mid-claim): break it
                            try:
                                os.unlink(self._lock_path)
                            except FileNotFoundError:
                                pass
                        time.sleep(0.05)

            def __exit__(inner, *exc):
                os.close(inner.fd)
                try:
                    os.unlink(self._lock_path)
                except FileNotFoundError:
                    pass

        return _Lock()

    def try_claim(
        self, record: LeaseRecord, previous: LeaseRecord | None
    ) -> bool:
        with self._locked():
            current = self.read()
            cur_key = (current.holder, current.renewed_at) if current else None
            prev_key = (previous.holder, previous.renewed_at) if previous else None
            if cur_key != prev_key:
                return False
            tmp = f"{self.path}.{record.holder}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(dataclasses.asdict(record), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            return True

    def clear(self, holder: str) -> None:
        with self._locked():
            current = self.read()
            if current and current.holder == holder:
                try:
                    os.unlink(self.path)
                except FileNotFoundError:
                    pass


class LeaderElector:
    """client-go leaderelection.LeaderElector analog.

    acquire_blocking() returns once this identity holds the lease; a
    daemon thread renews it every `retry_period`. is_leader() flips False
    if renewal is lost (a standby stole an expired lease) — the scheduler
    loop must check it each cycle and stop binding when not leading.
    """

    def __init__(
        self,
        lease: Lease,
        *,
        identity: str | None = None,
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
        renew_deadline: float | None = None,
    ):
        self.lease = lease
        self.identity = identity or f"{os.uname().nodename}-{os.getpid()}"
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        # client-go keeps renewDeadline (10s) strictly below leaseDuration
        # (15s): the holder declares itself non-leader BEFORE the instant
        # a standby may steal the expired lease, so there is no
        # dual-leader window. Default 2/3; the clamp below holds for
        # explicit values too — a deadline >= the lease duration would
        # reopen the window the deadline exists to close.
        if renew_deadline is None:
            renew_deadline = max(lease_duration * (2.0 / 3.0), retry_period * 1.5)
        self.renew_deadline = min(renew_deadline, lease_duration * 0.9)
        self._leading = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def is_leader(self) -> bool:
        return self._leading.is_set()

    def _try_acquire_once(self) -> bool:
        now = time.time()
        current = self.lease.read()
        if current and current.holder == self.identity:
            acquired = current.acquired_at
        elif current and not current.expired(now):
            return False
        else:
            acquired = now
        record = LeaseRecord(
            holder=self.identity,
            acquired_at=acquired,
            renewed_at=now,
            duration=self.lease_duration,
        )
        return self.lease.try_claim(record, current)

    def _try_acquire_safe(self) -> bool:
        """Acquire/renew attempt that treats backend errors as failure.

        Network-backed leases (kube.lease.KubeLease) can raise on a
        transient API outage; an exception must not kill the renew thread
        while is_leader() still reads True (silent split-brain)."""
        try:
            return self._try_acquire_once()
        except Exception as e:
            log.warning("lease backend error (%s): %s", self.identity, e)
            return False

    def acquire_blocking(self, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._stop.is_set():
            if self._try_acquire_safe():
                self._leading.set()
                log.info("acquired leadership as %s", self.identity)
                self._thread = threading.Thread(target=self._run_loop, daemon=True)
                self._thread.start()
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(self.retry_period)
        return False

    def _run_loop(self) -> None:
        """Renew while leading; on loss, keep trying to re-acquire.

        Loss is TIME-based, like client-go: one failed renew (a transient
        API hiccup) keeps leadership until `renew_deadline` — strictly
        shorter than the lease duration, so this holder stops scheduling
        before the instant a standby may steal the expired lease (no
        dual-leader window). The loop then stays in candidate mode so a
        recovered replica resumes scheduling without a process restart
        (the caller's loop pauses on is_leader()==False rather than
        exiting)."""
        # monotonic: the deadline measures LOCAL elapsed time since the
        # last successful renew; wall-clock (time.time) would stretch the
        # window across an NTP step-back, reopening the dual-leader gap
        last_renew = time.monotonic()
        while not self._stop.wait(self.retry_period):
            if self._try_acquire_safe():
                last_renew = time.monotonic()
                if not self._leading.is_set():
                    log.info("re-acquired leadership as %s", self.identity)
                    self._leading.set()
            elif self._leading.is_set() and (
                time.monotonic() - last_renew > self.renew_deadline
            ):
                log.warning("lost leadership (%s)", self.identity)
                self._leading.clear()

    def release(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.retry_period * 2)
        if self._leading.is_set():
            self.lease.clear(self.identity)
            self._leading.clear()


# distinct default identities for memberships created within one
# process (see ReplicaMembership.__init__)
_MEMBERSHIP_SEQ = itertools.count()


class ReplicaMembership:
    """Elected MEMBERSHIP for the replicated fleet: N slots, each an
    ordinary fenced lease, where the slot index IS the queue partition
    index (host/queue.pod_partition with n_partitions == n_slots).

    This generalizes the single active/passive pair to N active
    replicas: instead of one lease everyone contends for, a joining
    replica claims the first free slot (scanning 0..N-1, one-shot
    acquire per slot, then backing off). Holding slot i means "I own
    partition i" — renewal, renew-deadline fencing, and loss semantics
    are exactly LeaderElector's, so a crashed replica's partition
    becomes claimable after its lease expires and the successor resumes
    that partition's queue. Safety does NOT rest on the lease: even a
    zombie replica that schedules past its deadline cannot double-bind,
    because every bind runs the bind-table CAS (host/replica.BindTable)
    — the lease bounds unowned-partition downtime, the CAS guards
    correctness. `yoda-tpu scheduler --replicas N` joins one membership
    per in-process replica.
    """

    def __init__(
        self,
        make_lease,
        n_slots: int,
        *,
        identity: str | None = None,
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
        renew_deadline: float | None = None,
    ):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self._make_lease = make_lease
        self.n_slots = n_slots
        # the default identity carries a per-instance sequence number:
        # nodename-pid alone would make two memberships in ONE process
        # (the in-process fleet runner) look like the same holder, and
        # a slot lease re-acquires for its own identity — both would
        # "win" slot 0
        self.identity = identity or (
            f"{os.uname().nodename}-{os.getpid()}"
            f"-m{next(_MEMBERSHIP_SEQ)}"
        )
        self._kw = dict(
            lease_duration=lease_duration,
            retry_period=retry_period,
            renew_deadline=renew_deadline,
        )
        self.retry_period = retry_period
        self.slot: int | None = None
        self.elector: LeaderElector | None = None

    @classmethod
    def on_files(cls, path: str, n_slots: int, **kw) -> "ReplicaMembership":
        """Membership over FileLease slot files `<path>.slot<i>`."""
        return cls(
            lambda i: FileLease(f"{path}.slot{i}"), n_slots, **kw
        )

    def join(self, timeout: float | None = None) -> int | None:
        """Claim the first free slot; block up to `timeout` (None =
        forever). Returns the slot index — the partition this replica
        now owns — or None on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            for i in range(self.n_slots):
                elector = LeaderElector(
                    self._make_lease(i),
                    # filesystem-safe separator: FileLease embeds the
                    # identity in its tmp-file name, so "/" would point
                    # the write at a nonexistent directory
                    identity=f"{self.identity}.slot{i}",
                    **self._kw,
                )
                # one-shot: timeout=0 tries the slot once and moves on
                if elector.acquire_blocking(timeout=0):
                    self.slot = i
                    self.elector = elector
                    log.info(
                        "joined membership as %s: slot %d of %d",
                        self.identity, i, self.n_slots,
                    )
                    return i
            if deadline is not None and time.monotonic() > deadline:
                return None
            time.sleep(self.retry_period)

    def is_member(self) -> bool:
        """True while this replica's slot lease is held (same fencing
        as LeaderElector.is_leader — flips False before the slot is
        stealable)."""
        return self.elector is not None and self.elector.is_leader()

    def leave(self) -> None:
        if self.elector is not None:
            self.elector.release()
            self.elector = None
            self.slot = None
