"""Typed cluster objects — the host-side stand-ins for the k8s API types
the reference consumes (v1.Pod, v1.Node, framework.NodeInfo, and the SCV
CRD's Card/Scv, pkg/yoda/filter/filter.go:8).

Deliberately minimal: only the fields the scheduling capabilities touch.
String quantities use plain floats in canonical units (cpu millicores,
bytes, counts) — parsing of k8s quantity strings ("500m", "2Gi") is in
parse_quantity below.
"""

from __future__ import annotations

from dataclasses import dataclass, field


def parse_quantity(q: str | int | float) -> float:
    """k8s resource.Quantity subset: '500m', '2Gi', '1.5', 4."""
    if isinstance(q, (int, float)):
        return float(q)
    s = q.strip()
    suffixes = {
        "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
        "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15,
    }
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    for suf, mult in suffixes.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    return float(s)


def parse_cpu_milli(q: str | int | float) -> float:
    """CPU quantity to millicores ('500m' -> 500, 2 -> 2000)."""
    if isinstance(q, str) and q.strip().endswith("m"):
        return float(q.strip()[:-1])
    return parse_quantity(q) * 1000.0


@dataclass
class Container:
    requests: dict[str, float] = field(default_factory=dict)  # canonical units
    # container image reference (upstream ImageLocality scoring input);
    # "" = unknown/absent
    image: str = ""


@dataclass
class Toleration:
    key: str | None = None   # None = empty key (wildcard with Exists)
    value: str = ""
    operator: str = "Equal"  # "Equal" | "Exists"
    effect: str = ""         # "" = all effects


@dataclass
class MatchExpression:
    key: str
    operator: str            # In | NotIn | Exists | DoesNotExist
    values: list[str] = field(default_factory=list)
    # OR-group id for required node affinity: upstream nodeSelectorTerms
    # are OR-of-AND-lists — a node passes if, for SOME term id, every
    # expression carrying that id matches (kube/convert.pod_from_api
    # assigns ids; the flat default 0 keeps a plain AND list)
    term: int = 0


def labels_match(
    labels: dict[str, str],
    match_labels: dict[str, str],
    match_expressions: list["MatchExpression"] = (),
) -> bool:
    """k8s label-selector semantics: every matchLabels pair AND every
    matchExpression must hold (missing key satisfies NotIn; an unknown
    operator fails closed). Shared by PDB selection and the snapshot
    builder's selector matching so the two cannot drift."""
    if not all(labels.get(k) == v for k, v in match_labels.items()):
        return False
    for e in match_expressions:
        has = e.key in labels
        val = labels.get(e.key)
        if e.operator == "In":
            if not has or val not in e.values:
                return False
        elif e.operator == "NotIn":
            if has and val in e.values:
                return False
        elif e.operator == "Exists":
            if not has:
                return False
        elif e.operator == "DoesNotExist":
            if has:
                return False
        else:
            return False
    return True


@dataclass
class PodAffinityTerm:
    match_labels: dict[str, str]
    topology_key: str = "kubernetes.io/hostname"
    anti: bool = False
    # preferredDuringSchedulingIgnoredDuringExecution: a score term with
    # this weight instead of a hard filter (engine.compute_soft_scores)
    preferred: bool = False
    weight: int = 1
    # labelSelector.matchExpressions, ANDed with match_labels
    match_expressions: list["MatchExpression"] = field(default_factory=list)
    # namespaces whose pods the selector may match. None = ALL
    # namespaces (host-API convenience and the namespaceSelector:{}
    # case); upstream's default — the owning pod's own namespace — is
    # what kube/convert fills in ([pod.namespace]) when the term
    # carries no explicit list
    namespaces: list[str] | None = None
    # a non-empty namespaceSelector (labels-selected namespaces,
    # k8s >= 1.21), stored as (match_labels, match_expressions) so
    # kube/convert.resolve_namespace_selectors can turn it into the
    # concrete list — upstream semantics: selector-matched namespaces
    # UNIONed with any explicit `namespaces` entries. None = no selector.
    namespace_selector: tuple | None = None


@dataclass
class SpreadConstraint:
    """topologySpreadConstraints entry: placements of pods matching the
    selector (match_labels AND match_expressions) may not skew across
    `topology_key` domains by more than `max_skew`. Skew here is measured
    against the minimum count over all schedulable nodes' domains (upstream
    additionally filters domains by the pod's node affinity — documented
    simplification).

    soft=False is DoNotSchedule (a hard filter); soft=True is
    ScheduleAnyway (a score term preferring less-skewed domains,
    engine.compute_soft_scores)."""

    match_labels: dict[str, str]
    topology_key: str = "kubernetes.io/hostname"
    max_skew: int = 1
    soft: bool = False
    match_expressions: list["MatchExpression"] = field(default_factory=list)
    # upstream spread selectors match only the pod's OWN namespace;
    # kube/convert fills [pod.namespace]. None = all namespaces
    # (host-API convenience, the pre-namespace behavior)
    namespaces: list[str] | None = None


@dataclass
class PodDisruptionBudget:
    """policy/v1 PodDisruptionBudget, the slice preemption consults
    (upstream PostFilter orders candidates by PDB violations; this
    framework's preemption never violates a budget — documented stricter
    deviation in ops/preempt.py's module docstring).

    min_available / max_unavailable accept ints or "N%" strings exactly
    like the API; disruptions_allowed, when set, is the server-computed
    status.disruptionsAllowed and takes precedence over the spec math.
    """

    name: str
    namespace: str = "default"
    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list["MatchExpression"] = field(default_factory=list)
    min_available: int | str | None = None
    max_unavailable: int | str | None = None
    disruptions_allowed: int | None = None

    def selects(self, pod: "Pod") -> bool:
        if pod.namespace != self.namespace:
            return False
        return labels_match(pod.labels, self.match_labels, self.match_expressions)

    def allowed(
        self, matching_count: int, expected_count: int | None = None
    ) -> int:
        """Evictions this budget permits given the current healthy count.

        Percentage budgets resolve against `expected_count` — the owning
        controllers' summed replica counts, as the upstream disruption
        controller computes it (host/scheduler resolves it through the
        informer-cached ReplicaSet/StatefulSet stores via the pods'
        ownerReferences). Narrowed deviation: when NO expected count is
        resolvable (controller-less pods, or no controller informer —
        simulated clusters), percentages fall back to the CURRENT
        matching count, which over-allows when replicas are already down
        (50% of 10 with 6 healthy: k8s allows 1, the fallback allows 3).
        Real clusters are doubly covered: the PDB controller maintains
        status.disruptionsAllowed, which takes precedence over all spec
        math."""
        if self.disruptions_allowed is not None:
            return max(0, int(self.disruptions_allowed))
        base = expected_count if expected_count is not None else matching_count

        def resolve(v) -> int:
            if isinstance(v, str) and v.endswith("%"):
                import math

                return math.ceil(base * float(v[:-1]) / 100.0)
            return int(v)

        if self.max_unavailable is not None:
            # upstream: healthy - (expected - maxUnavailable) — with
            # replicas already down, the missing ones count as
            # disruptions in flight (base == matching_count reduces to
            # the plain maxUnavailable resolve)
            desired_healthy = max(0, base - resolve(self.max_unavailable))
            return max(0, matching_count - desired_healthy)
        if self.min_available is not None:
            return max(0, matching_count - resolve(self.min_available))
        return matching_count  # no constraint given


@dataclass
class WeightedExpression:
    """One preferred node-affinity term: a weighted matchExpression
    (preferredDuringScheduling...; the upstream term's expression list is
    modeled as one expression per weighted term)."""

    expr: MatchExpression
    weight: int = 1
    # preferred-term group id: upstream preferred terms are weighted
    # AND-lists; expressions sharing a term id must ALL match for the
    # weight to be granted once. None = this expression is its own term.
    term: int | None = None


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    # metadata.uid: the identity that survives delete-and-recreate under
    # the same name; None for simulated pods (falls back to ns/name)
    uid: str | None = None
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: dict[str, float] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    node_affinity: list[MatchExpression] = field(default_factory=list)
    pod_affinity: list[PodAffinityTerm] = field(default_factory=list)
    preferred_node_affinity: list[WeightedExpression] = field(default_factory=list)
    topology_spread: list[SpreadConstraint] = field(default_factory=list)
    # spec.nodeName: pin to one node (upstream NodeName filter)
    target_node: str | None = None
    # hostPorts requested by any container (upstream NodePorts filter);
    # encoded as capacity-1 pseudo-resource columns by the snapshot builder
    host_ports: list[int] = field(default_factory=list)
    node_name: str | None = None  # set once bound
    scheduler_name: str = "yoda-tpu"
    # status.startTime as epoch seconds; None = not started (treated as
    # newest, i.e. least important, in preemption victim ordering —
    # upstream GetPodStartTime's nil-means-now stance)
    start_time: float | None = None
    # PVC claim names referenced by spec.volumes (kube/volumes resolves
    # bound claims' PV topology into node_affinity before scheduling)
    volume_claims: list[str] = field(default_factory=list)
    # 'ns/name' keys of this pod's ReadWriteOncePod claims (set by
    # kube/volumes.fold): the scheduler serializes access per cycle —
    # upstream VolumeRestrictions' at-most-one-pod exclusivity
    exclusive_claims: list[str] = field(default_factory=list)
    # spec.priority (PriorityClass admission). None = unset: the queue
    # and batch builder then fall back to the reference's scv/priority
    # label (sort.go:12-18); when both exist the API-server-resolved
    # spec wins, matching upstream
    priority: int | None = None
    # attachable-volumes-csi-<driver> units this pod's bound CSI
    # volumes consume (kube/volumes.attach_demands; upstream
    # NodeVolumeLimits) — folded into the pod's request vector
    attach_demands: dict[str, float] = field(default_factory=dict)
    # the controller ownerReference as (kind, name) — e.g.
    # ("ReplicaSet", "web-7d4b9"); None = controller-less. Feeds the
    # PDB percentage math's expected-replica lookup (upstream disruption
    # controller semantics)
    owner: tuple | None = None


@dataclass
class PersistentVolume:
    """The scheduling-relevant slice of a PV: its node-affinity terms
    (spec.nodeAffinity.required — OR of AND-lists, local volumes) plus
    zone/region topology labels (legacy VolumeZone semantics), already
    folded into `terms` by kube/convert.pv_from_api. A pod bound to this
    PV may only run on nodes satisfying some term."""

    name: str
    terms: list[list[MatchExpression]] = field(default_factory=list)
    # spec.csi.driver — feeds NodeVolumeLimits: each bound CSI volume
    # consumes one attachable-volumes-csi-<driver> capacity unit on its
    # node. "" = not a CSI volume (no attach-limit accounting).
    csi_driver: str = ""


@dataclass
class PersistentVolumeClaim:
    """PVC binding state: volume_name is set once the claim is Bound.
    An unbound claim (WaitForFirstConsumer, or still pending binding)
    contributes no scheduling constraint — the volume follows the pod
    (constrain-at-bind), upstream VolumeBinding's WFFC stance.
    access_modes feeds the VolumeRestrictions check: a ReadWriteOncePod
    claim already in use keeps new pods pending."""

    namespace: str
    name: str
    volume_name: str | None = None
    access_modes: list[str] = field(default_factory=list)
    # spec.storageClassName — resolves the class's volumeBindingMode for
    # the WFFC selected-node handoff (VolumeBinding's active half)
    storage_class: str | None = None
    # volume.kubernetes.io/selected-node annotation, when already set
    # (idempotency: the binder does not re-PATCH it)
    selected_node: str | None = None


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class Card:
    """GPU card, mirroring the SCV CRD status fields the reference filters
    and scores on (filter.go:52-58, algorithm.go:280-291)."""

    bandwidth: float = 0
    clock: float = 0
    core: float = 0
    power: float = 0
    free_memory: float = 0
    total_memory: float = 0
    health: str = "Healthy"


@dataclass
class Node:
    name: str
    labels: dict[str, str] = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    allocatable: dict[str, float] = field(default_factory=dict)
    cards: list[Card] = field(default_factory=list)
    # container images present on the node: image reference -> sizeBytes
    # (node.status.images; every entry's name aliases share the size) —
    # upstream ImageLocality's input
    images: dict[str, float] = field(default_factory=dict)
