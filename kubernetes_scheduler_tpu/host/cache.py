"""Per-cycle cache: what Redis was for.

The reference uses an external Redis purely as shared memory between the N
concurrent per-node Score invocations of one scheduling cycle — statistics
keys "U-<node>"/"V-<node>"/"U-AVG"/"M-tmp"/"nodeLen" and score memos
"S-<node>" (pkg/yoda/score/algorithm.go:57-89,116), wiped with FlushDB at
PreScore and NormalizeScore (pkg/yoda/scheduler.go:103,160). The batched
engine computes the whole matrix in one pass, so the cross-call
side-channel disappears; this in-process cache remains for (a) the scalar
fallback path, which has the same memoization structure, and (b) optional
TTL'd entries like the dead path's 60-minute score cache
(algorithm.go:171).
"""

from __future__ import annotations

import time
from typing import Any


class CycleCache:
    def __init__(self, *, clock=time.monotonic):
        self._data: dict[str, tuple[Any, float | None]] = {}
        self._clock = clock

    def set(self, key: str, value: Any, ttl_seconds: float | None = None) -> None:
        expires = None if ttl_seconds is None else self._clock() + ttl_seconds
        self._data[key] = (value, expires)

    def get(self, key: str, default: Any = None) -> Any:
        item = self._data.get(key)
        if item is None:
            return default
        value, expires = item
        if expires is not None and self._clock() > expires:
            del self._data[key]
            return default
        return value

    def __contains__(self, key: str) -> bool:
        return self.get(key, _MISSING) is not _MISSING

    def flush(self) -> None:
        """FlushDB equivalent (scheduler.go:103,160)."""
        self._data.clear()


_MISSING = object()
