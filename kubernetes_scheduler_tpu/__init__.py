"""kubernetes_scheduler_tpu — a TPU-native batched cluster-scheduling framework.

Re-imagines the capabilities of the Yoda kube-scheduler plugin
(Mr-LvGJ/kubernetes-scheduler, mounted at /root/reference) as a batched
assignment engine on TPU:

- the per-pod × per-node Filter/Score goroutine fan-out of the upstream
  scheduling framework (reference: pkg/yoda/scheduler.go:96-156) becomes one
  jitted JAX program over dense pod × node matrices;
- the Redis side-channel used to memoize per-cycle statistics
  (reference: pkg/yoda/cache/cache.go, pkg/yoda/score/algorithm.go:57-89)
  is eliminated — the whole score matrix is produced in a single device pass;
- the Prometheus utilization scrape (reference: pkg/yoda/advisor/advisor.go)
  is kept host-side and materialized as a dense node-utilization matrix;
- scoring policies (live and legacy: pkg/yoda/score/algorithm.go) are
  pluggable vmapped kernels; GPU-card ("SCV") predicates
  (pkg/yoda/filter/filter.go) become boolean mask tensors;
- the node axis is sharded across a `jax.sharding.Mesh` with XLA collectives
  over ICI — the framework's data/"sequence" parallelism.

Layout:
    ops/       pure-JAX kernels (score, feasibility, normalize, assign, stats)
    parallel/  mesh construction, shard_map engine, collectives
    models/    scoring policies: heuristic kernels + learned (flax) scorer
    host/      cluster state, snapshot builders, metrics advisor, queue, binder
    sim/       kwok-style synthetic cluster generators for benchmarks
    utils/     config, logging/tracing, padding helpers
"""

__version__ = "0.1.0"
