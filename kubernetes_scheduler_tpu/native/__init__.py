"""ctypes bindings for the native host runtime (native/libyoda_host.so).

The reference's host is compiled Go; ours keeps the host hot paths native
too: the scheduling queue, the scalar fallback cycle, and requested-matrix
aggregation run in C++ (native/*.cc), reached from Python without
pybind11 (not in this image) via ctypes over flat numpy buffers.

The library is built on demand with `make -C native` the first time it is
needed; `available()` reports whether a toolchain/library exists so every
caller can fall back to the pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("yoda_tpu.native")

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libyoda_host.so")
ABI_VERSION = 4

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _sources_newer_than_lib() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for name in os.listdir(_NATIVE_DIR):
        if name.endswith((".cc", ".h")):
            if os.path.getmtime(os.path.join(_NATIVE_DIR, name)) > lib_mtime:
                return True
    return False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            text=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, OSError) as e:
        out = getattr(e, "stderr", "") or str(e)
        log.warning("native build failed, using pure-Python host paths: %s", out)
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    i64 = ctypes.c_int64

    lib.yoda_host_abi_version.restype = ctypes.c_int32
    lib.yoda_queue_new.restype = ctypes.c_void_p
    lib.yoda_queue_new.argtypes = [ctypes.c_double, ctypes.c_double]
    lib.yoda_queue_free.argtypes = [ctypes.c_void_p]
    lib.yoda_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32]
    lib.yoda_queue_requeue_unschedulable.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32, ctypes.c_double,
    ]
    lib.yoda_queue_mark_scheduled.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.yoda_queue_mark_scheduled_batch.argtypes = [ctypes.c_void_p, u64p, i64]
    lib.yoda_queue_pop_window.restype = i64
    lib.yoda_queue_pop_window.argtypes = [ctypes.c_void_p, ctypes.c_double, u64p, i64]
    lib.yoda_queue_len.restype = i64
    lib.yoda_queue_len.argtypes = [ctypes.c_void_p]

    # Tensor pointers are declared c_void_p so callers can pass the raw
    # integer address (ndarray.ctypes.data): extracting the address is
    # ~2.5x cheaper than building a typed POINTER per call, and on tiny
    # cycles marshaling — not the C++ — is the entire cost.
    vp = ctypes.c_void_p
    lib.yoda_scalar_cycle.restype = i64
    lib.yoda_scalar_cycle.argtypes = [
        i64, i64, i64, vp, vp, vp, vp, vp, ctypes.c_int, vp,
    ]
    lib.yoda_scalar_cycle_buf.restype = i64
    lib.yoda_scalar_cycle_buf.argtypes = [
        i64, i64, i64, vp, vp, vp, vp, vp, vp, ctypes.c_int, vp,
    ]
    lib.yoda_aggregate_requested.argtypes = [i64, i64, i64, vp, vp, vp]
    lib.yoda_native_loop.restype = i64
    lib.yoda_native_loop.argtypes = [
        ctypes.c_void_p, i64, i64, i64, i64, i64, vp, vp, vp, vp, vp, vp,
        ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_double,
        vp, vp,
    ]
    return lib


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        # YODA_NATIVE_LIB: load an alternate build verbatim (the
        # sanitizer harness points here at build-asan/; no rebuild logic
        # — a stale override should fail its ABI check, not be silently
        # replaced by an unsanitized rebuild)
        override = os.environ.get("YODA_NATIVE_LIB")
        if override:
            try:
                lib = _bind(ctypes.CDLL(override))
            except OSError as e:
                log.warning("could not load YODA_NATIVE_LIB=%s: %s", override, e)
                _load_failed = True
                return None
            if lib.yoda_host_abi_version() != ABI_VERSION:
                log.warning(
                    "YODA_NATIVE_LIB=%s has ABI %d, expected %d",
                    override, lib.yoda_host_abi_version(), ABI_VERSION,
                )
                _load_failed = True
                return None
            _lib = lib
            return _lib
        if _sources_newer_than_lib() and not _build():
            _load_failed = True
            return None
        try:
            lib = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError as e:
            log.warning("could not load %s: %s", _LIB_PATH, e)
            _load_failed = True
            return None
        got = lib.yoda_host_abi_version()
        if got != ABI_VERSION:
            log.warning("native ABI %d != expected %d; rebuilding", got, ABI_VERSION)
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "clean"],
                capture_output=True,
                timeout=60,
            )
            if not _build():
                _load_failed = True
                return None
            lib = _bind(ctypes.CDLL(_LIB_PATH))
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _f32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _addr(a: np.ndarray) -> int:
    """Raw buffer address for c_void_p parameters (cheap marshaling)."""
    return a.ctypes.data


class NativeQueue:
    """Priority + backoff queue over opaque uint64 pod handles.

    Callers map handles to Pod objects (host/queue.py's NativeBackedQueue
    does this); `now` is injected for testable clocks.
    """

    def __init__(self, *, initial_backoff: float = 1.0, max_backoff: float = 10.0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._q = lib.yoda_queue_new(initial_backoff, max_backoff)

    def push(self, pod: int, priority: int) -> None:
        self._lib.yoda_queue_push(self._q, pod, priority)

    def requeue_unschedulable(self, pod: int, priority: int, now: float) -> None:
        self._lib.yoda_queue_requeue_unschedulable(self._q, pod, priority, now)

    def mark_scheduled(self, pod: int) -> None:
        self._lib.yoda_queue_mark_scheduled(self._q, pod)

    def mark_scheduled_batch(self, pods: np.ndarray) -> None:
        """One foreign call for a whole cycle's binds (uint64 handles)."""
        self._lib.yoda_queue_mark_scheduled_batch(
            self._q, _ptr(pods, ctypes.c_uint64), len(pods)
        )

    def pop_window(self, max_pods: int, now: float) -> np.ndarray:
        out = np.empty(max_pods, dtype=np.uint64)
        n = self._lib.yoda_queue_pop_window(
            self._q, now, _ptr(out, ctypes.c_uint64), max_pods
        )
        return out[:n]

    def __len__(self) -> int:
        return int(self._lib.yoda_queue_len(self._q))

    def __del__(self):
        q = getattr(self, "_q", None)
        if q:
            self._lib.yoda_queue_free(q)
            self._q = None


def scalar_cycle(
    pod_req,
    r_io,
    free_cap,
    disk_io,
    cpu_pct,
    *,
    truncate: bool = True,
) -> tuple[np.ndarray, np.ndarray, int]:
    """Run the native scalar fallback cycle.

    Returns (node_idx [P], free_after [N,R], n_bound). Inputs are any
    array-likes; row order of pod_req is the scheduling (priority) order.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    pod_req = _f32(pod_req)
    r_io = _f32(r_io)
    free = _f32(free_cap).copy()
    disk_io = _f32(disk_io)
    cpu_pct = _f32(cpu_pct)
    p, r = pod_req.shape
    n = free.shape[0]
    if free.shape != (n, r):
        raise ValueError(f"free_cap shape {free.shape} != ({n}, {r})")
    if r_io.shape != (p,) or disk_io.shape != (n,) or cpu_pct.shape != (n,):
        raise ValueError("inconsistent scalar_cycle input shapes")
    out = np.empty(p, dtype=np.int32)
    bound = lib.yoda_scalar_cycle(
        p, n, r,
        _addr(pod_req), _addr(r_io), _addr(free), _addr(disk_io),
        _addr(cpu_pct), int(truncate), _addr(out),
    )
    return out, free, int(bound)


class ScalarCycler:
    """Prebound scalar cycle for repeated same-shape cluster state.

    Binds every buffer address once; each `run()` is a single foreign
    call into yoda_scalar_cycle_buf with free capacity restored from the
    bound `free` buffer (the input is never mutated). For tiny cycles —
    the adaptive-dispatch scalar regime, e.g. the single-pod BASELINE.md
    config — this removes the per-call marshaling that otherwise costs
    ~10x the C++ cycle itself.

    Change state between runs with `update(...)` (copies into the bound
    buffers) or by writing the array attributes in place
    (``cyc.free[:] = new_free``). The attributes are read-only
    properties: the raw addresses are cached, so rebinding them must be
    impossible — a dropped buffer would leave C++ reading freed memory.
    A new shape means constructing a new cycler.
    """

    __slots__ = (
        "_lib", "_pod_req", "_r_io", "_free", "_disk_io", "_cpu_pct",
        "_free_after", "_node_idx", "_args",
    )

    def __init__(self, pod_req, r_io, free_cap, disk_io, cpu_pct, *,
                 truncate: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        # always copy: the cached addresses must point at buffers this
        # object owns, never at caller arrays whose lifetime we can't see
        self._pod_req = _f32(pod_req).copy()
        self._r_io = _f32(r_io).copy()
        self._free = _f32(free_cap).copy()
        self._disk_io = _f32(disk_io).copy()
        self._cpu_pct = _f32(cpu_pct).copy()
        p, r = self._pod_req.shape
        n = self._free.shape[0]
        if self._free.shape != (n, r):
            raise ValueError(
                f"free_cap shape {self._free.shape} != ({n}, {r})"
            )
        if (
            self._r_io.shape != (p,)
            or self._disk_io.shape != (n,)
            or self._cpu_pct.shape != (n,)
        ):
            raise ValueError("inconsistent ScalarCycler input shapes")
        self._free_after = np.empty_like(self._free)
        self._node_idx = np.empty(p, dtype=np.int32)
        self._args = (
            p, n, r, _addr(self._pod_req), _addr(self._r_io),
            _addr(self._free), _addr(self._free_after),
            _addr(self._disk_io), _addr(self._cpu_pct), int(truncate),
            _addr(self._node_idx),
        )

    pod_req = property(lambda self: self._pod_req)
    r_io = property(lambda self: self._r_io)
    free = property(lambda self: self._free)
    disk_io = property(lambda self: self._disk_io)
    cpu_pct = property(lambda self: self._cpu_pct)
    free_after = property(lambda self: self._free_after)
    node_idx = property(lambda self: self._node_idx)

    @property
    def shape(self) -> tuple[int, int, int]:
        """(pods, nodes, resources) this cycler is bound to."""
        return tuple(self._args[:3])

    def update(self, *, pod_req=None, r_io=None, free=None, disk_io=None,
               cpu_pct=None) -> None:
        """Copy new state into the bound buffers (shapes must match)."""
        for buf, val in (
            (self._pod_req, pod_req), (self._r_io, r_io),
            (self._free, free), (self._disk_io, disk_io),
            (self._cpu_pct, cpu_pct),
        ):
            if val is not None:
                buf[...] = val

    def run(self) -> int:
        """One cycle; results land in .node_idx / .free_after. Returns
        the number of pods bound."""
        return int(self._lib.yoda_scalar_cycle_buf(*self._args))


class NativeLoop:
    """The fully-native tiny-cycle host loop (native/loop.cc): queue pop
    -> scalar cycle -> bind/requeue, many cycles per foreign call.

    This is the single-pod-regime answer to the ctypes dispatch floor
    (PARITY.md): where ScalarCycler pays one foreign call PER cycle
    (~2us, ~20x the C++ work), this pays one per `run(n_cycles)` batch.
    Decisions are identical to driving the scalar cycle one popped
    window at a time from Python — pinned by tests/test_native.py.

    Pod handles are row indices into the bound [M, R] pod arrays; push
    them with `submit`. The clock is simulated: it starts at 0 and each
    cycle advances dt_per_cycle, so backoff requeues behave
    deterministically.
    """

    __slots__ = (
        "_lib", "_queue", "_pod_req", "_r_io", "_prio", "_free",
        "_disk_io", "_cpu_pct", "_node_idx", "_truncate", "_dt", "_now",
        "_window", "_reset_free",
    )

    def __init__(self, pod_req, r_io, prio, free_cap, disk_io, cpu_pct, *,
                 window: int = 1, truncate: bool = True,
                 initial_backoff: float = 1.0, max_backoff: float = 10.0,
                 dt_per_cycle: float = 1e-6, reset_free: bool = False):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._pod_req = _f32(pod_req).copy()
        self._r_io = _f32(r_io).copy()
        self._prio = np.ascontiguousarray(prio, dtype=np.int32).copy()
        self._free = _f32(free_cap).copy()
        self._disk_io = _f32(disk_io).copy()
        self._cpu_pct = _f32(cpu_pct).copy()
        m, r = self._pod_req.shape
        n = self._free.shape[0]
        if self._free.shape != (n, r):
            raise ValueError(f"free_cap shape {self._free.shape} != ({n}, {r})")
        if self._r_io.shape != (m,) or self._prio.shape != (m,):
            raise ValueError("inconsistent NativeLoop pod-side shapes")
        if self._disk_io.shape != (n,) or self._cpu_pct.shape != (n,):
            raise ValueError("inconsistent NativeLoop node-side shapes")
        self._node_idx = np.full(m, -1, dtype=np.int32)
        self._truncate = int(truncate)
        # reset_free: every cycle schedules against the original capacity
        # (steady-state cluster regime; see loop.cc)
        self._reset_free = int(reset_free)
        self._dt = float(dt_per_cycle)
        self._now = 0.0
        self._window = int(window)
        self._queue = lib.yoda_queue_new(initial_backoff, max_backoff)

    node_idx = property(lambda self: self._node_idx)
    free = property(lambda self: self._free)

    def __len__(self) -> int:
        return int(self._lib.yoda_queue_len(self._queue))

    def submit(self, handle: int) -> None:
        """Queue pod `handle` (a row of the bound pod arrays)."""
        self._lib.yoda_queue_push(
            self._queue, int(handle), int(self._prio[handle])
        )

    def submit_all(self) -> None:
        for h in range(self._pod_req.shape[0]):
            self.submit(h)

    def run(self, n_cycles: int) -> tuple[int, int]:
        """Run up to n_cycles cycles natively; returns (binds, cycles)."""
        out_cycles = ctypes.c_int64(0)
        bound = self._lib.yoda_native_loop(
            self._queue, int(n_cycles), self._window,
            self._pod_req.shape[0], self._free.shape[0],
            self._free.shape[1],
            _addr(self._pod_req), _addr(self._r_io), _addr(self._prio),
            _addr(self._free), _addr(self._disk_io), _addr(self._cpu_pct),
            self._truncate, self._reset_free, self._now, self._dt,
            _addr(self._node_idx), ctypes.addressof(out_cycles),
        )
        if bound < 0:
            raise RuntimeError("native loop: pod handle out of range")
        cycles = int(out_cycles.value)
        self._now += cycles * self._dt
        return int(bound), cycles

    def reset(self, free_cap=None) -> None:
        """Restore capacity (and clear decisions) for a fresh pass."""
        if free_cap is not None:
            self._free[...] = _f32(free_cap)
        self._node_idx[...] = -1
        self._now = 0.0

    def __del__(self):
        q = getattr(self, "_queue", None)
        if q:
            self._lib.yoda_queue_free(q)
            self._queue = None


def aggregate_requested(pod_node, pod_req, n_nodes: int) -> np.ndarray:
    """Sum running-pod requests into a fresh [n_nodes, R] matrix."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    pod_node = np.ascontiguousarray(pod_node, dtype=np.int32)
    pod_req = _f32(pod_req)
    m, r = pod_req.shape
    if pod_node.shape != (m,):
        raise ValueError("pod_node/pod_req length mismatch")
    out = np.zeros((n_nodes, r), dtype=np.float32)
    lib.yoda_aggregate_requested(
        m, n_nodes, r, _addr(pod_node), _addr(pod_req), _addr(out)
    )
    return out
