"""graftlint CLI: `python -m kubernetes_scheduler_tpu.analysis`.

Exits non-zero on any unwaived violation; `make lint` wires this into
the build. Waived sites are listed (with their justifications) under
--verbose so the allow-list stays reviewable.
"""

from __future__ import annotations

import argparse
import json
import sys

from kubernetes_scheduler_tpu.analysis.core import run_lint
from kubernetes_scheduler_tpu.analysis.rules import RULES


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_scheduler_tpu.analysis",
        description="repo-native static analysis (graftlint)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files to lint (default: the whole package)",
    )
    parser.add_argument(
        "--rules",
        help=f"comma-separated rule subset of: {', '.join(sorted(RULES))}",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    parser.add_argument(
        "--verbose", action="store_true",
        help="also list waived violations with their justifications",
    )
    args = parser.parse_args(argv)

    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    try:
        violations = run_lint(args.paths or None, rules=rules)
    except ValueError as e:
        parser.error(str(e))
    active = [v for v in violations if not v.waived]
    waived = [v for v in violations if v.waived]

    if args.format == "json":
        print(json.dumps([v.__dict__ for v in violations], indent=2))
    else:
        for v in active:
            print(v.format())
        if args.verbose:
            for v in waived:
                print(v.format())
        print(
            f"graftlint: {len(active)} violation(s), "
            f"{len(waived)} waived",
            file=sys.stderr,
        )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
