"""graftlint CLI: `python -m kubernetes_scheduler_tpu.analysis`.

Exits non-zero on any unwaived violation; `make lint` wires this into
the build. Beyond the sixteen AST families, a full-repo run also
traces the engine-contract layer (analysis/contracts.py, jax.eval_shape
on CPU — the mesh-sharded surfaces through shard_map on the virtual
multi-device topology, the COLLECTIVE_BUDGET.json gate, and the
seeded SPMD mutant harness ride along) unless --no-contracts, and the
protocol-model layer
(analysis/model/: bounded model checking of the session/epoch/
capability protocol, anchor drift, mutation harness) unless
--no-models; machine output: `--format json|sarif`
(SARIF 2.1.0 — validated structurally before printing, so a malformed
artifact fails lint, not the CI uploader), `--json-artifact PATH` to
drop the findings JSON beside any display format, `--baseline` for the
checked-in suppression file (stale or unexplained entries fail lint),
and `--budget-seconds` asserting the whole run's wall time — the
parse-once index keeps full-repo lint inside it. Waived sites are
listed (with their justifications) under --verbose so the allow-list
stays reviewable.

`--changed-only REF` is the fast pre-commit loop: the AST families
still parse the whole package (the interprocedural core needs every
edge), but findings are scoped to the files changed vs REF plus their
reverse-dependency closure from the shared call graph, and the two
whole-program layers (contracts, protocol models) run only when a file
on their surface is in that closure. Changed-only findings are a
subset of the full run's by construction (pinned in
tests/test_analysis.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from kubernetes_scheduler_tpu.analysis.core import (
    BASELINE_NAME,
    _REPO_ROOT,
    apply_baseline,
    load_baseline,
    run_lint,
)
from kubernetes_scheduler_tpu.analysis.rules import RULES


def _rule_docs() -> dict:
    """rule id -> first docstring line of its module (SARIF metadata)."""
    import importlib

    docs = {}
    for name, fn in RULES.items():
        mod = importlib.import_module(fn.__module__)
        head = (mod.__doc__ or name).strip().splitlines()[0]
        docs[name] = head
    return docs


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_scheduler_tpu.analysis",
        description="repo-native static analysis (graftlint)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files to lint (default: the whole package)",
    )
    parser.add_argument(
        "--rules",
        help=f"comma-separated rule subset of: {', '.join(sorted(RULES))}",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
    )
    parser.add_argument(
        "--json-artifact", metavar="PATH",
        help="also write the findings JSON to PATH (CI artifact)",
    )
    parser.add_argument(
        "--baseline", metavar="PATH", default=None,
        help=f"suppression file (default: {BASELINE_NAME} at the repo "
             "root when present); --no-baseline disables",
    )
    parser.add_argument("--no-baseline", action="store_true")
    parser.add_argument(
        "--contracts", action="store_true",
        help="run the engine-contract layer even for a path-scoped lint",
    )
    parser.add_argument(
        "--no-contracts", action="store_true",
        help="skip the engine-contract layer on a full-repo lint",
    )
    parser.add_argument(
        "--models", action="store_true",
        help="run the protocol-model layer even for a scoped lint",
    )
    parser.add_argument(
        "--no-models", action="store_true",
        help="skip the protocol-model layer on a full-repo lint",
    )
    parser.add_argument(
        "--model-budget-seconds", type=float, default=60.0,
        help="wall budget for the protocol-model layer (models + "
             "anchors + mutation harness); an un-exhausted model is a "
             "violation, never a silent skip",
    )
    parser.add_argument(
        "--changed-only", metavar="REF",
        help="scope findings to files changed vs the git REF plus "
             "their reverse-dependency closure (fast pre-commit loop); "
             "whole-program layers run only when their surface is in "
             "the closure",
    )
    parser.add_argument(
        "--budget-seconds", type=float, default=None,
        help="fail if the whole run exceeds this wall time",
    )
    parser.add_argument(
        "--verbose", action="store_true",
        help="also list waived violations with their justifications",
    )
    args = parser.parse_args(argv)
    t0 = time.monotonic()

    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    if args.changed_only and args.paths:
        parser.error("--changed-only and explicit paths are exclusive")
    ctx_sink: list = []
    try:
        violations = run_lint(args.paths or None, rules=rules,
                              ctx_out=ctx_sink)
    except ValueError as e:
        parser.error(str(e))

    # --changed-only: the families parsed (and analyzed) the whole
    # package — the interprocedural core needs every edge — but the
    # findings reported are those in the changed files' reverse-
    # dependency closure. Whole-program layers below key off the same
    # closure. Subset-of-full-run by construction.
    scope = None
    if args.changed_only:
        from kubernetes_scheduler_tpu.analysis.core import (
            changed_vs_ref,
            reverse_dependency_closure,
        )

        try:
            changed = changed_vs_ref(_REPO_ROOT, args.changed_only)
        except ValueError as e:
            parser.error(str(e))
        scope = reverse_dependency_closure(ctx_sink[0], changed)
        violations = [v for v in violations if v.path in scope]

    def _surface_hit(patterns) -> bool:
        import fnmatch

        if scope is None:
            return False
        return any(
            fnmatch.fnmatch(p, pat) for p in scope for pat in patterns
        )

    # layer 2: engine contracts — on by default for the full-repo run
    # `make lint` does, opt-in for scoped runs (tracing needs jax); a
    # changed-only run traces them only when the closure touches the
    # engine/ops surface
    full_repo = not args.paths and rules is None and not args.changed_only
    run_contracts = args.contracts or (full_repo and not args.no_contracts)
    if args.changed_only and not args.no_contracts:
        from kubernetes_scheduler_tpu.analysis.contracts import SURFACE

        run_contracts = run_contracts or _surface_hit(SURFACE)
    if run_contracts:
        from kubernetes_scheduler_tpu.analysis.contracts import (
            check_contracts,
            check_sharded_contracts,
        )

        violations.extend(check_contracts())
        # the sharded half: eval_shape through shard_map on the virtual
        # CPU mesh (sharded==dense spec pin, divisibility formula, the
        # COLLECTIVE_BUDGET.json gate) plus the seeded SPMD mutant
        # harness — an analyzer that stops catching a bug class is
        # itself a lint violation, like the protocol-model mutants
        violations.extend(check_sharded_contracts())
        from kubernetes_scheduler_tpu.analysis.spmd_mutants import (
            check_spmd_mutants,
        )

        violations.extend(check_spmd_mutants())

    # layer 2b: the thread/determinism mutant harness — pure AST, no
    # tracing, so it rides every full-repo run; changed-only runs re-arm
    # it when the closure touches the threaded layers or the analyzer
    run_thread_mutants = full_repo
    if args.changed_only:
        from kubernetes_scheduler_tpu.analysis.thread_mutants import (
            SURFACE as THREAD_SURFACE,
        )

        run_thread_mutants = run_thread_mutants or _surface_hit(
            THREAD_SURFACE
        )
    if run_thread_mutants:
        from kubernetes_scheduler_tpu.analysis.thread_mutants import (
            check_thread_mutants,
        )

        violations.extend(check_thread_mutants())

    # layer 3: protocol models (analysis/model/) — bounded model
    # checking of the session/epoch/capability protocol, transition
    # anchor drift, and the mutation harness, reported as pseudo-rule
    # `protocol-model`; same full-repo default / surface-keyed
    # changed-only behavior as the contracts layer
    run_models = args.models or (full_repo and not args.no_models)
    if args.changed_only and not args.no_models:
        from kubernetes_scheduler_tpu.analysis.model.runner import (
            SURFACE as MODEL_SURFACE,
        )

        run_models = run_models or _surface_hit(MODEL_SURFACE)
    if run_models:
        from kubernetes_scheduler_tpu.analysis.model.runner import (
            check_protocol_layer,
        )

        violations.extend(
            check_protocol_layer(
                # a path-scoped ctx would miss anchor targets: let the
                # layer build its own full-package index in that case
                ctx_sink[0] if (ctx_sink and not args.paths) else None,
                budget_seconds=args.model_budget_seconds,
            )
        )

    baseline = args.baseline
    if baseline is None and not args.no_baseline:
        default = os.path.join(_REPO_ROOT, BASELINE_NAME)
        baseline = default if os.path.exists(default) else None
    if baseline and not args.no_baseline:
        try:
            entries = load_baseline(baseline)
        except (OSError, ValueError) as e:
            parser.error(f"--baseline {baseline}: {e}")
        # scoped runs can't distinguish out-of-scope from stale — only
        # the full-repo run polices baseline liveness
        violations.extend(
            apply_baseline(
                violations, entries, baseline, check_stale=full_repo
            )
        )

    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    active = [v for v in violations if not v.waived]
    waived = [v for v in violations if v.waived]

    if args.json_artifact:
        with open(args.json_artifact, "w", encoding="utf-8") as f:
            json.dump([v.__dict__ for v in violations], f, indent=2)

    if args.format == "json":
        print(json.dumps([v.__dict__ for v in violations], indent=2))
    elif args.format == "sarif":
        from kubernetes_scheduler_tpu.analysis.sarif import (
            render_sarif,
            validate_sarif,
        )

        doc = render_sarif(violations, _rule_docs())
        validate_sarif(doc)
        print(json.dumps(doc, indent=2))
    else:
        for v in active:
            print(v.format())
        if args.verbose:
            for v in waived:
                print(v.format())
        print(
            f"graftlint: {len(active)} violation(s), "
            f"{len(waived)} waived",
            file=sys.stderr,
        )
    elapsed = time.monotonic() - t0
    if args.budget_seconds is not None and elapsed > args.budget_seconds:
        print(
            f"graftlint: wall time {elapsed:.1f}s exceeded the "
            f"--budget-seconds {args.budget_seconds:.1f}s gate",
            file=sys.stderr,
        )
        return 1
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
