"""graftlint CLI: `python -m kubernetes_scheduler_tpu.analysis`.

Exits non-zero on any unwaived violation; `make lint` wires this into
the build. Beyond the fourteen AST families, a full-repo run also
traces the engine-contract layer (analysis/contracts.py, jax.eval_shape
on CPU) unless --no-contracts; machine output: `--format json|sarif`
(SARIF 2.1.0 — validated structurally before printing, so a malformed
artifact fails lint, not the CI uploader), `--json-artifact PATH` to
drop the findings JSON beside any display format, `--baseline` for the
checked-in suppression file (stale or unexplained entries fail lint),
and `--budget-seconds` asserting the whole run's wall time — the
parse-once index keeps full-repo lint inside it. Waived sites are
listed (with their justifications) under --verbose so the allow-list
stays reviewable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from kubernetes_scheduler_tpu.analysis.core import (
    BASELINE_NAME,
    _REPO_ROOT,
    apply_baseline,
    load_baseline,
    run_lint,
)
from kubernetes_scheduler_tpu.analysis.rules import RULES


def _rule_docs() -> dict:
    """rule id -> first docstring line of its module (SARIF metadata)."""
    import importlib

    docs = {}
    for name, fn in RULES.items():
        mod = importlib.import_module(fn.__module__)
        head = (mod.__doc__ or name).strip().splitlines()[0]
        docs[name] = head
    return docs


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_scheduler_tpu.analysis",
        description="repo-native static analysis (graftlint)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files to lint (default: the whole package)",
    )
    parser.add_argument(
        "--rules",
        help=f"comma-separated rule subset of: {', '.join(sorted(RULES))}",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
    )
    parser.add_argument(
        "--json-artifact", metavar="PATH",
        help="also write the findings JSON to PATH (CI artifact)",
    )
    parser.add_argument(
        "--baseline", metavar="PATH", default=None,
        help=f"suppression file (default: {BASELINE_NAME} at the repo "
             "root when present); --no-baseline disables",
    )
    parser.add_argument("--no-baseline", action="store_true")
    parser.add_argument(
        "--contracts", action="store_true",
        help="run the engine-contract layer even for a path-scoped lint",
    )
    parser.add_argument(
        "--no-contracts", action="store_true",
        help="skip the engine-contract layer on a full-repo lint",
    )
    parser.add_argument(
        "--budget-seconds", type=float, default=None,
        help="fail if the whole run exceeds this wall time",
    )
    parser.add_argument(
        "--verbose", action="store_true",
        help="also list waived violations with their justifications",
    )
    args = parser.parse_args(argv)
    t0 = time.monotonic()

    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    try:
        violations = run_lint(args.paths or None, rules=rules)
    except ValueError as e:
        parser.error(str(e))

    # layer 2: engine contracts — on by default for the full-repo run
    # `make lint` does, opt-in for scoped runs (tracing needs jax)
    full_repo = not args.paths and rules is None
    if args.contracts or (full_repo and not args.no_contracts):
        from kubernetes_scheduler_tpu.analysis.contracts import (
            check_contracts,
        )

        violations.extend(check_contracts())

    baseline = args.baseline
    if baseline is None and not args.no_baseline:
        default = os.path.join(_REPO_ROOT, BASELINE_NAME)
        baseline = default if os.path.exists(default) else None
    if baseline and not args.no_baseline:
        try:
            entries = load_baseline(baseline)
        except (OSError, ValueError) as e:
            parser.error(f"--baseline {baseline}: {e}")
        # scoped runs can't distinguish out-of-scope from stale — only
        # the full-repo run polices baseline liveness
        violations.extend(
            apply_baseline(
                violations, entries, baseline, check_stale=full_repo
            )
        )

    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    active = [v for v in violations if not v.waived]
    waived = [v for v in violations if v.waived]

    if args.json_artifact:
        with open(args.json_artifact, "w", encoding="utf-8") as f:
            json.dump([v.__dict__ for v in violations], f, indent=2)

    if args.format == "json":
        print(json.dumps([v.__dict__ for v in violations], indent=2))
    elif args.format == "sarif":
        from kubernetes_scheduler_tpu.analysis.sarif import (
            render_sarif,
            validate_sarif,
        )

        doc = render_sarif(violations, _rule_docs())
        validate_sarif(doc)
        print(json.dumps(doc, indent=2))
    else:
        for v in active:
            print(v.format())
        if args.verbose:
            for v in waived:
                print(v.format())
        print(
            f"graftlint: {len(active)} violation(s), "
            f"{len(waived)} waived",
            file=sys.stderr,
        )
    elapsed = time.monotonic() - t0
    if args.budget_seconds is not None and elapsed > args.budget_seconds:
        print(
            f"graftlint: wall time {elapsed:.1f}s exceeded the "
            f"--budget-seconds {args.budget_seconds:.1f}s gate",
            file=sys.stderr,
        )
        return 1
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
