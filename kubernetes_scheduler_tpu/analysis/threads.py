"""The declared thread model + happens-before substrate (families 17-18).

Every concurrency guarantee the repo ships rests on assumptions the
lockset family alone cannot see: WHICH code runs on which thread, and
which cross-thread orderings (Event publication, queue hand-off,
thread start/join) make a lock-free access safe. This module makes
both explicit:

- `THREAD_ROOTS` is the registry of real thread entry points — the
  host serving loop, the pipelined in-flight completion stage, the
  BackgroundAdvisor refresh thread, the informer watch threads, the
  pending-pod feeder, the CycleTrigger waiter, the metrics HTTP
  handlers, the bridge RPC workers, the leader elector — each bound to
  code PR-10 style (`Anchor`-shaped fragments + call edges verified
  against the live ModuleIndex, so a refactor that moves a loop out
  from under its declared root fails lint instead of silently
  un-modeling a thread).

- `build_model(index)` resolves the registry against the index, ADDS
  every discovered spawn site (`threading.Thread(target=...)`,
  `threading.Thread` subclasses — so fixtures and scratch mutants are
  analyzable with no registry entry), and computes, per function, the
  set of thread identities that can reach it over a dispatch-extended
  call graph (attribute-typed `self.x.m()` calls resolved through
  constructor assignments; spawn edges deliberately excluded — a
  `Thread(target=f)` transfers control to a NEW thread, not this one).

- `class_concurrency(index, sf, cls)` collects every self-attribute
  access (reads AND writes, with the lexical lockset held at the
  site), plus the per-method happens-before facts the race family
  discharges pairs with: `Event.set`/`Event.wait` lines, `.start()` /
  `.join()` lines, and the set of thread-safe attributes (locks,
  Events, Queues, the repo's internally-locked Counter/Histogram/
  Gauge) whose method calls are hand-off edges rather than shared
  mutable state.

The model is an over-approximation with under-approximated reach
(RacerD-style): a function is only attributed to a thread the analysis
can PROVE reaches it, so missing dispatch edges cost findings, never
false ones.
"""

from __future__ import annotations

import ast
import builtins
from dataclasses import dataclass, field

from kubernetes_scheduler_tpu.analysis.core import Violation, dotted_name
from kubernetes_scheduler_tpu.analysis.dataflow import (
    class_lock_facts,
    method_entry_locksets,
    shallow_walk,
    _MUTATORS,
)

RULE = "thread-race"

# the serving thread's identity: declared host-loop roots and every
# discovered spawn-SITE (the code around a `t.start()` runs on the
# spawner's thread, which for this repo is always the serving loop or
# the harness driving it) share it, so setup-vs-cycle "pairs" on the
# same real thread can never fire
MAIN = "main"


# ---- the declared registry -------------------------------------------------


@dataclass(frozen=True)
class ThreadRoot:
    """One declared thread entry point, bound to code.

    name:         registry key (README's thread-root inventory table)
    thread:       identity; accesses from roots SHARING an identity run
                  on the same real thread and never race each other
    path:         repo-relative file
    func:         dotted def within the file ("Cls.method" / "fn")
    concurrent:   True when many instances of this thread run at once
                  (HTTP handler pool, gRPC workers) — a single write
                  site then conflicts with itself
    must_contain: source fragments that must appear in the resolved def
    calls:        bare callee names the def must reach (call graph)
    reaches:      extra entry qname tails ("Cls.method") the root is
                  DECLARED to reach — the modeling seam for dispatch
                  the static resolver cannot see (callbacks, bound
                  methods passed as values)
    description:  one line for the README inventory
    """

    name: str
    thread: str
    path: str
    func: str
    concurrent: bool = False
    must_contain: tuple = ()
    calls: tuple = ()
    reaches: tuple = ()
    description: str = ""


_PKG = "kubernetes_scheduler_tpu"

THREAD_ROOTS: tuple[ThreadRoot, ...] = (
    ThreadRoot(
        name="host-loop",
        thread=MAIN,
        path=f"{_PKG}/kube/source.py",
        func="run_kube_loop",
        must_contain=("feeder.start()", "sched.run_cycle()"),
        description="the serving loop: feeder-fed cycles on the main thread",
    ),
    ThreadRoot(
        name="host-cycle",
        thread=MAIN,
        path=f"{_PKG}/host/scheduler.py",
        func="Scheduler.run_cycle",
        must_contain=("_run_cycle_pipelined", "_run_cycle_serial"),
        description="one scheduling cycle (serial or pipelined driver)",
    ),
    ThreadRoot(
        name="pipelined-completion",
        thread=MAIN,
        path=f"{_PKG}/host/scheduler.py",
        func="Scheduler._complete_cycle_split",
        must_contain=("self._observe_dispatch",),
        calls=("_observe_dispatch",),
        description=(
            "in-flight completion stage — the force half of the "
            "run_cycle_split seam, resolved ON the thread that calls "
            "complete() (the host loop, or a fleet drain completing "
            "replicas in order), not a thread of its own"
        ),
    ),
    ThreadRoot(
        name="cycle-trigger-waiter",
        thread=MAIN,
        path=f"{_PKG}/host/mirror.py",
        func="CycleTrigger.wait",
        must_contain=("self._evt.wait(timeout)", "self._evt.clear()"),
        description=(
            "event-driven idle wait; producers notify() from their own "
            "threads (set-then-clear-after-wait, no lost wakeups)"
        ),
    ),
    ThreadRoot(
        name="advisor-refresh",
        thread="advisor-refresh",
        path=f"{_PKG}/host/advisor.py",
        func="BackgroundAdvisor._run",
        must_contain=("self._refresh_once()", "self._stop.wait"),
        calls=("_refresh_once",),
        description="background utilization scrape loop",
    ),
    ThreadRoot(
        name="informer-watch",
        thread="informer-watch",
        path=f"{_PKG}/kube/source.py",
        func="InformerCache._resource_loop",
        concurrent=True,
        must_contain=("self._stop.is_set()", "self.client.watch"),
        reaches=(
            "SnapshotMirror.seed",
            "SnapshotMirror.apply_node_event",
            "SnapshotMirror.apply_pod_event",
        ),
        description=(
            "per-resource list+watch loops (nodes, pods, PDBs, "
            "namespaces, controllers, storage) — one thread each, all "
            "funneling through the cache lock; attach_mirror's on_event "
            "feeds the snapshot mirror from these threads"
        ),
    ),
    ThreadRoot(
        name="pending-feeder",
        thread="pending-feeder",
        path=f"{_PKG}/kube/source.py",
        func="_Feeder.run",
        must_contain=("watch_pending_events", "self._submit_new"),
        reaches=("Scheduler.submit", "CycleTrigger.notify"),
        description=(
            "pending-pod watcher feeding Scheduler.submit / the "
            "scheduling queue on arrival"
        ),
    ),
    ThreadRoot(
        name="metrics-http",
        thread="metrics-http",
        path=f"{_PKG}/host/observe.py",
        func="MetricsExporter._render_scheduler",
        concurrent=True,
        must_contain=("metrics_snapshot", "prom_collectors"),
        reaches=("Scheduler.metrics_snapshot", "Scheduler.arm_profile"),
        description=(
            "/metrics /healthz /debug/profile handlers (ThreadingHTTP"
            "Server: one thread per request)"
        ),
    ),
    ThreadRoot(
        name="bridge-worker",
        thread="bridge-worker",
        path=f"{_PKG}/bridge/server.py",
        func="EngineService.schedule_batch",
        concurrent=True,
        must_contain=("self._device_lock",),
        calls=("_resident_snapshot", "_finish_call"),
        description=(
            "sidecar RPC pool (schedule_batch/schedule_windows/preempt/"
            "health on a ThreadPoolExecutor); the device section is "
            "serialized by _device_lock"
        ),
    ),
    ThreadRoot(
        name="bridge-worker-windows",
        thread="bridge-worker",
        path=f"{_PKG}/bridge/server.py",
        func="EngineService.schedule_windows",
        concurrent=True,
        must_contain=("self._device_lock",),
        description="windows RPC on the same worker pool",
    ),
    ThreadRoot(
        name="bridge-worker-health",
        thread="bridge-worker",
        path=f"{_PKG}/bridge/server.py",
        func="EngineService.health",
        concurrent=True,
        description="health probe RPC on the same worker pool",
    ),
    ThreadRoot(
        name="leader-elector",
        thread="leader-elector",
        path=f"{_PKG}/host/leader.py",
        func="LeaderElector._run_loop",
        must_contain=("self._try_acquire_safe()", "time.monotonic()"),
        description="lease renew/re-acquire loop gating the serving loop",
    ),
)


def _def_source(fi) -> str:
    """ast.unparse of the def with docstrings stripped (anchors.py
    semantics — fragments match executable code, never prose)."""
    import copy

    node = copy.deepcopy(fi.node)
    for n in ast.walk(node):
        body = getattr(n, "body", None)
        if (
            isinstance(body, list) and body
            and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)
        ):
            n.body = body[1:] or [ast.Pass()]
    return ast.unparse(node)


def _resolve_root(index, root: ThreadRoot):
    qname = f"{root.path}::{root.func}"
    fi = index.funcs.get(qname)
    if fi is not None:
        return fi
    tail = "." + root.func
    cands = [
        f for q, f in index.funcs.items()
        if q.startswith(root.path + "::") and q.endswith(tail)
    ]
    return cands[0] if len(cands) == 1 else None


def verify_thread_roots(index, roots=THREAD_ROOTS) -> list[Violation]:
    """Anchor-drift check: every declared root whose file is in the
    index must still resolve, contain its fragments, and keep its call
    edges. Roots whose file is not in the lint scope are skipped — a
    fixture-only run cannot (and need not) verify the live registry."""
    out: list[Violation] = []
    paths = {f.sf.path for f in index.funcs.values()}
    for root in roots:
        if root.path not in paths:
            continue
        fi = _resolve_root(index, root)
        if fi is None:
            out.append(Violation(
                RULE, root.path, 1,
                f"declared thread root `{root.name}` is anchored to "
                f"`{root.func}`, which no longer exists in this file — "
                "the thread model (analysis/threads.THREAD_ROOTS) no "
                "longer matches the code; re-anchor the root or restore "
                "the entry point",
            ))
            continue
        src = _def_source(fi)
        line = fi.node.lineno
        for frag in root.must_contain:
            if frag not in src:
                out.append(Violation(
                    RULE, root.path, line,
                    f"thread root `{root.name}`: `{root.func}` no longer "
                    f"contains `{frag}` — the code moved out from under "
                    "the declared thread model; re-derive the root "
                    "(analysis/threads.THREAD_ROOTS) against the new "
                    "code",
                ))
        if root.calls:
            callee_names = {
                q.rsplit("::", 1)[-1].rsplit(".", 1)[-1]
                for q in index.callees(fi.qname)
            }
            for want in root.calls:
                if want not in callee_names and f"{want}(" not in src:
                    out.append(Violation(
                        RULE, root.path, line,
                        f"thread root `{root.name}`: `{root.func}` no "
                        f"longer calls `{want}` — the root's reach is "
                        "modeled on that edge; update THREAD_ROOTS or "
                        "the code",
                    ))
        for tail in root.reaches:
            if _tail_exists(index, tail) is False:
                out.append(Violation(
                    RULE, root.path, line,
                    f"thread root `{root.name}` declares a dispatch "
                    f"edge to `{tail}`, which no longer resolves "
                    "anywhere in the tree — the declared reach is the "
                    "seam static resolution cannot see, so a stale one "
                    "silently drops those accesses from the model; "
                    "update THREAD_ROOTS",
                ))
    return out


def _tail_exists(index, tail: str) -> bool | None:
    """True when the declared tail resolves, False when its owner is in
    the index but the def is gone (drift), None when the owner is not
    loaded at all — a scoped run cannot verify cross-file reaches (the
    full `make lint` run does)."""
    suffix = "::" + tail if "." not in tail else "." + tail
    if any(
        q.endswith(suffix) or q.rsplit("::", 1)[-1] == tail
        for q in index.funcs
    ):
        return True
    if "." in tail:
        cls_name = tail.rsplit(".", 1)[0]
        owner_loaded = any(
            fi.cls is not None and fi.cls.name == cls_name
            for fi in index.funcs.values()
        )
        return False if owner_loaded else None
    return None


# ---- spawn-site discovery --------------------------------------------------

_THREAD_CTORS = {"Thread", "threading.Thread"}
_THREAD_BASES = {"Thread", "threading.Thread"}


def _is_thread_ctor(call: ast.Call) -> bool:
    dn = dotted_name(call.func)
    return dn in _THREAD_CTORS


def _spawn_targets(fi, call: ast.Call) -> list[str]:
    """Qnames a `threading.Thread(target=X)` ctor hands control to.

    Resolves `self._m` (enclosing class), bare same-file names, and the
    informer idiom — `target` loaded from a local list of bound methods
    (`loops = [self._node_loop, ...]; for target in loops: Thread(...)`).
    """
    target = None
    for kw in call.keywords:
        if kw.arg == "target":
            target = kw.value
    if target is None and call.args:
        target = call.args[0]
    if target is None:
        return []
    out: list[str] = []

    def _method_qname(attr: str) -> str | None:
        if fi.cls is None:
            return None
        q = fi.qname.rsplit(".", 1)[0] + "." + attr
        return q

    dn = dotted_name(target)
    if dn is not None:
        parts = dn.split(".")
        if parts[0] == "self" and len(parts) == 2:
            q = _method_qname(parts[1])
            if q is not None:
                out.append(q)
        elif len(parts) == 1:
            # bare name: a same-file def, or a local bound to a list of
            # bound methods (the informer start() loop)
            q = f"{fi.sf.path}::{parts[0]}"
            if q not in out:
                out.append(q)
            for node in shallow_walk(fi.node):
                if not isinstance(node, (ast.Assign, ast.AugAssign)):
                    continue
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                if not any(
                    isinstance(t, ast.Name) and t.id == parts[0]
                    for t in targets
                ):
                    continue
                for elt in ast.walk(node.value):
                    edn = dotted_name(elt)
                    if edn and edn.startswith("self.") and edn.count(".") == 1:
                        q = _method_qname(edn.split(".", 1)[1])
                        if q is not None and q not in out:
                            out.append(q)
    return out


# ---- the dispatch-extended reachability graph ------------------------------

# attributes holding these constructions are synchronization objects or
# internally-locked hand-off structures: method calls on them are HB
# edges (Queue.put/get, Event.set/wait) or thread-safe feeds
# (Counter.inc under its own lock), not shared mutable state. Rebinding
# the attribute itself outside __init__ still counts as a write.
SAFE_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "Event", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "deque", "Counter", "Histogram", "Gauge",
    "CycleTrigger", "local",
    # repo classes that serialize internally (their own threading.Lock
    # around every mutation) — calls on them are thread-safe feeds
    "SpanWriter",
}


def _ctor_name(value: ast.AST) -> str | None:
    if isinstance(value, ast.Call):
        dn = dotted_name(value.func)
        if dn:
            return dn.rsplit(".", 1)[-1]
    return None


def _class_key(sf, cls: ast.ClassDef) -> str:
    return f"{sf.path}::{cls.name}"


class ThreadModel:
    """threads_of: qname -> set of thread identities proven to reach it;
    concurrent: identities with >1 simultaneous instance; roots: the
    resolved (declared + discovered) entry list for rendering."""

    def __init__(self):
        self.threads_of: dict[str, set[str]] = {}
        self.concurrent: set[str] = set()
        self.roots: list[tuple[str, str, str]] = []  # (identity, name, qname)

    def threads(self, qname: str) -> frozenset:
        return frozenset(self.threads_of.get(qname, ()))


def _attr_types(index) -> dict[tuple[str, str], set[str]]:
    """(class key, attr) -> class keys the attr may hold, read off
    `self.a = ClassName(...)` ctor assignments (imports/same-file
    resolved loosely by class name) and one level of return-ctor
    inference through project factory functions."""
    out: dict[tuple[str, str], set[str]] = {}

    def _classes_for(name: str) -> list[str]:
        return [
            _class_key(sf, cls) for sf, cls in index.classes.get(name, ())
        ]

    def _returned_classes(fname: str) -> list[str]:
        keys: list[str] = []
        for cand in index.by_name.get(fname, ()):
            for node in shallow_walk(cand.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    cn = _ctor_name(node.value)
                    if cn:
                        keys.extend(_classes_for(cn))
        return keys

    for fi in index.funcs.values():
        if fi.cls is None:
            continue
        owner = _class_key(fi.sf, fi.cls)
        for node in shallow_walk(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            cn = _ctor_name(node.value)
            if not cn:
                continue
            keys = _classes_for(cn) or _returned_classes(cn)
            if not keys:
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.setdefault((owner, t.attr), set()).update(keys)
    return out


_LOOSE_CAP = 3  # an unresolved bare call lands on ≤ this many same-named
# defs project-wide, or the edge is dropped — thread attribution must
# never ride a name like `close` that forty classes define

_BUILTINS = frozenset(dir(builtins))  # set()/id() are never project calls


def thread_edges(index) -> dict[str, set[str]]:
    """The reachability graph thread identities propagate over: tight
    resolution (self.m / imports / same-file) + attribute-typed
    dispatch (`self.x.m()` through ctor assignments, local `x = Cls()`
    included) + a capped loose fallback — with `Thread(target=...)`
    spawn edges EXCLUDED (control moves to a new thread there; the
    spawned side enters the model as its own root)."""
    attr_types = _attr_types(index)
    method_index: dict[tuple[str, str], str] = {}
    for q, fi in index.funcs.items():
        if fi.cls is not None:
            cls_key = q.rsplit(".", 1)[0]
            method_index[(cls_key, fi.name)] = q

    edges: dict[str, set[str]] = {q: set() for q in index.funcs}
    for q, fi in index.funcs.items():
        owner = _class_key(fi.sf, fi.cls) if fi.cls is not None else None
        local_types: dict[str, set[str]] = {}
        for node in shallow_walk(fi.node):
            if isinstance(node, ast.Assign):
                cn = _ctor_name(node.value)
                if cn and cn in index.classes:
                    keys = {
                        _class_key(sf, cls)
                        for sf, cls in index.classes[cn]
                    }
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_types.setdefault(t.id, set()).update(keys)
            if not isinstance(node, ast.Call):
                continue
            if _is_thread_ctor(node):
                continue  # spawn, not a call edge on this thread
            cands = index.resolve_call(fi, node, loose=False)
            if cands:
                edges[q].update(c.qname for c in cands)
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            parts = dn.split(".")
            hit = False
            if len(parts) == 3 and parts[0] == "self" and owner is not None:
                for cls_key in attr_types.get((owner, parts[1]), ()):
                    callee = method_index.get((cls_key, parts[2]))
                    if callee is not None:
                        edges[q].add(callee)
                        hit = True
            elif len(parts) == 2 and parts[0] in local_types:
                for cls_key in local_types[parts[0]]:
                    callee = method_index.get((cls_key, parts[1]))
                    if callee is not None:
                        edges[q].add(callee)
                        hit = True
            if not hit and len(parts) == 1 and parts[0] not in _BUILTINS:
                # bare project calls only: a dotted `obj.append(...)` on
                # an untyped receiver must NOT land on some class's
                # `append` — thread attribution never rides a method
                # name forty receivers share
                loose = index.by_name.get(parts[0], ())
                if 0 < len(loose) <= _LOOSE_CAP:
                    edges[q].update(c.qname for c in loose)
    return edges


def _reach(edges: dict[str, set[str]], entries) -> set[str]:
    seen: set[str] = set()
    stack = [q for q in entries if q in edges]
    while stack:
        q = stack.pop()
        if q in seen:
            continue
        seen.add(q)
        stack.extend(c for c in edges.get(q, ()) if c not in seen)
    return seen


def build_model(index, roots=THREAD_ROOTS) -> ThreadModel:
    """Resolve the declared registry + discover spawn sites, then
    propagate thread identities over the dispatch-extended graph."""
    model = ThreadModel()
    edges = thread_edges(index)
    entries: dict[str, set[str]] = {}  # identity -> entry qnames

    def _tail_qnames(tail: str) -> list[str]:
        suffix = "::" + tail if "." not in tail else "." + tail
        return [
            q for q in index.funcs
            if q.endswith(suffix) or q.endswith("::" + tail)
        ]

    paths = {f.sf.path for f in index.funcs.values()}
    for root in roots:
        if root.path not in paths:
            continue
        fi = _resolve_root(index, root)
        if fi is None:
            continue  # drift is verify_thread_roots's finding, not a crash
        entries.setdefault(root.thread, set()).add(fi.qname)
        if root.concurrent:
            model.concurrent.add(root.thread)
        model.roots.append((root.thread, root.name, fi.qname))
        for tail in root.reaches:
            for q in _tail_qnames(tail):
                entries[root.thread].add(q)

    # discovered spawns: each target is its own identity UNLESS it is
    # already a declared root's entry (declaring `_Feeder.run` as
    # pending-feeder must not ALSO mint a worker identity for the same
    # real thread — a function would then conflict with itself); the
    # spawning function (and everything that reaches it) runs on MAIN
    declared_qnames = {q for ents in entries.values() for q in ents}
    spawners: set[str] = set()
    for q, fi in index.funcs.items():
        for node in shallow_walk(fi.node):
            if isinstance(node, ast.Call) and _is_thread_ctor(node):
                spawners.add(q)
                for tq in _spawn_targets(fi, node):
                    if tq in index.funcs and tq not in declared_qnames:
                        ident = "worker:" + tq.rsplit("::", 1)[-1]
                        entries.setdefault(ident, set()).add(tq)
                        model.roots.append((ident, ident, tq))
    for name, cands in index.classes.items():
        for sf, cls in cands:
            bases = {dotted_name(b) for b in cls.bases}
            if bases & _THREAD_BASES:
                q = f"{sf.path}::{cls.name}.run"
                if q in index.funcs and q not in declared_qnames:
                    ident = f"worker:{cls.name}.run"
                    entries.setdefault(ident, set()).add(q)
                    model.roots.append((ident, ident, q))

    if spawners:
        # reverse closure: whoever transitively calls a spawner runs on
        # the spawner's (main) thread up to that point
        rev: dict[str, set[str]] = {}
        for src, dsts in edges.items():
            for d in dsts:
                rev.setdefault(d, set()).add(src)
        main_entries = _reach(rev, spawners)
        entries.setdefault(MAIN, set()).update(main_entries)

    for ident, ents in entries.items():
        for q in _reach(edges, ents):
            model.threads_of.setdefault(q, set()).add(ident)
    return model


# ---- per-class access + happens-before facts -------------------------------


@dataclass
class Access:
    attr: str
    kind: str            # "w" | "r"
    qname: str           # method qname
    method: str
    line: int
    held: frozenset      # lock attrs lexically held at the site


@dataclass
class MethodHB:
    """Per-method happens-before facts the discharge logic consumes."""

    sets: list = field(default_factory=list)    # (event attr, line)
    waits: list = field(default_factory=list)   # (event attr, line)
    starts: list = field(default_factory=list)  # lineno of any .start()
    joins: list = field(default_factory=list)   # lineno of any .join()


@dataclass
class ClassConcurrency:
    cls_name: str
    path: str
    accesses: dict = field(default_factory=dict)   # attr -> [Access]
    hb: dict = field(default_factory=dict)         # method -> MethodHB
    entry_locksets: dict = field(default_factory=dict)
    safe_attrs: set = field(default_factory=set)
    event_attrs: set = field(default_factory=set)
    methods: dict = field(default_factory=dict)    # method name -> qname


def _self_attr_read(node) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and isinstance(node.ctx, ast.Load)
        # keyed `self.__dict__[...]` forms are resolved to the KEY (they
        # ARE `self.<key>`); the bare dict object itself is not a datum
        and node.attr != "__dict__"
    ):
        return node.attr
    return None


def self_dict_sub(node) -> str | None:
    """'key' for a `self.__dict__["key"]` Subscript — semantically an
    access to `self.key`, and tracked at that granularity (the memoized-
    property idiom must not conflate every cache under one `__dict__`
    attr: two threads touching DIFFERENT keys never conflict)."""
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.value, ast.Attribute)
        and isinstance(node.value.value, ast.Name)
        and node.value.value.id == "self"
        and node.value.attr == "__dict__"
        and isinstance(node.slice, ast.Constant)
        and isinstance(node.slice.value, str)
    ):
        return node.slice.value
    return None


def self_dict_get(node) -> str | None:
    """'key' for a `self.__dict__.get("key", ...)` call (read)."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "get"
        and isinstance(node.func.value, ast.Attribute)
        and isinstance(node.func.value.value, ast.Name)
        and node.func.value.value.id == "self"
        and node.func.value.attr == "__dict__"
        and node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)
    ):
        return node.args[0].value
    return None


def class_concurrency(index, sf, cls: ast.ClassDef) -> ClassConcurrency:
    facts = class_lock_facts(cls)
    cc = ClassConcurrency(cls_name=cls.name, path=sf.path)
    cc.entry_locksets = method_entry_locksets(facts) if facts.locks else {}
    for item in ast.walk(cls):
        if isinstance(item, ast.Assign):
            cn = _ctor_name(item.value)
            if cn in SAFE_CTORS:
                for t in item.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        cc.safe_attrs.add(t.attr)
                        if cn == "Event":
                            cc.event_attrs.add(t.attr)
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        method = item.name
        qname = None
        for q, fi in index.funcs.items():
            if fi.node is item:
                qname = q
                break
        if qname is None:
            qname = f"{sf.path}::{cls.name}.{method}"
        cc.methods[method] = qname
        hb = MethodHB()
        cc.hb[method] = hb

        def walk(node, held):
            for child in ast.iter_child_nodes(node):
                child_held = held
                if isinstance(child, ast.With):
                    acquired = {
                        i.context_expr.attr
                        for i in child.items
                        if (
                            isinstance(i.context_expr, ast.Attribute)
                            and isinstance(i.context_expr.value, ast.Name)
                            and i.context_expr.value.id == "self"
                            and i.context_expr.attr in facts.locks
                        )
                    }
                    if acquired:
                        child_held = held | acquired
                if isinstance(child, ast.Call):
                    dget = self_dict_get(child)
                    if dget is not None:
                        cc.accesses.setdefault(dget, []).append(Access(
                            dget, "r", qname, method, child.lineno,
                            frozenset(child_held),
                        ))
                    fdn = dotted_name(child.func)
                    if fdn and "." in fdn:
                        owner, mname = fdn.rsplit(".", 1)
                        if mname == "start":
                            hb.starts.append(child.lineno)
                        elif mname == "join":
                            hb.joins.append(child.lineno)
                        if owner.startswith("self.") and owner.count(".") == 1:
                            attr = owner.split(".", 1)[1]
                            if (
                                attr in cc.event_attrs
                                or "evt" in attr or "event" in attr
                            ):
                                if mname == "set":
                                    hb.sets.append((attr, child.lineno))
                                elif mname == "wait":
                                    hb.waits.append((attr, child.lineno))
                    # mutator calls on plain (non-hand-off) attrs write
                    if (
                        isinstance(child.func, ast.Attribute)
                        and child.func.attr in _MUTATORS
                    ):
                        owner_node = child.func.value
                        if isinstance(owner_node, ast.Subscript):
                            owner_node = owner_node.value
                        if (
                            isinstance(owner_node, ast.Attribute)
                            and isinstance(owner_node.value, ast.Name)
                            and owner_node.value.id == "self"
                            and owner_node.attr not in cc.safe_attrs
                        ):
                            cc.accesses.setdefault(
                                owner_node.attr, []
                            ).append(Access(
                                owner_node.attr, "w", qname, method,
                                child.lineno, frozenset(child_held),
                            ))
                elif isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (
                        child.targets if isinstance(child, ast.Assign)
                        else [child.target]
                    )
                    for t in targets:
                        dkey = self_dict_sub(t)
                        if dkey is not None:
                            cc.accesses.setdefault(dkey, []).append(
                                Access(
                                    dkey, "w", qname, method,
                                    child.lineno, frozenset(child_held),
                                )
                            )
                            continue
                        base = t
                        if isinstance(base, ast.Subscript):
                            base = base.value
                        if (
                            isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"
                            and base.attr != "__dict__"
                        ):
                            # rebinding even a hand-off attr is a write
                            cc.accesses.setdefault(base.attr, []).append(
                                Access(
                                    base.attr, "w", qname, method,
                                    child.lineno, frozenset(child_held),
                                )
                            )
                dkey = self_dict_sub(child)
                if dkey is not None and isinstance(child.ctx, ast.Load):
                    cc.accesses.setdefault(dkey, []).append(Access(
                        dkey, "r", qname, method, child.lineno,
                        frozenset(child_held),
                    ))
                attr = _self_attr_read(child)
                if attr is not None and attr not in cc.safe_attrs:
                    cc.accesses.setdefault(attr, []).append(Access(
                        attr, "r", qname, method, child.lineno,
                        frozenset(child_held),
                    ))
                if not isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    walk(child, child_held)

        walk(item, frozenset())
    return cc


def guaranteed_locks(cc: ClassConcurrency, acc: Access) -> frozenset:
    """Locks held on EVERY path reaching the site: the lexical set plus
    the intersection of the method's entry locksets (lockset-race's
    fixpoint, reused — a private helper only ever called under the lock
    inherits it without a waiver)."""
    contexts = cc.entry_locksets.get(acc.method)
    if not contexts:
        return acc.held
    inter = None
    for c in contexts:
        inter = set(c) if inter is None else inter & c
    return acc.held | frozenset(inter or ())
